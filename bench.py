"""Benchmark: Llama causal-LM training throughput on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric is model FLOPs utilization (MFU) for a bf16 Llama training step
(fwd+bwd+AdamW) at seq 2048 — the BASELINE.json north-star metric shape
(target >= 0.45 on v5p-128; vs_baseline = mfu / 0.45).
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


PEAK_BF16_FLOPS = {
    # per-chip dense bf16 peak
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v5 lite": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
    "cpu": 1e12,  # nominal, so the script still reports off-TPU
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in PEAK_BF16_FLOPS.items():
        if key in kind:
            return val
    return PEAK_BF16_FLOPS["cpu"]


def run_config(config, batch, seq, dev):
    """Train-step MFU for one model config. Returns (mfu, tok_s, dt, loss)."""
    import jax
    from paddle_tpu.models.llama import (ParallelConfig, build_train_step,
                                         train_flops_per_token)
    on_tpu = dev.platform != "cpu"
    # save_attn: keep flash-attention outputs across the remat boundary
    # (skips recomputing attention in backward; measured +0.004 MFU, and
    # 'dots'/no-remat exceed memory at this shape)
    parallel = ParallelConfig(remat=True, remat_policy="save_attn",
                              use_flash=on_tpu)
    step, params, opt = build_train_step(config, parallel, lr=1e-4)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, config.vocab_size, (batch, seq)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    # warmup (compile) + 2 steps. NOTE: sync via device_get, not
    # block_until_ready — the axon remote-TPU platform returns from
    # block_until_ready before execution finishes, which inflates
    # throughput ~1000x. A host transfer of the loss is a true barrier.
    for _ in range(3):
        params, opt, loss = step(params, opt, ids, labels)
    jax.device_get(loss)

    n_steps = 10 if on_tpu else 2
    t0 = time.perf_counter()
    for _ in range(n_steps):
        params, opt, loss = step(params, opt, ids, labels)
    jax.device_get(loss)
    dt = (time.perf_counter() - t0) / n_steps

    tok_s = batch * seq / dt
    mfu = tok_s * train_flops_per_token(config, seq) / peak_flops(dev)
    del params, opt
    return mfu, tok_s, dt, float(jax.device_get(loss))


HBM_BW = {  # per-chip HBM bandwidth, bytes/s
    "v5e": 819e9, "v5litepod": 819e9, "v5 lite": 819e9,
    "v5p": 2765e9, "v4": 1228e9, "v6e": 1640e9, "cpu": 50e9,
}


def device_time_ms(fn, args, name="timedfn", reps=3):
    """Mean ON-DEVICE time of one jitted call, from profiler trace events.

    Wall-clock through the axon tunnel includes ~5-12 ms of dispatch
    overhead per call and does not pipeline across dispatches, so for
    kernels in the single-digit-ms range it overstates time by up to 10x
    (measured: a 0.72 ms matmul walls at 13.5 ms). The profiler's
    device-side `jit_<name>` spans are the ground truth."""
    import glob
    import gzip
    import tempfile

    import jax

    fn.__name__ = name
    f = jax.jit(fn)
    o = f(*args)
    jax.device_get(jnp_ravel_first(o))
    durs = []
    with tempfile.TemporaryDirectory() as td:
        with jax.profiler.trace(td):
            for _ in range(reps):
                o = f(*args)
            jax.device_get(jnp_ravel_first(o))
        for fpath in glob.glob(td + "/**/*.trace.json.gz", recursive=True):
            with gzip.open(fpath, "rt") as fh:
                tr = json.load(fh)
            for e in tr.get("traceEvents", []):
                if e.get("ph") == "X" and \
                        e.get("name", "").startswith(f"jit_{name}("):
                    durs.append(e["dur"])
    if not durs:  # profiler unavailable (non-TPU backends): fall back
        print(f"WARNING: no device trace events for {name}; falling back "
              "to wall-clock (dispatch-inflated on the tunnel)",
              file=sys.stderr)
        t0 = time.perf_counter()
        for _ in range(reps):
            o = f(*args)
        jax.device_get(jnp_ravel_first(o))
        return (time.perf_counter() - t0) / reps * 1e3
    return sum(durs) / len(durs) / 1e3


def jnp_ravel_first(o):
    import jax.numpy as jnp
    leaf = o[0] if isinstance(o, (tuple, list)) else o
    return jnp.ravel(leaf)[0]


def run_decode(config, batch, dev, prompt_len=128, new_tokens=128):
    """Warm greedy-generation latency: returns (ms_per_step, tok_s,
    floor_ms). The whole continuation is ONE device dispatch (lax.scan), so
    per-step time is on-chip cost, not tunnel round-trips. floor_ms is the
    weight-read bound: decode is HBM-bound, every step streams all params
    once (KV cache traffic is comparatively small at this context)."""
    import jax.numpy as jnp
    from paddle_tpu.models.llama import (count_params, greedy_generate,
                                         init_llama_params)
    params = init_llama_params(config, seed=0)
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, config.vocab_size,
                         (batch, prompt_len)).astype(np.int32)

    def timed(n_new):
        greedy_generate(params, prompt, config, n_new)  # compile
        reps = 3 if dev.platform != "cpu" else 1
        t0 = time.perf_counter()
        for _ in range(reps):
            greedy_generate(params, prompt, config, n_new)
        return (time.perf_counter() - t0) / reps

    # subtract the prefill+first-token pass (max_new_tokens=1 stops there)
    # so ms_per_step is the decode-scan cost the floor applies to
    t_prefill = timed(1)
    dt = timed(new_tokens) - t_prefill
    n_steps = new_tokens - 1
    kind = getattr(dev, "device_kind", "cpu").lower()
    bw = next((v for k, v in HBM_BW.items() if k in kind), HBM_BW["cpu"])
    itemsize = jnp.dtype(config.dtype).itemsize
    bytes_per_step = count_params(config) * itemsize  # weights read per token
    floor_ms = bytes_per_step / bw * 1e3
    del params
    return dt / n_steps * 1e3, batch * n_steps / dt, floor_ms


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.llama import LlamaConfig

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    seq = 2048 if on_tpu else 128
    batch = 4 if on_tpu else 2
    if on_tpu:
        # flagship shape: head_dim=128 (Llama-2's), MXU-sized matmuls
        config = LlamaConfig(vocab_size=32000, hidden_size=2048,
                             intermediate_size=8192, num_hidden_layers=12,
                             num_attention_heads=16, num_key_value_heads=16,
                             max_position_embeddings=seq, dtype=jnp.bfloat16)
        # round-1 shape (head_dim=64), kept for cross-round comparability
        config_hd64 = LlamaConfig(vocab_size=32000, hidden_size=1024,
                                  intermediate_size=4096, num_hidden_layers=24,
                                  num_attention_heads=16,
                                  num_key_value_heads=16,
                                  max_position_embeddings=seq,
                                  dtype=jnp.bfloat16)
    else:
        from paddle_tpu.models.llama import llama_tiny
        config = llama_tiny(seq=seq)
        config_hd64 = None

    mfu, tok_s, dt, loss = run_config(config, batch, seq, dev)
    detail = {
        "tokens_per_sec_per_chip": round(tok_s, 1),
        "step_time_s": round(dt, 4),
        "device": str(getattr(dev, "device_kind", dev.platform)),
        "seq_len": seq, "batch": batch,
        "hidden": config.hidden_size, "layers": config.num_hidden_layers,
        "head_dim": config.head_dim,
        "loss": round(loss, 4),
    }
    if config_hd64 is not None:
        mfu64, tok_s64, dt64, _ = run_config(config_hd64, batch, seq, dev)
        detail["hd64_shape"] = {
            "mfu": round(float(mfu64), 4),
            "tokens_per_sec_per_chip": round(tok_s64, 1),
            "step_time_s": round(dt64, 4),
            "hidden": config_hd64.hidden_size,
            "layers": config_hd64.num_hidden_layers,
            "head_dim": config_hd64.head_dim,
        }

    # KV-cache greedy decode (whole continuation = one dispatch). ms/step is
    # bounded below by streaming all bf16 weights from HBM once per step
    # (weight_floor_ms); tok/s scales with batch at near-constant step time.
    decode = {}
    for name, cfg in [("flagship", config)] + (
            [("hd64", config_hd64)] if config_hd64 is not None else []):
        for b in (1, 8):
            mspt, tok_s_d, floor = run_decode(cfg, b, dev)
            decode[f"{name}_b{b}"] = {
                "ms_per_step": round(mspt, 2),
                "tokens_per_sec": round(tok_s_d, 1),
                "weight_floor_ms": round(floor, 2),
                "x_of_floor": round(mspt / floor, 2),
            }
    detail["decode"] = decode

    if on_tpu:
        # long-context: streaming-KV Pallas kernels (whole-KV residency
        # would exceed VMEM ~6k tokens earlier); causal, head_dim=128.
        # Timed via profiler DEVICE events: wall-clock over the axon tunnel
        # carries ~5-12 ms dispatch overhead per call, which buried these
        # kernels under ~10x noise in the round-2 numbers (0.082 "eff" for
        # a kernel actually running at 0.60).
        import jax as _jax
        from paddle_tpu.ops import flash_attention as _fa
        long_seq = {}
        for s_long in (16384, 32768):
            bh, d_ = 8, 128
            rng2 = np.random.RandomState(1)
            q = jnp.asarray(rng2.randn(bh, s_long, d_).astype(np.float32),
                            dtype=jnp.bfloat16)
            k = jnp.asarray(rng2.randn(bh, s_long, d_).astype(np.float32),
                            dtype=jnp.bfloat16)
            v = jnp.asarray(rng2.randn(bh, s_long, d_).astype(np.float32),
                            dtype=jnp.bfloat16)

            def fwd(q, k, v):
                return _fa._flash_fwd(q, k, v, True, 1 / 11.3, 1024, 1024)[0]

            def bwd(q, k, v):
                # grad w.r.t. ALL of q/k/v: grad-of-q-only would DCE the
                # dK/dV streaming kernel out of the program entirely
                loss = lambda q, k, v: (_fa._flash_attention(
                    q, k, v, True, 1 / 11.3, 1024, 1024)
                    .astype(jnp.float32) ** 2).sum()
                return _jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

            ms_f = device_time_ms(fwd, (q, k, v), f"lsfwd{s_long}")
            ms_b = device_time_ms(bwd, (q, k, v), f"lsbwd{s_long}")
            fl = 2 * 2 * bh * s_long * s_long * d_ / 2  # causal half
            long_seq[f"S{s_long}"] = {
                "ms": round(ms_f, 1),
                "attn_eff": round(fl / (ms_f / 1e3) / peak_flops(dev), 3),
                "bwd_ms": round(ms_b, 1),
                # bwd does ~2.5x the fwd FLOPs (5 matmuls vs 2)
                "bwd_eff": round(2.5 * fl / (ms_b / 1e3) / peak_flops(dev), 3),
            }
        detail["long_seq_flash_fwd"] = long_seq

    print(json.dumps({
        "metric": "llama_train_mfu",
        "value": round(float(mfu), 4),
        "unit": "MFU",
        "vs_baseline": round(float(mfu) / 0.45, 4),
        "detail": detail,
    }))


if __name__ == "__main__":
    main()
