"""Benchmark: Llama causal-LM training throughput on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Metric is model FLOPs utilization (MFU) for a bf16 Llama training step
(fwd+bwd+AdamW) at seq 2048 — the BASELINE.json north-star metric shape
(target >= 0.45 on v5p-128; vs_baseline = mfu / 0.45).
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np


PEAK_BF16_FLOPS = {
    # per-chip dense bf16 peak
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v5 lite": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v6e": 918e12,
    "cpu": 1e12,  # nominal, so the script still reports off-TPU
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in PEAK_BF16_FLOPS.items():
        if key in kind:
            return val
    return PEAK_BF16_FLOPS["cpu"]


def run_config(config, batch, seq, dev, policy="save_mlp"):
    """Train-step MFU for one model config. Returns (mfu, tok_s, dt, loss).

    policy: remat policy. 'save_mlp' (keep flash outputs AND the gate/up
    matmul outputs — half the forward matmul FLOPs — across the remat
    boundary) wins wherever the residuals fit: flagship 0.621 vs 0.612
    (save_attn), 13B-geometry 0.642 vs 0.602, hd64 0.466. The 7B
    geometry (L=4, B=8) cannot hold the extra [B, S, I] residuals and
    keeps 'save_attn'; 'dots'/no-remat exceed memory at all these
    shapes."""
    import jax
    from paddle_tpu.models.llama import (ParallelConfig, build_train_step,
                                         train_flops_per_token)
    on_tpu = dev.platform != "cpu"
    parallel = ParallelConfig(remat=True, remat_policy=policy,
                              use_flash=on_tpu)
    step, params, opt = build_train_step(config, parallel, lr=1e-4)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, config.vocab_size, (batch, seq)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    # warmup (compile) + 2 steps. NOTE: sync via device_get, not
    # block_until_ready — the axon remote-TPU platform returns from
    # block_until_ready before execution finishes, which inflates
    # throughput ~1000x. A host transfer of the loss is a true barrier.
    for _ in range(3):
        params, opt, loss = step(params, opt, ids, labels)
    jax.device_get(loss)

    n_steps = 10 if on_tpu else 2
    trials = 3 if on_tpu else 1
    dt = 1e9
    for _ in range(trials):  # best-of-trials: tunnel jitter is one-sided
        t0 = time.perf_counter()
        for _ in range(n_steps):
            params, opt, loss = step(params, opt, ids, labels)
        jax.device_get(loss)
        dt = min(dt, (time.perf_counter() - t0) / n_steps)

    tok_s = batch * seq / dt
    mfu = tok_s * train_flops_per_token(config, seq) / peak_flops(dev)
    del params, opt
    return mfu, tok_s, dt, float(jax.device_get(loss))


HBM_BW = {  # per-chip HBM bandwidth, bytes/s
    "v5e": 819e9, "v5litepod": 819e9, "v5 lite": 819e9,
    "v5p": 2765e9, "v4": 1228e9, "v6e": 1640e9, "cpu": 50e9,
}


def trace_device_ms(run, span_prefix, reps=3):
    """Run `run()` reps times under the jax profiler and return the mean
    duration (ms) of device spans whose name starts with span_prefix, or
    None if no such span was recorded (e.g. non-TPU backends)."""
    import glob
    import gzip
    import tempfile

    import jax

    durs = []
    with tempfile.TemporaryDirectory() as td:
        with jax.profiler.trace(td):
            for _ in range(reps):
                run()
        for fpath in glob.glob(td + "/**/*.trace.json.gz", recursive=True):
            with gzip.open(fpath, "rt") as fh:
                tr = json.load(fh)
            for e in tr.get("traceEvents", []):
                if e.get("ph") == "X" and \
                        e.get("name", "").startswith(span_prefix):
                    durs.append(e["dur"])
    if not durs:
        return None
    return sum(durs) / len(durs) / 1e3


def device_time_ms(fn, args, name="timedfn", reps=3):
    """Mean ON-DEVICE time of one jitted call, from profiler trace events.

    Wall-clock through the axon tunnel includes ~5-12 ms of dispatch
    overhead per call and does not pipeline across dispatches, so for
    kernels in the single-digit-ms range it overstates time by up to 10x
    (measured: a 0.72 ms matmul walls at 13.5 ms). The profiler's
    device-side `jit_<name>` spans are the ground truth."""
    import jax

    fn.__name__ = name
    f = jax.jit(fn)
    o = f(*args)
    jax.device_get(jnp_ravel_first(o))

    def run():
        o = f(*args)
        jax.device_get(jnp_ravel_first(o))

    ms = trace_device_ms(run, f"jit_{name}(", reps=reps)
    if ms is None:  # profiler unavailable (non-TPU backends): fall back
        print(f"WARNING: no device trace events for {name}; falling back "
              "to wall-clock (dispatch-inflated on the tunnel)",
              file=sys.stderr)
        t0 = time.perf_counter()
        for _ in range(reps):
            o = f(*args)
        jax.device_get(jnp_ravel_first(o))
        return (time.perf_counter() - t0) / reps * 1e3
    return ms


_MEASURED_BW = {}


def measured_hbm_bw(dev):
    """Achievable HBM read bandwidth (bytes/s), measured with a trivial
    streaming reduce over 1 GiB of bf16. The datasheet number (819 GB/s on
    v5e) is not attainable by real kernels, so floors computed against it
    can read x_of_floor < 1.0 — an impossibility. Floors below are
    reported against this measured ceiling instead."""
    kind = getattr(dev, "device_kind", "cpu")
    if kind in _MEASURED_BW:
        return _MEASURED_BW[kind]
    import jax
    import jax.numpy as jnp
    n = 1 << 29  # 512Mi bf16 elements = 1 GiB
    big = jax.jit(lambda k: (jax.random.uniform(k, (n,), jnp.float32) - 0.5)
                  .astype(jnp.bfloat16))(jax.random.PRNGKey(0))
    jax.device_get(big.ravel()[0])
    ms = device_time_ms(lambda x: jnp.sum(x.astype(jnp.float32)), (big,),
                        "hbmread")
    del big
    bw = (n * 2) / (ms / 1e3)
    _MEASURED_BW[kind] = bw
    return bw


def jnp_ravel_first(o):
    import jax.numpy as jnp
    leaf = o[0] if isinstance(o, (tuple, list)) else o
    return jnp.ravel(leaf)[0]


def run_decode(config, batch, dev, prompt_len=128, new_tokens=128,
               quantize=False):
    """Warm greedy-generation decode cost. Returns
    (ms_per_step, tok_s, floor_ms, measured_floor_ms).

    ms_per_step comes from the profiler's device span of the decode scan
    (jit_generate_scan) alone — the prefill executable is a separate span,
    so no wall-clock subtraction (which previously produced x_of_floor
    readings < 1.0, a physical impossibility). floor_ms is the weight-read
    bound against the DATASHEET bandwidth; measured_floor_ms against the
    achievable bandwidth from measured_hbm_bw — decode is HBM-bound, every
    step streams all params once (KV-cache traffic is comparatively small
    at this context length). quantize=True runs weight-only int8 (halved
    weight stream; floors computed against the int8 bytes)."""
    import jax.numpy as jnp
    from paddle_tpu.models.llama import (count_params, generate_scan_bucket,
                                         greedy_generate, init_llama_params,
                                         quantize_llama_int8)
    params = init_llama_params(config, seed=0)
    if quantize:
        params = quantize_llama_int8(params)
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, config.vocab_size,
                         (batch, prompt_len)).astype(np.int32)

    greedy_generate(params, prompt, config, new_tokens)  # compile
    n_steps = generate_scan_bucket(new_tokens)
    scan_ms = trace_device_ms(
        lambda: greedy_generate(params, prompt, config, new_tokens),
        "jit_generate_scan(", reps=3)
    if scan_ms is None:  # off-TPU: wall-clock with prefill subtraction
        if dev.platform != "cpu":
            print("WARNING: no jit_generate_scan device span; decode "
                  "timing falling back to dispatch-inflated wall-clock",
                  file=sys.stderr)

        def timed(n_new):
            greedy_generate(params, prompt, config, n_new)
            t0 = time.perf_counter()
            greedy_generate(params, prompt, config, n_new)
            return time.perf_counter() - t0
        # best-of-3 each term, clamped: single-shot jitter can make the
        # difference negative (ADVICE r3)
        full = min(timed(new_tokens) for _ in range(3))
        one = min(timed(1) for _ in range(3))
        scan_ms = max((full - one) * 1e3, 1e-3)
    mspt = scan_ms / n_steps

    kind = getattr(dev, "device_kind", "cpu").lower()
    bw = next((v for k, v in HBM_BW.items() if k in kind), HBM_BW["cpu"])
    itemsize = 1 if quantize else jnp.dtype(config.dtype).itemsize
    streamed = count_params(config)
    if not config.tie_word_embeddings:
        # the INPUT embedding table is read via a b-row gather per step,
        # not streamed; only the separate lm_head streams. (Tied: the
        # table IS the head and streams once.)
        streamed -= config.vocab_size * config.hidden_size
    bytes_per_step = streamed * itemsize  # weights read per token
    # the KV cache is ALSO read once per step (the decode scan reads the
    # full static-shape cache extent every layer): at batch>1 this is the
    # dominant batch-dependent term, and a floor that ignores it calls
    # honest cache traffic "overhead". Cache stays bf16 under weight-only
    # int8 quantization.
    c = config
    cache_len = prompt_len + new_tokens
    kv_bytes = (2 * c.num_hidden_layers * batch * cache_len
                * c.num_key_value_heads * c.head_dim
                * jnp.dtype(c.dtype).itemsize)
    bytes_per_step += kv_bytes
    floor_ms = bytes_per_step / bw * 1e3
    mbw = measured_hbm_bw(dev) if dev.platform != "cpu" else bw
    measured_floor_ms = bytes_per_step / mbw * 1e3
    del params
    return mspt, batch / (mspt / 1e3), floor_ms, measured_floor_ms


def bench_moe(dev):
    """Config-ladder #5 timed on one chip: ERNIE-MoE (slot-schedule
    top-2 dispatch, r5) train step. Reports ACTIVE-parameter MFU — the
    capacity factor (1.25) pads expert buckets beyond the routed tokens,
    so computed utilization is cf x higher than active, and the f32
    AdamW moments stream for ALL expert params though only top-k are
    active per token. Single chip has no all-to-all (ep=1); the ep=2
    all-to-all share is recorded by the driver dryrun's timing line."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.ernie_moe import ErnieMoEConfig, build_train_step
    cfg = ErnieMoEConfig(vocab_size=8192, hidden_size=1024,
                         intermediate_size=4096, num_hidden_layers=8,
                         num_attention_heads=8, num_experts=8, moe_topk=2,
                         capacity_factor=1.25, moe_every=2,
                         max_position_embeddings=512, dtype=jnp.bfloat16)
    B, S = 8, 512
    step, p, o = build_train_step(cfg, ep_degree=1, lr=1e-4)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int32)
    import jax as _jax
    for _ in range(3):
        p, o, loss, _lm = step(p, o, ids, labels)
    _jax.device_get(loss)
    # DEVICE-span timing (the bench's standard for sub-100ms dispatches:
    # the axon tunnel adds ~5-12 ms of host dispatch per call, which at
    # this step size would be a ~13% fiction; the flagship 300-800 ms
    # steps absorb it). Falls back to wall-clock off-TPU.
    state = {"p": p, "o": o}

    def run():
        state["p"], state["o"], loss, _lm = step(state["p"], state["o"],
                                                 ids, labels)
        _jax.device_get(loss)

    ms = trace_device_ms(run, "jit_step(", reps=5)
    if ms is not None:
        dt = ms / 1e3
    else:
        n, trials, dt = 10, 3, 1e9
        for _ in range(trials):
            t0 = time.perf_counter()
            for _ in range(n):
                p, o, loss, _lm = step(p, o, ids, labels)
            _jax.device_get(loss)
            dt = min(dt, (time.perf_counter() - t0) / n)
    p, o = state.get("p", p), state.get("o", o)
    tok_s = B * S / dt
    c = cfg
    n_dense = sum(1 for i in range(c.num_hidden_layers)
                  if (i % c.moe_every) != (c.moe_every - 1))
    n_moe = c.num_hidden_layers - n_dense
    ffn = 2 * c.hidden_size * c.intermediate_size
    active = (c.vocab_size * c.hidden_size
              + c.num_hidden_layers * 4 * c.hidden_size ** 2
              + n_dense * ffn
              + n_moe * (c.moe_topk * ffn + c.hidden_size * c.num_experts))
    fpt = 6.0 * active + 12 * c.num_hidden_layers * c.hidden_size * S
    del p, o
    return {
        "active_mfu": round(tok_s * fpt / peak_flops(dev), 4),
        "tokens_per_sec_per_chip": round(tok_s, 1),
        "step_time_s": round(dt, 4),
        "experts": c.num_experts, "topk": c.moe_topk,
        "capacity_factor": c.capacity_factor,
        "dominant_cost": "expert-FFN matmuls on cf x1.25-padded capacity "
                         "buckets + f32 AdamW moment streaming for the "
                         "full (not active) expert params; dispatch/"
                         "combine are row gathers with gather-only vjps "
                         "(r5 slot schedule — the r4 one-hot einsums are "
                         "gone; no all-to-all at ep=1, see MULTICHIP ep2 "
                         "timing line for the virtual-mesh a2a share)",
    }


def bench_moe_dropless(dev):
    """The dropless counterpart of bench_moe on the SAME config: ragged
    grouped-GEMM expert compute (dispatch_mode='ragged', no capacity
    buckets, zero drops) with param-dtype optimizer moments
    (multi_precision=False) so the bf16 expert moments stream at half
    the bytes. Reports active-parameter MFU plus the pad-waste stats
    that replace the capacity factor: tile-alignment padding is bounded
    by one MXU row tile per expert, vs cf=1.25's unconditional 25%."""
    import jax as _jax
    import jax.numpy as jnp
    from paddle_tpu.models.ernie_moe import ErnieMoEConfig, build_train_step
    cfg = ErnieMoEConfig(vocab_size=8192, hidden_size=1024,
                         intermediate_size=4096, num_hidden_layers=8,
                         num_attention_heads=8, num_experts=8, moe_topk=2,
                         capacity_factor=1.25, moe_every=2,
                         max_position_embeddings=512, dtype=jnp.bfloat16)
    B, S = 8, 512
    step, p, o = build_train_step(cfg, ep_degree=1, lr=1e-4,
                                  dispatch_mode="ragged",
                                  multi_precision=False, with_stats=True)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int32)
    for _ in range(3):
        p, o, loss, aux = step(p, o, ids, labels)
    _jax.device_get(loss)
    state = {"p": p, "o": o}

    def run():
        state["p"], state["o"], loss, aux = step(state["p"], state["o"],
                                                 ids, labels)
        _jax.device_get(loss)

    ms = trace_device_ms(run, "jit_step(", reps=5)
    if ms is not None:
        dt = ms / 1e3
    else:
        n, trials, dt = 10, 3, 1e9
        for _ in range(trials):
            t0 = time.perf_counter()
            for _ in range(n):
                p, o, loss, aux = step(p, o, ids, labels)
            _jax.device_get(loss)
            dt = min(dt, (time.perf_counter() - t0) / n)
    p, o = state["p"], state["o"]
    p, o, loss, aux = step(p, o, ids, labels)
    st = _jax.device_get(aux)
    live = float(st["moe_live_rows"])
    padded = float(st["moe_padded_rows"])
    tok_s = B * S / dt
    c = cfg
    n_dense = sum(1 for i in range(c.num_hidden_layers)
                  if (i % c.moe_every) != (c.moe_every - 1))
    n_moe = c.num_hidden_layers - n_dense
    ffn = 2 * c.hidden_size * c.intermediate_size
    active = (c.vocab_size * c.hidden_size
              + c.num_hidden_layers * 4 * c.hidden_size ** 2
              + n_dense * ffn
              + n_moe * (c.moe_topk * ffn + c.hidden_size * c.num_experts))
    fpt = 6.0 * active + 12 * c.num_hidden_layers * c.hidden_size * S
    del p, o
    return {
        "active_mfu": round(tok_s * fpt / peak_flops(dev), 4),
        "tokens_per_sec_per_chip": round(tok_s, 1),
        "step_time_s": round(dt, 4),
        "experts": c.num_experts, "topk": c.moe_topk,
        "dispatch_mode": "ragged",
        "multi_precision": False,
        "moe_dropped_tokens": float(st["moe_dropped_tokens"]),
        "moe_routed_tokens": float(st["moe_routed_tokens"]),
        # pad-waste: dead rows the ragged schedule computes (tile
        # alignment only; <= one row tile per expert per MoE layer) as a
        # fraction of the expert-buffer rows — the number that replaces
        # the capacity path's unconditional cf-1 = 25% bucket padding
        "pad_rows_per_step": padded,
        "pad_waste_frac": round(padded / max(live + padded, 1.0), 4),
        "expert_rows_per_layer_mean": [
            round(float(x) / max(n_moe, 1), 1)
            for x in np.asarray(st["moe_expert_rows"])],
        "dominant_cost": "ragged grouped-GEMM expert FFNs over the "
                         "expert-sorted token buffer (gmm fwd + dX/dW on "
                         "one flat row-tile schedule); zero drops, pad "
                         "bounded by one 128-row tile per expert; bf16 "
                         "AdamW moments (multi_precision=False) halve "
                         "optimizer streaming vs the capacity rung",
    }


def bench_moe_skew(dev):
    """PR 10 rung: skew-proof expert parallelism on the FINE-GRAINED
    ERNIE-MoE preset (E=32, top-4, one shared expert — ernie_moe_fine).

    Three records in one rung:
    - active-parameter MFU of the production MoE step (ragged dispatch,
      active-only AdamW moments, param-dtype moment storage) — the
      headline moe_active_mfu tracks the best MoE configuration, which
      after this PR is this one;
    - ANALYTIC wire bytes of the ragged a2a vs the dense capacity a2a
      under uniform / zipf / point-mass routing, measured from the
      actual top-k routing of sampled gate logits at ep=4: the ragged
      transport ships only routed rows, the dense one always ships the
      full cf-padded capacity buffers;
    - overlap fraction (non-final a2a hops the schedule lets the expert
      FFN start under) from TRACE-TIME counters of an ep=2 island
      lowering with the overlap schedule on; null when <2 devices.
    """
    import jax as _jax
    import jax.numpy as jnp
    from paddle_tpu.models.ernie_moe import build_train_step, ernie_moe_fine
    from paddle_tpu.parallel.moe import moe_capacity
    cfg = ernie_moe_fine()
    B, S = 8, 512
    step, p, o = build_train_step(cfg, ep_degree=1, lr=1e-4,
                                  dispatch_mode="ragged_a2a",
                                  multi_precision=False, with_stats=True,
                                  active_only_moments=True)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int32)
    for _ in range(3):
        p, o, loss, aux = step(p, o, ids, labels)
    _jax.device_get(loss)
    state = {"p": p, "o": o}

    def run():
        state["p"], state["o"], loss, aux = step(state["p"], state["o"],
                                                 ids, labels)
        _jax.device_get(loss)

    ms = trace_device_ms(run, "jit_step(", reps=5)
    # the profiler reps donated the local p/o into state: rebind before
    # the wall-clock fallback touches them again
    p, o = state["p"], state["o"]
    if ms is not None:
        dt = ms / 1e3
    else:
        n, trials, dt = 10, 3, 1e9
        for _ in range(trials):
            t0 = time.perf_counter()
            for _ in range(n):
                p, o, loss, aux = step(p, o, ids, labels)
            _jax.device_get(loss)
            dt = min(dt, (time.perf_counter() - t0) / n)
    del state, p, o
    tok_s = B * S / dt
    c = cfg
    n_dense = sum(1 for i in range(c.num_hidden_layers)
                  if (i % c.moe_every) != (c.moe_every - 1))
    n_moe = c.num_hidden_layers - n_dense
    ffn = 2 * c.hidden_size * c.intermediate_size
    shared_ffn = 2 * c.hidden_size * (c.num_shared_experts
                                      * c.intermediate_size)
    active = (c.vocab_size * c.hidden_size
              + c.num_hidden_layers * 4 * c.hidden_size ** 2
              + n_dense * ffn
              + n_moe * (c.moe_topk * ffn + shared_ffn
                         + c.hidden_size * c.num_experts))
    fpt = 6.0 * active + 12 * c.num_hidden_layers * c.hidden_size * S

    # -- analytic wire-byte sweep at ep=4 ---------------------------------
    E, k, H = c.num_experts, c.moe_topk, c.hidden_size
    ep = 4
    e_local = E // ep
    T_shard = B * S // ep
    dtype_bytes = 2  # bf16 rows on the wire
    cap, _ref = moe_capacity(T_shard, k, E, c.capacity_factor)
    # the dense capacity a2a ships every REMOTE expert's full capacity
    # bucket regardless of routing — per rank, per MoE layer
    dense_bytes = (E - e_local) * cap * H * dtype_bytes
    sweep = {}
    for name in ("uniform", "zipf", "point_mass"):
        logits = rng.randn(ep * T_shard, E).astype(np.float32)
        if name == "zipf":
            logits -= 3.0 * np.log(np.arange(E) + 1.0)[None, :]
        elif name == "point_mass":
            logits[:, 0] += 20.0
            logits[:, 1] += 19.0
        topk = np.argsort(-logits, axis=-1)[:, :k]          # [T, k]
        src = np.repeat(np.arange(ep), T_shard)             # token -> rank
        dest = topk // e_local                              # [T, k]
        wire_rows = int((dest != src[:, None]).sum())
        wire_bytes = wire_rows * H * dtype_bytes / ep       # per rank
        sweep[name] = {
            "wire_rows": wire_rows,
            "ragged_wire_bytes_per_rank": int(wire_bytes),
            "dense_capacity_bytes_per_rank": int(dense_bytes),
            "wire_vs_dense_ratio": round(wire_bytes / dense_bytes, 4),
        }

    # -- overlap fraction from a trace of the ep=2 island -----------------
    overlap_frac = None
    devs = _jax.devices()
    if len(devs) >= 2:
        from jax.sharding import Mesh, PartitionSpec as P
        from jax.experimental.shard_map import shard_map
        from paddle_tpu import observability as obs
        from paddle_tpu.parallel.moe import moe_ragged_dispatch_a2a
        mesh = Mesh(np.array(devs[:2]), ("ep",))

        def island(xs, ls, w1s, w2s):
            out, aux = moe_ragged_dispatch_a2a(
                xs, ls, w1s, w2s, E, axis_name="ep", k=k, overlap=True)
            return out

        f = shard_map(island, mesh=mesh,
                      in_specs=(P("ep"), P("ep"), P("ep"), P("ep")),
                      out_specs=P("ep"), check_rep=False)
        obs.reset_counters()
        try:
            # counters are trace-time: lowering alone records the hop
            # schedule, no device step needed
            _jax.jit(f).lower(
                jnp.zeros((128, H), jnp.bfloat16),
                jnp.zeros((128, E), jnp.float32),
                jnp.zeros((E, H, c.intermediate_size), jnp.bfloat16),
                jnp.zeros((E, c.intermediate_size, H), jnp.bfloat16))
            cnt = obs.counters()
            tot = cnt.get("moe.a2a.hops_total", 0.0)
            overlap_frac = (round(cnt.get("moe.a2a.hops_overlapped", 0.0)
                                  / tot, 4) if tot else None)
        finally:
            obs.reset_counters()

    return {
        "active_mfu": round(tok_s * fpt / peak_flops(dev), 4),
        "tokens_per_sec_per_chip": round(tok_s, 1),
        "step_time_s": round(dt, 4),
        "experts": E, "topk": k,
        "num_shared_experts": c.num_shared_experts,
        "dispatch_mode": "ragged_a2a",
        "multi_precision": False,
        "active_only_moments": True,
        "sweep_ep": ep,
        "sweep": sweep,
        "overlap_fraction": overlap_frac,
        "dominant_cost": "fine-grained expert FFNs (E=32 top-4, I=512) "
                         "on the flat grouped-GEMM schedule plus one "
                         "shared-expert dense FFN; a2a wire cost scales "
                         "with ROUTED rows (see sweep) instead of the "
                         "dense path's cf-padded capacity buckets; AdamW "
                         "moments stream only for experts that routed "
                         "tokens this step (active-only masking)",
    }


def decode_pair_stack_ab(dev, config_hd64):
    """hd64_b8 floor-gap attempt (ISSUE satellite): A/B the standalone
    slab decode kernel with PADDLE_TPU_DECODE_HD64_STACK on/off. The
    pair-stacked variant packs two head_dim-64 heads per 128-lane tile:
    NH/2 fewer padded MXU FLOPs and an NH/2 thinner per-lane window, so
    the fitter keeps the full 512-lane T tile where the wide slab drops
    to fragmented 128-lane DMAs. Recorded either way; the baseline block
    choice stays the default unless the env flag asks for the stack."""
    import os

    import jax.numpy as jnp
    from paddle_tpu._compat import enable_x64
    from paddle_tpu.ops.decode_attention import decode_attention_slab
    c = config_hd64
    B, NH, HD = 8, c.num_attention_heads, c.head_dim
    KVD = NH * HD
    L, T, pos = 2, 4096, 4095
    it = jnp.dtype(c.dtype).itemsize
    rng = np.random.RandomState(9)
    q = np.zeros((B, NH, KVD), np.float32)
    for h in range(NH):   # head-block-diagonal, as the slab caller builds
        q[:, h, h * HD:(h + 1) * HD] = rng.randn(B, HD) * 0.1
    qs = jnp.asarray(q, c.dtype)
    kc = jnp.asarray(rng.randn(L, B, KVD, T), c.dtype)
    vc = jnp.asarray(rng.randn(L, B, KVD, T), c.dtype)
    res = {"batch": B, "num_heads": NH, "head_dim": HD, "cache_T": T}
    key = "PADDLE_TPU_DECODE_HD64_STACK"
    prev = os.environ.get(key)
    try:
        for name, flag in (("baseline_ms", "0"), ("pair_stack_ms", "1")):
            os.environ[key] = flag
            # x64 off for the whole jit trace+lower: the package enables
            # x64 globally, but under jit the pallas index maps lower
            # OUTSIDE the kernel's own mosaic_trace_ctx and 64-bit index
            # constants leak in (eager calls lower inside the ctx)
            with enable_x64(False):
                ms = device_time_ms(
                    lambda q, k, v: decode_attention_slab(q, k, v, 1, pos),
                    (qs, kc, vc), f"hd64slab{flag}")
            res[name] = round(ms, 3)
    finally:
        if prev is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = prev
    res["pair_stack_speedup"] = round(
        res["baseline_ms"] / max(res["pair_stack_ms"], 1e-9), 3)
    # the floor for this kernel is streaming one layer's k+v cache once
    bw = next((v for k_, v in HBM_BW.items()
               if k_ in getattr(dev, "device_kind", "cpu").lower()),
              HBM_BW["cpu"])
    res["cache_stream_floor_ms"] = round(2 * B * KVD * T * it / bw * 1e3, 3)
    return res


def decode_block_sweep(dev, config_hd64):
    """hd64 floor-gap satellite: sweep PADDLE_TPU_DECODE_BLOCK_T over the
    fused attend+update slab kernel at the hd64_b8 shape — the kernel
    family _fit_block_t serves (the r5 1.36x-of-floor reading). The
    override forces each tile size; the kernel-level x_of_floor is
    against streaming one layer's k+v cache once. The winner's tile is
    what the fitter default should produce with the 6-window accounting
    for the update path."""
    import os

    import jax.numpy as jnp
    from paddle_tpu.ops.decode_attention import decode_attend_update_slab
    c = config_hd64
    B, NH, HD = 8, c.num_attention_heads, c.head_dim
    KVD = NH * HD
    L, T, pos = 2, 4096, 4000
    it = jnp.dtype(c.dtype).itemsize
    rng = np.random.RandomState(10)
    q = np.zeros((B, NH, KVD), np.float32)
    for h in range(NH):
        q[:, h, h * HD:(h + 1) * HD] = rng.randn(B, HD) * 0.1
    qs = jnp.asarray(q, c.dtype)
    nk = jnp.asarray(rng.randn(B, KVD), c.dtype)
    nv = jnp.asarray(rng.randn(B, KVD), c.dtype)
    kc = jnp.asarray(rng.randn(L, B, KVD, T), c.dtype)
    vc = jnp.asarray(rng.randn(L, B, KVD, T), c.dtype)
    bw = next((v for k_, v in HBM_BW.items()
               if k_ in getattr(dev, "device_kind", "cpu").lower()),
              HBM_BW["cpu"])
    floor_ms = 2 * B * KVD * T * it / bw * 1e3
    key = "PADDLE_TPU_DECODE_BLOCK_T"
    prev = os.environ.get(key)
    res = {"batch": B, "head_dim": HD, "cache_T": T,
           "cache_stream_floor_ms": round(floor_ms, 3)}
    try:
        for tag in ("fitted", "128", "256", "512"):
            if tag == "fitted":
                os.environ.pop(key, None)
            else:
                os.environ[key] = tag
            ms = device_time_ms(
                lambda q, nk, nv, k, v: decode_attend_update_slab(
                    q, nk, nv, k, v, 1, pos),
                (qs, nk, nv, kc, vc), f"updslab{tag}")
            res[f"block_{tag}"] = {
                "ms": round(ms, 3),
                "x_of_floor": round(ms / max(floor_ms, 1e-9), 3)}
    finally:
        if prev is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = prev
    best = min((k for k in res if k.startswith("block_")),
               key=lambda k: res[k]["ms"])
    res["best"] = best
    return res


def bench_step_ledger(dev, config, batch, seq, step_time_s,
                      use_flash=True):
    """Measured-mode roofline ledger for the train step (measurement only
    — no behavior change): each component from
    observability.flagship_component_specs timed in isolation at the
    step's real shapes (device spans on TPU, wall-clock fallback
    elsewhere) and fed to RooflineLedger with its analytic FLOPs/bytes,
    so every line carries a compute-/memory-bound classification and an
    achieved-vs-roofline fraction. The explicit 'unattributed' remainder
    is what the components don't cover — remat recompute, elementwise
    glue, layout changes, scheduling gaps. Collectives are 0.0 on one
    chip by construction."""
    from paddle_tpu.observability.ledger import (RooflineLedger,
                                                 flagship_component_specs)
    led = RooflineLedger(name="flagship_step", device=dev)
    specs = flagship_component_specs(config, batch, seq,
                                     use_flash=use_flash)
    for i, spec in enumerate(specs):
        fn, args = spec["build"]()
        ms = device_time_ms(fn, args, f"ldg{i}")
        led.add(spec["name"], flops=spec["mult"] * spec["flops"],
                bytes_accessed=spec["mult"] * spec["bytes_accessed"],
                transcendentals=spec["mult"] * spec["transcendentals"],
                time_ms=spec["mult"] * ms, calls=spec["mult"])
    led.add("collectives", time_ms=0.0, calls=0)
    step_ms = step_time_s * 1e3
    rep = led.report(step_ms)
    comps = {}
    for ln in rep["lines"]:
        comps[ln["name"]] = {
            "ms": round(ln["attributed_ms"], 3),
            "frac": (round(ln["frac_of_step"], 4)
                     if ln["frac_of_step"] is not None else None),
            "bound": ln["bound"],
            "roofline_frac": (round(ln["achieved_frac"], 3)
                              if ln["achieved_frac"] is not None else None),
        }
    return {
        "step_ms": round(step_ms, 3),
        "peak_source": rep["peak_source"],
        "bw_source": rep["bw_source"],
        "attributed_ms": round(rep["attributed_ms"], 3),
        "unattributed_ms": round(rep["unattributed_ms"], 3),
        "unattributed_frac": round(rep["unattributed_frac"], 4),
        "components": comps,
        "note": ("components timed in isolation at step shapes; "
                 "'unattributed' is the residual (remat recompute, "
                 "elementwise glue, layout changes); collectives are "
                 "zero on a single chip"),
    }


def bench_ledger_roofline(dev, config, on_tpu):
    """PR 17 rung: roofline-ledger cost and parity. The same training run
    twice from identical seeds — bare, then with the always-on model-mode
    RooflineLedger fed exactly as TrainStep feeds it (kernel-cost window
    delta over the compile trace, on_step per step) — gated on (a)
    bitwise-identical loss sequences (the ledger only ever sees host
    floats and trace-time cost constants) and (b) attributed ledger
    overhead — time inside ledger calls via the overlap_bench timing
    proxy — under 2% of the monitored run's wall. The headline
    ``unattributed_frac`` comes from the measured-mode component ledger
    at the same shapes (model-mode roofline times are optimistic floors,
    so its remainder is an upper bound, not the attribution metric)."""
    import jax
    from benchmarks.overlap_bench import _TimedProxy
    from paddle_tpu.models.llama import ParallelConfig, build_train_step
    from paddle_tpu.observability.ledger import RooflineLedger
    from paddle_tpu.ops import _common as _opsc

    parallel = ParallelConfig(remat=True, use_flash=on_tpu)
    rng = np.random.RandomState(6)
    n_steps, batch, seq = (20, 4, 512) if on_tpu else (8, 2, 64)
    ids = rng.randint(0, config.vocab_size, (batch, seq)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    def run(ledger):
        step, params, opt = build_train_step(config, parallel, lr=1e-4)
        snap = _opsc.snapshot_kernel_costs()
        for _ in range(2):  # compile + settle outside the timed window
            params, opt, loss = step(params, opt, ids, labels)
        if ledger is not None:
            # the compile trace fired every pallas cost_estimate site:
            # the window delta IS this program's per-kernel cost
            ledger.ingest(_opsc.kernel_costs_since(snap))
        jax.device_get(loss)
        losses = []
        t0 = time.perf_counter()
        last = t0
        for _ in range(n_steps):
            params, opt, loss = step(params, opt, ids, labels)
            # per-step host sync in BOTH runs so the bare and ledgered
            # loops execute the identical schedule
            losses.append(float(jax.device_get(loss)))
            now = time.perf_counter()
            if ledger is not None:
                ledger.on_step(now - last)
            last = now
        return losses, time.perf_counter() - t0

    losses_off, wall_off = run(None)
    counter = [0.0]
    led = RooflineLedger(name="bench_train_step", device=dev)
    losses_on, wall_on = run(_TimedProxy(led, counter))
    overhead_pct = counter[0] / wall_on * 100.0
    model_rep = led.report()
    measured = bench_step_ledger(dev, config, batch, seq,
                                 wall_off / n_steps, use_flash=on_tpu)
    out = {
        "steps": n_steps,
        "ledger_losses_identical": losses_on == losses_off,
        "ledger_overhead_pct": round(overhead_pct, 3),
        "model_mode_lines": len([ln for ln in model_rep["lines"]
                                 if ln["name"] != "unattributed"]),
        "model_mode_unattributed_frac": (
            round(model_rep["unattributed_frac"], 4)
            if model_rep["unattributed_frac"] is not None else None),
        "unattributed_frac": measured["unattributed_frac"],
        "measured": measured,
    }
    assert out["ledger_losses_identical"], (losses_off, losses_on)
    assert overhead_pct < 2.0, \
        f"roofline ledger attributed overhead {overhead_pct:.2f}% >= 2%"
    assert out["model_mode_lines"] >= 1, \
        "model-mode ledger ingested no kernel cost lines"
    if not on_tpu:
        out["note"] = ("tiny config on CPU — functional rung; the "
                       "overhead gate is attributed (proxy-timed), and "
                       "measured-mode component times are wall-clock "
                       "fallbacks")
    return out


def varlen_ceiling_ablation(dev, dense_fwd_ms, dense_bwd_ms, S=16384):
    """Varlen-efficiency ceiling satellite: run ONE S-token sequence
    (cu=[0, S] — layout identical to dense) through the varlen
    flat-schedule kernels and compare against the dense flash numbers at
    the same shape. The one-seq eff IS the kernel's ceiling: the gap
    from dense flash is pure flat-schedule overhead (scalar-prefetched
    tile walk, per-tile boundary masks), and the remaining gap of the
    16-seq pack to THIS ceiling is the packing tax (ragged tails,
    per-seq softmax resets) — not schedule waste. S defaults to the
    on-TPU 16384; off-TPU callers pass a small S so interpret mode can
    afford the quadratic walk."""
    import jax as _jax
    import jax.numpy as jnp
    from paddle_tpu.ops.flash_varlen import (flash_varlen_attention,
                                             varlen_schedule_stats)
    cu = jnp.asarray([0, S], jnp.int32)
    rng = np.random.RandomState(6)
    mk = lambda: jnp.asarray(rng.randn(S, 8, 128).astype(np.float32),
                             jnp.bfloat16)
    qv, kv, vv = mk(), mk(), mk()

    def fwd(q, k, v):
        return flash_varlen_attention(q, k, v, cu, cu, 1 / 11.3, True,
                                      self_attn=True, max_seqlen=S)

    def bwd(q, k, v):
        loss = lambda *a: (flash_varlen_attention(
            *a, cu, cu, 1 / 11.3, True, self_attn=True,
            max_seqlen=S).astype(jnp.float32) ** 2).sum()
        return _jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    ms_f = device_time_ms(fwd, (qv, kv, vv), "vlceilf")
    ms_b = device_time_ms(bwd, (qv, kv, vv), "vlceilb")
    fl = 2 * 2 * 8 * S * S * 128 / 2
    pk = peak_flops(dev)
    out = {
        "oneseq_fwd_ms": round(ms_f, 2), "oneseq_bwd_ms": round(ms_b, 2),
        "dense_flash_fwd_ms": round(dense_fwd_ms, 2),
        "dense_flash_bwd_ms": round(dense_bwd_ms, 2),
        "varlen_fwd_eff_ceiling": round(fl / (ms_f / 1e3) / pk, 3),
        "varlen_bwd_eff_ceiling": round(2.5 * fl / (ms_b / 1e3) / pk, 3),
        "schedule_overhead_fwd": round(max(ms_f / dense_fwd_ms - 1, 0), 3),
        "schedule_overhead_bwd": round(max(ms_b / dense_bwd_ms - 1, 0), 3),
        "schedule": varlen_schedule_stats(
            np.asarray(cu), np.asarray(cu), 8, 128, causal=True,
            self_attn=True, dtype=jnp.bfloat16, max_seqlen=S),
    }
    return out


def bench_fleet_observability(dev, config, on_tpu):
    """PR 15 rung: FleetMonitor cost and parity. The same training run
    twice from identical seeds — bare, then with every step feeding a
    FleetMonitor (interval reporting: site counter deltas, all-device
    memory, one fleet_health JSONL record each) — gated on (a) bitwise-
    identical loss sequences (the monitor only ever SEES host floats the
    loop already had, it cannot perturb the computation) and (b)
    attributed monitor overhead — time inside FleetMonitor calls via the
    overlap_bench timing proxy — under 2% of the monitored run's wall."""
    import jax
    from benchmarks.overlap_bench import _TimedProxy
    from paddle_tpu.models.llama import ParallelConfig, build_train_step
    from paddle_tpu.observability import fleet as fleet_mod
    from paddle_tpu.observability.fleet import FleetMonitor

    parallel = ParallelConfig(remat=True, use_flash=on_tpu)
    rng = np.random.RandomState(5)
    n_steps, batch, seq = (20, 4, 512) if on_tpu else (8, 2, 64)
    ids = rng.randint(0, config.vocab_size, (batch, seq)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    def run(monitor):
        step, params, opt = build_train_step(config, parallel, lr=1e-4)
        for _ in range(2):  # compile + settle outside the timed window
            params, opt, loss = step(params, opt, ids, labels)
        jax.device_get(loss)
        losses = []
        t0 = time.perf_counter()
        last = t0
        for _ in range(n_steps):
            params, opt, loss = step(params, opt, ids, labels)
            # per-step host sync in BOTH runs so the monitored and bare
            # loops execute the identical schedule (and the loss is a
            # host float by the time the monitor sees it)
            losses.append(float(jax.device_get(loss)))
            now = time.perf_counter()
            if monitor is not None:
                monitor.on_step(now - last, loss=losses[-1])
            last = now
        return losses, time.perf_counter() - t0

    losses_off, wall_off = run(None)
    path = os.path.join(
        tempfile.mkdtemp(prefix="paddle_tpu_fleet_bench_"),
        "fleet_health.jsonl")
    counter = [0.0]
    mon = FleetMonitor(rank=0, world=1, interval=4, out_path=path)
    losses_on, wall_on = run(_TimedProxy(mon, counter))
    n_reports, problems = fleet_mod.check_file(path)
    overhead_pct = counter[0] / wall_on * 100.0
    last_report = mon.reports[-1] if mon.reports else {}
    out = {
        "steps": n_steps,
        "reports": n_reports,
        "monitored_losses_identical": losses_on == losses_off,
        "fleet_overhead_pct": round(overhead_pct, 3),
        "fleet_overhead_ab_pct": round((wall_on / wall_off - 1.0) * 100.0,
                                       2),
        "health_check_ok": not problems,
        "step_time_ms_worst": (last_report.get("step_time_ms") or
                               {}).get("worst"),
        "hbm_peak_bytes": last_report.get("hbm_peak_bytes"),
        "anomalies": len(mon.anomalies),
    }
    assert out["monitored_losses_identical"], (losses_off, losses_on)
    assert overhead_pct < 2.0, \
        f"fleet monitor attributed overhead {overhead_pct:.2f}% >= 2%"
    assert not problems, problems
    if not on_tpu:
        out["note"] = ("tiny config on CPU — functional rung; the "
                       "overhead gate is attributed (proxy-timed), not "
                       "the noisy A/B wall delta")
    return out


def bench_serve_continuous(dev, config, on_tpu):
    """Tentpole rung: the continuous-batching serving engine under a
    Poisson arrival trace with mixed prompt lengths. Reports end-to-end
    tokens/s, per-token latency percentiles (TPOT p50/p99), TTFT, and
    the engine telemetry means (queue depth, decode-batch occupancy,
    block-pool utilization, prefill-vs-decode time share). Off-TPU the
    tiny config runs the full engine in pallas interpret mode — a
    functional rung with honest relative latencies; the flagship trace
    needs the TPU round."""
    from paddle_tpu.inference import InferenceEngine, Request, ServeConfig
    from paddle_tpu.models.llama import init_llama_params
    from paddle_tpu.observability.metrics import StepMetrics

    rng = np.random.RandomState(11)
    if on_tpu:
        serve = ServeConfig(block_size=128, num_blocks=257, max_batch=8,
                            prefill_chunk=256, max_seq_len=2048)
        n_req, rate, max_new = 24, 40.0, 64
        plens = rng.choice([64, 128, 384, 768], size=n_req,
                           p=[0.35, 0.35, 0.2, 0.1])
    else:
        serve = ServeConfig(block_size=128, num_blocks=17, max_batch=4,
                            prefill_chunk=64, max_seq_len=256)
        n_req, rate, max_new = 6, 8.0, 8
        plens = rng.choice([8, 24, 96, 130], size=n_req)
    params = init_llama_params(config, seed=0)
    metrics = StepMetrics(name="serve", n_devices=1)
    # all PR-12 observability layers ON for the measured run: the reported
    # tokens/s carries the request-tracing + histogram + flight-recorder
    # cost (bounded <2% by overlap_bench.bench_overhead)
    eng = InferenceEngine(params, config, serve, telemetry=metrics,
                          trace_requests=True, flight_recorder=True)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_req))
    prompts = [rng.randint(1, config.vocab_size, size=int(n)).tolist()
               for n in plens]
    reqs = [Request(p, max_new_tokens=max_new, arrival=float(t))
            for p, t in zip(prompts, arrivals)]
    stats = eng.run(reqs)
    recs = metrics.records

    # tracing-overhead check on the same prompts, deterministic replay so
    # the traced and untraced runs execute identical schedules and must
    # produce identical tokens (tracing is measurement-only). The headline
    # pct is ATTRIBUTED (time inside observability calls / run wall, via
    # the overlap_bench proxy clamp); the raw A/B wall delta rides along
    # for reference but carries several percent of host-scheduler noise.
    from benchmarks.overlap_bench import _TimedProxy

    def _det_run(on, attribute=False):
        e = InferenceEngine(params, config, serve, trace_requests=on,
                            flight_recorder=on)
        counter = [0.0]
        if attribute:
            e.tracer = _TimedProxy(e.tracer, counter)
            e.recorder = _TimedProxy(e.recorder, counter)
            e.slo = {k: _TimedProxy(h, counter) for k, h in e.slo.items()}
        rs = [Request(p, max_new_tokens=max_new, arrival=float(i))
              for i, p in enumerate(prompts)]
        t0 = time.perf_counter()
        e.run(rs, deterministic=True)
        return (time.perf_counter() - t0, counter[0],
                {s.req.request_id: list(s.generated) for s in e.finished})

    _det_run(False)  # warm the jit caches outside the timed pair
    t_off, _, toks_off = _det_run(False)
    t_on, _, toks_on = _det_run(True)
    wall_attr, obs_s, _ = _det_run(True, attribute=True)

    def mean_of(key):
        vals = [r[key] for r in recs if r.get(key) is not None]
        return round(float(np.mean(vals)), 4) if vals else None

    pre = sum(r.get("prefill_ms") or 0.0 for r in recs)
    dec = sum(r.get("decode_ms") or 0.0 for r in recs)
    out = {
        "requests": stats["requests"],
        "generated_tokens": stats["generated_tokens"],
        "tokens_per_sec": round(stats["tokens_per_sec"] or 0.0, 2),
        "ttft_p50_s": round(stats["ttft_p50_s"], 4),
        "ttft_p99_s": round(stats["ttft_p99_s"], 4),
        "tpot_p50_s": round(stats["tpot_p50_s"], 4),
        "tpot_p99_s": round(stats["tpot_p99_s"], 4),
        # streaming estimates from the fixed-memory LogHistograms, next to
        # the exact end-of-run percentiles above — must agree within one
        # log bucket (~16%) modulo the nearest-rank/interpolated split
        "ttft_stream_p50_s": round(stats["ttft_stream_p50_s"], 4),
        "ttft_stream_p99_s": round(stats["ttft_stream_p99_s"], 4),
        "tpot_stream_p50_s": round(stats["tpot_stream_p50_s"], 4),
        "tpot_stream_p99_s": round(stats["tpot_stream_p99_s"], 4),
        "unfinished": stats["unfinished"],
        "trace_spans": eng.tracer.span_count(),
        "tracing_overhead_pct": round(obs_s / wall_attr * 100.0, 2),
        "tracing_overhead_ab_pct": round((t_on / t_off - 1.0) * 100.0, 2),
        "traced_tokens_identical": toks_on == toks_off,
        "preemptions": stats["preemptions"],
        "iterations": stats["iterations"],
        "compiled_shapes": sorted(stats["compiles"]),
        "arrival_trace": {"process": "poisson", "rate_per_s": rate,
                          "prompt_lengths": sorted(set(int(x)
                                                       for x in plens))},
        "pool_blocks": stats["pool_blocks"],
        "block_size": serve.block_size,
        "max_batch": serve.max_batch,
        "queue_depth_mean": mean_of("queue_depth"),
        "batch_occupancy_mean": mean_of("batch_occupancy"),
        "pool_utilization_mean": mean_of("pool_utilization"),
        "prefill_time_share": round(pre / max(pre + dec, 1e-9), 4),
    }
    if not on_tpu:
        out["note"] = ("tiny config in pallas interpret mode on CPU — "
                       "functional rung; flagship trace lands with the "
                       "TPU bench round")
    return out


def bench_preempt_resume(dev, config, on_tpu):
    """PR-13 robustness rung: what preemption tolerance costs.

    * save_overlap_overhead_pct — wall time of n train steps with the
      CheckpointManager's interval-paced ASYNC saves riding along
      (device->host snapshot inline, file write overlapping subsequent
      steps) vs the same n steps bare; blocking_save_overhead_pct rides
      along to show what the overlap buys back;
    * resume_to_parity_ms — CheckpointManager.restore into a fresh
      state plus the first post-restore step, whose loss must match the
      uninterrupted run at that step bitwise (same compiled step);
    * swap_drain_ms — InferenceEngine.swap_weights drain latency at a
      mid-serve iteration boundary (identical weights, token streams
      checked bit-identical against an unswapped run).
    """
    import os
    import shutil
    import tempfile

    import jax
    from paddle_tpu.distributed.checkpoint.manager import CheckpointManager
    from paddle_tpu.inference import InferenceEngine, Request, ServeConfig
    from paddle_tpu.models.llama import (ParallelConfig, build_train_step,
                                         init_llama_params)

    import jax.numpy as jnp

    parallel = ParallelConfig(remat=True, use_flash=on_tpu)
    step, params, opt = build_train_step(config, parallel, lr=1e-4)
    batch, seq = (4, 2048) if on_tpu else (2, 128)
    rng = np.random.RandomState(13)
    ids = rng.randint(0, config.vocab_size, (batch, seq)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int32)

    # the jitted step DONATES its param/opt buffers, so every run that
    # branches from shared state must branch from a fresh device copy
    def copy_tree(t):
        return jax.tree_util.tree_map(
            lambda a: jnp.copy(a) if isinstance(a, jax.Array) else a, t)

    p, o = copy_tree(params), copy_tree(opt)
    for _ in range(2):  # compile + warm outside every timed window
        p, o, loss = step(p, o, ids, labels)
    jax.device_get(loss)
    n = 6 if on_tpu else 4
    interval = n // 2  # two saves per measured run

    root = tempfile.mkdtemp(prefix="paddle_tpu_bench_ckpt_")
    try:
        # orbax cold-start (imports, type-handler registration, asyncio
        # setup) lands on the process's first save/restore — pay it here,
        # outside every timed window
        warm = CheckpointManager(os.path.join(root, "warm"), keep=1)
        warm.save({"params": p, "opt": o, "step": 0}, 0, block=True)
        warm.restore({"params": copy_tree(p), "opt": copy_tree(o),
                      "step": 0})

        pp, oo = copy_tree(p), copy_tree(o)
        t0 = time.perf_counter()
        for _ in range(n):
            pp, oo, loss = step(pp, oo, ids, labels)
        jax.device_get(loss)
        t_plain = time.perf_counter() - t0

        mgr = CheckpointManager(os.path.join(root, "async"), keep=2,
                                interval=interval)
        pp, oo = copy_tree(p), copy_tree(o)
        t0 = time.perf_counter()
        for i in range(1, n + 1):
            pp, oo, loss = step(pp, oo, ids, labels)
            mgr.on_step(i, lambda: {"params": pp, "opt": oo, "step": i})
        jax.device_get(loss)
        t_async = time.perf_counter() - t0
        errs = mgr.wait()  # drain the tail write OUTSIDE the window:
        assert not errs, errs  # overlapping it is the feature measured

        mgr_b = CheckpointManager(os.path.join(root, "block"), keep=2)
        pb, ob = copy_tree(p), copy_tree(o)
        t0 = time.perf_counter()
        for i in range(1, n + 1):
            pb, ob, loss = step(pb, ob, ids, labels)
            if i % interval == 0:
                mgr_b.save({"params": pb, "opt": ob, "step": i}, i,
                           block=True)
        jax.device_get(loss)
        t_block = time.perf_counter() - t0

        # resume-to-parity: the uninterrupted run's next-step loss is the
        # target; restore the newest checkpoint (written at step n, state
        # == pp/oo) into a fresh template and replay that step
        _, _, l_ref = step(pp, oo, ids, labels)
        l_ref = float(jax.device_get(l_ref))
        tmpl = {"params": copy_tree(params), "opt": copy_tree(opt),
                "step": 0}
        t0 = time.perf_counter()
        restored_step = mgr.restore(tmpl)
        _, _, l_res = step(tmpl["params"], tmpl["opt"], ids, labels)
        l_res = float(jax.device_get(l_res))
        resume_ms = (time.perf_counter() - t0) * 1e3

        # mid-serve weight-swap drain latency, identical-weights parity
        if on_tpu:
            serve = ServeConfig(block_size=128, num_blocks=65, max_batch=4,
                                prefill_chunk=256, max_seq_len=1024)
            plens, max_new = (64, 384), 16
        else:
            serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                                prefill_chunk=64, max_seq_len=256)
            plens, max_new = (8, 130), 6
        sparams = init_llama_params(config, seed=0)
        copy = lambda t: jax.tree_util.tree_map(lambda a: a, t)

        def mk_reqs():
            r = np.random.RandomState(3)
            return [Request(r.randint(1, config.vocab_size,
                                      size=int(nn)).tolist(),
                            max_new_tokens=max_new, arrival=float(i))
                    for i, nn in enumerate(plens)]

        ref_eng = InferenceEngine(copy(sparams), config, serve)
        ref_eng.run(mk_reqs(), deterministic=True)
        eng = InferenceEngine(copy(sparams), config, serve)
        eng.swap_weights(copy(sparams), at_iteration=3)
        st = eng.run(mk_reqs(), deterministic=True)
        toks = lambda e: {s.req.request_id: s.tokens for s in e.finished}

        out = {
            "train_steps_timed": n,
            "saves_per_run": n // interval,
            "step_time_plain_ms": round(t_plain / n * 1e3, 2),
            "save_overlap_overhead_pct":
                round((t_async / t_plain - 1) * 100, 2),
            "blocking_save_overhead_pct":
                round((t_block / t_plain - 1) * 100, 2),
            "resume_to_parity_ms": round(resume_ms, 1),
            "resume_step": restored_step,
            "resume_loss_bitwise": l_res == l_ref,
            "swap_drain_ms": round(eng.last_swap["swap_ms"], 2),
            "swap_tokens_identical": toks(eng) == toks(ref_eng),
            "swap_unfinished": st["unfinished"],
        }
        if not on_tpu:
            out["note"] = ("tiny config on CPU — overhead ratios are "
                           "functional-rung numbers; the flagship costs "
                           "land with the TPU bench round")
        return out
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_serve_overload(dev, config, on_tpu):
    """PR-14 robustness rung: the serving engine under a 2x-capacity
    burst with admission control, deadline shedding, and the crash
    journal all live.

    * determinism — the same arrival trace replayed twice must shed the
      SAME request set and produce bit-identical survivor streams
      (deterministic mode: deadlines/admission consult only the
      iteration clock);
    * accounting — every request ends finished/rejected/shed/failed
      with a cause (``no_silent_drops``), and the pool is leak-free
      after the burst;
    * goodput — a wall-clock run of the same burst reports generated
      tokens/s over admitted-and-finished requests, the shed rate, and
      finished-request TTFT p99;
    * cost — wall share attributed to the admission controller + the
      engine journal via the overlap_bench proxy clamp (the PR-12
      observability layers have their own <2% gate; this isolates what
      PR 14 added).
    """
    import os
    import shutil
    import tempfile

    from benchmarks.overlap_bench import _TimedProxy
    from paddle_tpu.inference import InferenceEngine, Request, ServeConfig
    from paddle_tpu.models.llama import init_llama_params

    rng = np.random.RandomState(17)
    if on_tpu:
        serve = dict(block_size=128, num_blocks=33, max_batch=4,
                     prefill_chunk=256, max_seq_len=1024, max_queue=16,
                     overcommit=8.0)
        n_req, max_new = 24, 32
        plens = rng.choice([64, 128, 384], size=n_req)
        ttft_dl, total_dl = 30.0, 120.0     # iteration-clock deadlines
    else:
        serve = dict(block_size=128, num_blocks=3, max_batch=1,
                     prefill_chunk=32, max_seq_len=256, max_queue=8,
                     overcommit=8.0)
        n_req, max_new = 8, 24
        plens = [30] * n_req
        ttft_dl, total_dl = 28.0, 160.0
    params = init_llama_params(config, seed=0)
    prompts = [rng.randint(1, config.vocab_size, size=int(n)).tolist()
               for n in plens]

    def mk_reqs(arrivals, scale=1.0):
        return [Request(p, max_new_tokens=max_new, arrival=float(t),
                        ttft_deadline=ttft_dl * scale,
                        deadline=total_dl * scale)
                for p, t in zip(prompts, arrivals)]

    root = tempfile.mkdtemp(prefix="paddle_tpu_bench_overload_")
    try:
        def det_run(tag, attribute=False):
            eng = InferenceEngine(
                params, config, ServeConfig(**serve),
                journal=os.path.join(root, f"{tag}.jsonl"))
            counter = [0.0]
            if attribute:
                eng._journal = _TimedProxy(eng._journal, counter)
                eng.admission = _TimedProxy(eng.admission, counter)
            t0 = time.perf_counter()
            stats = eng.run(mk_reqs(range(n_req)), deterministic=True)
            wall = time.perf_counter() - t0
            return eng, stats, wall, counter[0]

        det_run("warm")  # compile + warm outside every timed window
        eng_a, st_a, _, _ = det_run("a")
        eng_b, st_b, _, _ = det_run("b")
        shed_of = lambda e: sorted((s.req.request_id, s.fail_cause)
                                   for s in e.shed)
        toks_of = lambda e: {s.req.request_id: s.tokens
                             for s in e.finished}
        outcomes = st_a["outcomes"]
        silent = [rid for rid, (state, cause) in outcomes.items()
                  if state not in ("finished", "rejected", "shed",
                                   "failed")
                  or (state != "finished" and not cause)]

        # attributed admission+journal cost on the same deterministic
        # trace (max of 2 — conservative, like the overlap_bench gate)
        attrs = []
        det_wall = None
        for i in range(2):
            _, _, w, obs = det_run(f"attr{i}", attribute=True)
            attrs.append(obs / max(w, 1e-9))
            det_wall = w if det_wall is None else min(det_wall, w)
        attr = max(attrs)

        # wall-clock goodput run: the burst arrives at 2x the rate the
        # engine drains it; the iteration-clock deadlines rescale to
        # seconds via the measured per-iteration wall
        pace = det_wall / (2.0 * n_req)
        it_wall = det_wall / max(st_a["iterations"], 1)
        eng_w = InferenceEngine(params, config, ServeConfig(**serve),
                                journal=os.path.join(root, "wall.jsonl"))
        t0 = time.perf_counter()
        st_w = eng_w.run(mk_reqs([i * pace for i in range(n_req)],
                                 scale=it_wall))
        wall = time.perf_counter() - t0

        out = {
            "requests": n_req,
            "shed_deterministic": shed_of(eng_a) == shed_of(eng_b),
            "streams_identical": toks_of(eng_a) == toks_of(eng_b),
            "no_silent_drops": not silent,
            "pool_leak_free": eng_a.pool.used_blocks == 0
                              and eng_w.pool.used_blocks == 0,
            "det_finished": st_a["requests"],
            "det_shed": st_a["shed"],
            "det_rejected": st_a["rejected"],
            "admission_journal_overhead_pct": round(attr * 100.0, 3),
            "goodput_tokens_per_sec":
                round(st_w["generated_tokens"] / wall, 2),
            "wall_finished": st_w["requests"],
            "wall_shed_rate": round(st_w["shed"] / n_req, 3),
            "wall_rejected": st_w["rejected"],
            "wall_ttft_p99_s": round(st_w["ttft_p99_s"], 4)
                if st_w["requests"] else None,
        }
        if not on_tpu:
            out["note"] = ("tiny config in pallas interpret mode on CPU "
                           "— functional rung; flagship burst lands with "
                           "the TPU bench round")
        return out
    finally:
        shutil.rmtree(root, ignore_errors=True)


def bench_serve_prefix_cache(dev, config, on_tpu):
    """PR-16 tentpole rung: prefix-cached serving (COW shared KV blocks)
    under a Poisson trace where 80% of requests share one long system
    prompt. Reports the cache hit rate, TTFT p50/p99 cache-on vs
    cache-off on the SAME trace, tokens/s, and the two correctness
    gates the feature ships under: cached-vs-cold greedy tokens bitwise
    identical, and a leak-free pool (shared blocks counted once,
    parked cache blocks excluded)."""
    from paddle_tpu.inference import InferenceEngine, Request, ServeConfig
    from paddle_tpu.models.llama import init_llama_params

    rng = np.random.RandomState(16)
    if on_tpu:
        serve_kw = dict(block_size=128, num_blocks=257, max_batch=8,
                        prefill_chunk=256, max_seq_len=2048)
        n_req, rate, max_new, sys_len = 24, 12.0, 32, 1024
        tail = (16, 96)
    else:
        serve_kw = dict(block_size=128, num_blocks=24, max_batch=2,
                        prefill_chunk=64, max_seq_len=512)
        n_req, rate, max_new, sys_len = 10, 4.0, 6, 384
        tail = (8, 24)
    params = init_llama_params(config, seed=0)
    system = rng.randint(1, config.vocab_size, size=sys_len).tolist()
    prompts = []
    for i in range(n_req):
        if rng.rand() < 0.8 or i == 0:   # 80% share the system prompt
            sfx = rng.randint(1, config.vocab_size,
                              size=rng.randint(*tail)).tolist()
            prompts.append(system + sfx)
        else:
            prompts.append(rng.randint(
                1, config.vocab_size,
                size=rng.randint(sys_len // 4, sys_len // 2)).tolist())
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_req))

    def wall_run(prefix_cache):
        eng = InferenceEngine(
            params, config, ServeConfig(prefix_cache=prefix_cache,
                                        **serve_kw))
        reqs = [Request(list(p), max_new_tokens=max_new, arrival=float(t))
                for p, t in zip(prompts, arrivals)]
        t0 = time.perf_counter()
        stats = eng.run(reqs)
        return eng, stats, time.perf_counter() - t0

    def det_tokens(prefix_cache):
        eng = InferenceEngine(
            params, config, ServeConfig(prefix_cache=prefix_cache,
                                        **serve_kw))
        reqs = [Request(list(p), max_new_tokens=max_new, arrival=float(i))
                for i, p in enumerate(prompts)]
        eng.run(reqs, deterministic=True)
        return eng, {s.req.request_id: list(s.generated)
                     for s in eng.finished}

    det_tokens(False)            # warm the jit caches outside timing
    eng_off, st_off, wall_off = wall_run(False)
    eng_on, st_on, wall_on = wall_run(True)
    pc = eng_on.stats()["prefix_cache"]
    # bitwise parity gate on a deterministic replay of the same prompts
    eng_dc, toks_cold = det_tokens(False)
    eng_dw, toks_warm = det_tokens(True)
    # hit requests' first token can land inside the arrival-poll
    # iteration (TTFT records as 0.0); floor at 1 ms so the speedup
    # stays a finite, conservative number
    p50_up = st_off["ttft_p50_s"] / max(st_on["ttft_p50_s"], 1e-3)
    out = {
        "requests": n_req,
        "shared_prefix_tokens": sys_len,
        "hit_rate": pc["hit_rate"],
        "hit_tokens": pc["hit_tokens"],
        "cached_blocks": pc["cached_blocks"],
        "cow_copies": pc["cow_copies"],
        "ttft_p50_s_off": round(st_off["ttft_p50_s"], 4),
        "ttft_p50_s_on": round(st_on["ttft_p50_s"], 4),
        "ttft_p99_s_off": round(st_off["ttft_p99_s"], 4),
        "ttft_p99_s_on": round(st_on["ttft_p99_s"], 4),
        "ttft_p50_speedup": round(p50_up, 2),
        "tokens_per_sec_off":
            round(st_off["generated_tokens"] / wall_off, 2),
        "tokens_per_sec_on":
            round(st_on["generated_tokens"] / wall_on, 2),
        "cached_tokens_identical": toks_warm == toks_cold,
        "pool_leak_free": all(e.pool.used_blocks == 0 for e in
                              (eng_off, eng_on, eng_dc, eng_dw)),
        "det_hits": eng_dw.stats()["prefix_cache"]["hits"],
    }
    if not on_tpu:
        out["note"] = ("tiny config in pallas interpret mode on CPU — "
                       "functional rung; flagship trace lands with the "
                       "TPU bench round")
    return out


def bench_serve_kv_int8(dev, config, on_tpu):
    """PR-16 rung: int8 paged KV capacity. At a FIXED pool byte budget,
    how many sequences are concurrently resident with int8 blocks
    (bytes + per-column fp32 scale sidecars) vs fp16 blocks — measured
    by actually serving that many one-block sequences with zero
    preemptions — plus decode wall per token for each dtype. Uses a
    head_dim=64 config: the ratio 2*hd/(hd+4) needs hd >= 36 to clear
    the 1.8x target (at hd=64 the analytic ceiling is 1.88x)."""
    import jax.numpy as jnp

    from paddle_tpu.inference import InferenceEngine, Request, ServeConfig
    from paddle_tpu.models.llama import init_llama_params, llama_tiny

    if on_tpu:
        cfg = llama_tiny(vocab=2048, hidden=1024, layers=4, heads=16,
                         kv_heads=8, seq=256)
        budget_blocks, max_new, plen = 64, 8, 100
    else:
        cfg = llama_tiny(vocab=96, hidden=256, layers=1, heads=4,
                         kv_heads=2, seq=256)
        budget_blocks, max_new, plen = 8, 2, 100
    bs = 128
    kvd = cfg.num_key_value_heads * (
        cfg.hidden_size // cfg.num_attention_heads)
    nkv = cfg.num_key_value_heads
    # per-block bytes across k+v (per layer): fp16/fp32 model dtype vs
    # int8 bytes + one fp32 scale per (kv-head, column)
    fp_item = jnp.dtype(cfg.dtype).itemsize
    bytes_fp = 2 * kvd * bs * fp_item
    bytes_i8 = 2 * (kvd * bs * 1 + nkv * bs * 4)
    budget = budget_blocks * bytes_fp
    blocks_i8 = int(budget // bytes_i8)
    params = init_llama_params(cfg, seed=0)
    rng = np.random.RandomState(8)

    def peak_concurrency(kv_dtype, usable):
        serve = ServeConfig(block_size=bs, num_blocks=usable + 1,
                            max_batch=usable, prefill_chunk=128,
                            max_seq_len=128, kv_dtype=kv_dtype)
        eng = InferenceEngine(params, cfg, serve, record_events=True)
        reqs = [Request(rng.randint(1, cfg.vocab_size,
                                    size=plen).tolist(),
                        max_new_tokens=max_new, arrival=0.0)
                for _ in range(usable)]
        t0 = time.perf_counter()
        stats = eng.run(reqs)
        wall = time.perf_counter() - t0
        live = peak = 0
        for ev in eng.events:
            kind = ev[1]
            if kind == "admit":
                live += 1
                peak = max(peak, live)
            elif kind in ("finish", "evict", "shed", "failed"):
                live -= 1
        assert stats["preemptions"] == 0 and eng.pool.used_blocks == 0
        return peak, stats, wall

    peak_concurrency("auto", budget_blocks)      # warm jit caches
    peak_fp, st_fp, wall_fp = peak_concurrency("auto", budget_blocks)
    peak_i8, st_i8, wall_i8 = peak_concurrency("int8", blocks_i8)
    dec_fp = wall_fp / max(st_fp["generated_tokens"], 1)
    dec_i8 = wall_i8 / max(st_i8["generated_tokens"], 1)
    out = {
        "head_dim": cfg.hidden_size // cfg.num_attention_heads,
        "pool_budget_bytes_per_layer": int(budget),
        "block_bytes_fp": int(bytes_fp),
        "block_bytes_int8": int(bytes_i8),
        "blocks_fp": budget_blocks,
        "blocks_int8": blocks_i8,
        "max_concurrent_fp": peak_fp,
        "max_concurrent_int8": peak_i8,
        "concurrency_ratio": round(peak_i8 / max(peak_fp, 1), 2),
        # the 1.8x contract pinned against fp16 block bytes, independent
        # of the platform model dtype (fp32 on CPU inflates the measured
        # ratio above this)
        "model_kv_itemsize": int(fp_item),
        "fp16_equivalent_ratio": round(2 * kvd * bs * 2 / bytes_i8, 2),
        "decode_ms_per_tok_fp": round(dec_fp * 1e3, 3),
        "decode_ms_per_tok_int8": round(dec_i8 * 1e3, 3),
        "decode_ms_ratio": round(dec_i8 / max(dec_fp, 1e-9), 2),
    }
    if not on_tpu:
        out["note"] = ("tiny hd=64 config in pallas interpret mode on "
                       "CPU — capacity ratio is exact (byte arithmetic "
                       "+ real concurrent serving); decode timing is "
                       "interpret-mode, honest only relatively")
    return out


def bench_serve_speculative(dev, config, on_tpu):
    """PR-18 tentpole rung: speculative decoding (draft model + batched
    paged verification) vs the sequential engine on the SAME
    shared-prefix Poisson trace. Reports accept-rate, tokens/s and TPOT
    p50/p99 for both engines, and the gate the feature ships under:
    speculative streams token-bitwise-identical to sequential greedy
    decode (deterministic replay), leak-free pool.

    Throughput is measured in the deterministic ITERATION clock
    (tokens per scheduler iteration): on a real TPU decode is
    memory-bound, so a verify pass over K+1 positions costs roughly one
    sequential step and tokens/iteration is the honest speedup proxy;
    interpret-mode wall time scales with arithmetic instead and is
    reported alongside for reference only."""
    import jax

    from paddle_tpu.inference import InferenceEngine, Request, ServeConfig
    from paddle_tpu.models.llama import init_llama_params

    rng = np.random.RandomState(18)
    if on_tpu:
        serve_kw = dict(block_size=128, num_blocks=257, max_batch=8,
                        prefill_chunk=256, max_seq_len=2048)
        n_req, rate, max_new, sys_len, K = 24, 12.0, 32, 512, 4
        tail = (16, 96)
    else:
        serve_kw = dict(block_size=128, num_blocks=24, max_batch=2,
                        prefill_chunk=64, max_seq_len=256)
        n_req, rate, max_new, sys_len, K = 8, 6.0, 8, 96, 3
        tail = (8, 24)
    params = init_llama_params(config, seed=0)
    # Condition the weights so the default layer-truncated draft tracks
    # the base model: damp every layer's residual writes so logits are
    # dominated by the embedding path both models share. The parity
    # gate below holds for ANY weights by construction (emitted tokens
    # are always the base argmax); the damping only makes the recorded
    # accept-rate/speedup representative of a draft trained to track
    # its base, rather than of two mutually-random networks.
    damp = 0.05
    layers = dict(params["layers"])
    for name in ("o_proj", "down_proj"):
        layers[name] = jax.tree_util.tree_map(lambda a: a * damp,
                                              layers[name])
    params = dict(params, layers=layers)
    system = rng.randint(1, config.vocab_size, size=sys_len).tolist()
    prompts = [system + rng.randint(1, config.vocab_size,
                                    size=rng.randint(*tail)).tolist()
               for _ in range(n_req)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_req))

    def det_run(speculative):
        eng = InferenceEngine(
            params, config, ServeConfig(speculative=speculative,
                                        draft_k=K, **serve_kw))
        reqs = [Request(list(p), max_new_tokens=max_new, arrival=float(i))
                for i, p in enumerate(prompts)]
        t0 = time.perf_counter()
        stats = eng.run(reqs, deterministic=True)
        wall = time.perf_counter() - t0
        toks = {s.req.request_id: list(s.generated) for s in eng.finished}
        return eng, stats, wall, toks

    def wall_run(speculative):
        eng = InferenceEngine(
            params, config, ServeConfig(speculative=speculative,
                                        draft_k=K, **serve_kw))
        reqs = [Request(list(p), max_new_tokens=max_new, arrival=float(t))
                for p, t in zip(prompts, arrivals)]
        t0 = time.perf_counter()
        eng.run(reqs)
        return eng, time.perf_counter() - t0

    det_run(True)                # warm the jit caches outside timing
    det_run(False)
    eng_off, st_off, dwall_off, toks_off = det_run(False)
    eng_on, st_on, dwall_on, toks_on = det_run(True)
    weng_off, wall_off = wall_run(False)
    weng_on, wall_on = wall_run(True)
    sp = eng_on.stats()["speculative"]
    # iteration-clock throughput: tokens per scheduler iteration
    tpi_off = st_off["generated_tokens"] / max(st_off["iterations"], 1)
    tpi_on = st_on["generated_tokens"] / max(st_on["iterations"], 1)
    out = {
        "requests": n_req,
        "draft_k": K,
        "draft_layers": sp["draft_layers"],
        "base_layers": config.num_hidden_layers,
        "accept_rate": round(sp["accept_rate"], 3),
        "proposed": sp["proposed"],
        "accepted": sp["accepted"],
        "tokens_per_iteration_off": round(tpi_off, 3),
        "tokens_per_iteration_on": round(tpi_on, 3),
        "speedup": round(tpi_on / max(tpi_off, 1e-9), 2),
        "tpot_p50_iters_off": round(st_off["tpot_p50_s"], 4),
        "tpot_p50_iters_on": round(st_on["tpot_p50_s"], 4),
        "tpot_p99_iters_off": round(st_off["tpot_p99_s"], 4),
        "tpot_p99_iters_on": round(st_on["tpot_p99_s"], 4),
        "iterations_off": st_off["iterations"],
        "iterations_on": st_on["iterations"],
        "wall_tokens_per_sec_off":
            round(weng_off.stats()["generated_tokens"] / wall_off, 2),
        "wall_tokens_per_sec_on":
            round(weng_on.stats()["generated_tokens"] / wall_on, 2),
        "streams_identical": toks_on == toks_off,
        "pool_leak_free": all(e.pool.used_blocks == 0 for e in
                              (eng_off, eng_on, weng_off, weng_on)),
        "compiled_shapes": sorted(st_on["compiles"]),
        "arrival_trace": {"process": "poisson", "rate_per_s": rate,
                          "shared_prefix_tokens": sys_len},
    }
    if not on_tpu:
        out["note"] = ("tiny config in pallas interpret mode on CPU — "
                       "speedup is the iteration-clock proxy (interpret "
                       "wall time scales with arithmetic, not memory "
                       "traffic); TPU round lands final numbers")
    return out


def bench_serve_tp(dev, config, on_tpu):
    """PR-19 tentpole rung: tensor-parallel serving. The same Poisson
    trace served at mp=1 and at every feasible mp in {2, 4} — weights
    sliced per param_pspecs, KV pools sharded by kv-head — with
    speculation + int8 KV + prefix caching all on. Reports per-degree
    tokens/s, TTFT/TPOT p50/p99 and pool-bytes-per-rank, and the gates
    the feature ships under: every sharded stream token-bitwise-
    identical to mp=1 (greedy argmax absorbs the ULP drift of the
    row-parallel reductions; PARITY.md), leak-free pools at every
    degree.

    Off-TPU the virtual CPU mesh time-slices one host, so wall-clock
    "speedup" measures sharding overhead, not parallel speedup — the
    honest per-rank win there is pool_bytes_per_rank halving per
    doubling of mp; the TPU round lands real scaling numbers."""
    import jax

    from paddle_tpu.inference import InferenceEngine, Request, ServeConfig
    from paddle_tpu.models.llama import init_llama_params, llama_tiny

    rng = np.random.RandomState(19)
    if on_tpu:
        cfg = config  # flagship: nh=nkv=16, vocab/inter % 4 == 0
        serve_kw = dict(block_size=128, num_blocks=257, max_batch=8,
                        prefill_chunk=256, max_seq_len=2048)
        n_req, rate, max_new, sys_len, tail = 24, 12.0, 32, 512, (16, 96)
    else:
        # kv_heads=4 so mp=4 can shard the pools one kv head per rank
        cfg = llama_tiny(vocab=96, hidden=64, layers=2, heads=4,
                         kv_heads=4, seq=256)
        serve_kw = dict(block_size=128, num_blocks=24, max_batch=2,
                        prefill_chunk=64, max_seq_len=256)
        n_req, rate, max_new, sys_len, tail = 8, 6.0, 8, 96, (8, 24)
    spec_kw = dict(speculative=True, draft_k=3, prefix_cache=True,
                   kv_dtype="int8")
    ndev = len(jax.devices())
    degrees = [m for m in (1, 2, 4)
               if m <= ndev and cfg.num_key_value_heads % m == 0]
    if degrees == [1]:
        return {"note": f"needs >= 2 local devices for the mp rung, have "
                        f"{ndev} — run under XLA_FLAGS="
                        f"--xla_force_host_platform_device_count=8",
                "devices": ndev}
    params = init_llama_params(cfg, seed=0)
    system = rng.randint(1, cfg.vocab_size, size=sys_len).tolist()
    prompts = [system + rng.randint(1, cfg.vocab_size,
                                    size=rng.randint(*tail)).tolist()
               for _ in range(n_req)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_req))

    def det_run(mp):
        eng = InferenceEngine(params, cfg,
                              ServeConfig(mp=mp, **spec_kw, **serve_kw))
        reqs = [Request(list(p), max_new_tokens=max_new, arrival=float(i))
                for i, p in enumerate(prompts)]
        stats = eng.run(reqs, deterministic=True)
        toks = {s.req.request_id: list(s.generated) for s in eng.finished}
        return eng, stats, toks

    def wall_run(mp):
        eng = InferenceEngine(params, cfg,
                              ServeConfig(mp=mp, **spec_kw, **serve_kw))
        reqs = [Request(list(p), max_new_tokens=max_new, arrival=float(t))
                for p, t in zip(prompts, arrivals)]
        t0 = time.perf_counter()
        stats = eng.run(reqs)
        return eng, stats, time.perf_counter() - t0

    per_degree, ref_toks, leak_free, parity = {}, None, True, True
    for mp in degrees:
        det_run(mp)  # warm the per-degree jit caches outside timing
        eng_d, st_d, toks = det_run(mp)
        eng_w, st_w, wall = wall_run(mp)
        if mp == degrees[0]:
            ref_toks = toks
        parity = parity and (toks == ref_toks)
        leak_free = leak_free and all(e.pool.used_blocks == 0
                                      for e in (eng_d, eng_w))
        per_degree[f"mp{mp}"] = {
            "tokens_per_iteration": round(
                st_d["generated_tokens"] / max(st_d["iterations"], 1), 3),
            "wall_tokens_per_sec": round(
                st_w["generated_tokens"] / wall, 2),
            "ttft_p50_s": round(st_w["ttft_p50_s"], 4),
            "ttft_p99_s": round(st_w["ttft_p99_s"], 4),
            "tpot_p50_s": round(st_w["tpot_p50_s"], 4),
            "tpot_p99_s": round(st_w["tpot_p99_s"], 4),
            "pool_bytes_per_rank": eng_d.stats()["pool_bytes_per_rank"],
            "compiled_shapes": sorted(st_d["compiles"]),
        }
    base = per_degree[f"mp{degrees[0]}"]
    top = per_degree[f"mp{degrees[-1]}"]
    out = {
        "requests": n_req,
        "degrees": degrees,
        "kv_heads": cfg.num_key_value_heads,
        **per_degree,
        "wall_speedup_top": round(top["wall_tokens_per_sec"]
                                  / max(base["wall_tokens_per_sec"], 1e-9),
                                  2),
        "pool_bytes_ratio_top": round(base["pool_bytes_per_rank"]
                                      / max(top["pool_bytes_per_rank"], 1),
                                      2),
        "streams_identical": parity,
        "pool_leak_free": leak_free,
        "arrival_trace": {"process": "poisson", "rate_per_s": rate,
                          "shared_prefix_tokens": sys_len},
    }
    if not on_tpu:
        out["note"] = ("tiny config on the virtual CPU mesh — parity and "
                       "per-rank pool bytes are exact; wall-clock numbers "
                       "measure sharding overhead on one time-sliced "
                       "host, not parallel speedup; TPU round lands real "
                       "scaling")
    return out


def bench_serve_fleet(dev, config, on_tpu):
    """PR-20 tentpole rung: the multi-replica serving fleet. One
    shared-prefix Poisson trace served by N in {1, 2, 4} FleetRouter
    replicas (prefix caching on, per-replica journals), reporting
    per-N tokens/s and the router's affinity hit rate, plus the gates
    the feature ships under: every fleet's streams token-bitwise-
    identical to the lone engine's (greedy decode is a pure function
    of prompt + weights — replica count cannot change tokens), an A/B
    of affinity vs seeded-random dispatch on fleet-wide prefix-cache
    reuse, a chaos cell (kill one replica mid-burst: zero lost
    accepted requests, migrated streams bit-identical), and a rolling
    fleet-wide weight swap (every replica swaps at its idle boundary,
    zero drops).

    Off-TPU the replicas time-slice one host, so wall-clock "speedup"
    measures router + duplication overhead, not parallel speedup — the
    honest wins there are the affinity hit-rate delta and the chaos /
    rolling-swap gates; the TPU round lands real scaling numbers."""
    import shutil
    import tempfile

    from paddle_tpu.inference import (FleetRouter, InferenceEngine,
                                      Request, ServeConfig)
    from paddle_tpu.models.llama import init_llama_params, llama_tiny

    rng = np.random.RandomState(20)
    if on_tpu:
        cfg = config
        serve_kw = dict(block_size=128, num_blocks=257, max_batch=8,
                        prefill_chunk=256, max_seq_len=2048,
                        prefix_cache=True)
        n_req, rate, max_new, sys_len, tail = 24, 12.0, 32, 512, (16, 96)
    else:
        cfg = llama_tiny(vocab=96, hidden=64, layers=1, heads=4,
                         kv_heads=2, seq=512)
        serve_kw = dict(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=256,
                        prefix_cache=True)
        n_req, rate, max_new, sys_len, tail = 10, 4.0, 6, 140, (6, 16)
    params = init_llama_params(cfg, seed=0)
    system = rng.randint(1, cfg.vocab_size, size=sys_len).tolist()
    prompts = [system + rng.randint(1, cfg.vocab_size,
                                    size=rng.randint(*tail)).tolist()
               for _ in range(n_req)]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_req))

    def det_reqs():
        # iteration-clock arrivals, spaced so the shared prefix is
        # derived before later submits probe for it
        return [Request(list(p), max_new_tokens=max_new,
                        arrival=float(2 * i))
                for i, p in enumerate(prompts)]

    def det_run(n, policy="affinity", **runkw):
        d = tempfile.mkdtemp(prefix="fleet_bench_")
        try:
            fleet = FleetRouter(params, cfg, ServeConfig(**serve_kw),
                                n_replicas=n, journal_dir=d,
                                policy=policy)
            stats = fleet.run(det_reqs(), deterministic=True, **runkw)
            return fleet, stats, fleet.streams()
        finally:
            shutil.rmtree(d, ignore_errors=True)

    def wall_run(n):
        fleet = FleetRouter(params, cfg, ServeConfig(**serve_kw),
                            n_replicas=n)
        reqs = [Request(list(p), max_new_tokens=max_new,
                        arrival=float(t))
                for p, t in zip(prompts, arrivals)]
        t0 = time.perf_counter()
        stats = fleet.run(reqs)
        return fleet, stats, time.perf_counter() - t0

    # lone-engine reference: the bit-identity oracle for every fleet
    ref_eng = InferenceEngine(params, cfg, ServeConfig(**serve_kw))
    reqs = det_reqs()
    for i, r in enumerate(reqs):
        r.request_id = i
    ref_eng.run(reqs, deterministic=True)
    ref = {s.req.request_id: list(s.generated) for s in ref_eng.finished}

    per_n, parity, leak_free, zero_lost = {}, True, True, True
    for n in (1, 2, 4):
        det_run(n)  # warm the jit caches outside timing
        fleet_d, st_d, toks = det_run(n)
        fleet_w, st_w, wall = wall_run(n)
        parity = parity and (toks == ref)
        zero_lost = zero_lost and st_d["lost"] == 0 == st_w["lost"]
        leak_free = leak_free and all(
            fleet_d.engines[i].pool.used_blocks == 0
            for i in fleet_d._live())
        per_n[f"n{n}"] = {
            "tokens_per_iteration": round(
                st_d["generated_tokens"] / max(st_d["iterations"], 1),
                3),
            "wall_tokens_per_sec": round(
                st_w["generated_tokens"] / wall, 2),
            # worst live replica's streaming TTFT p99 (the fleet's
            # client-visible tail)
            "ttft_p99_s": round(max(
                fleet_w.engines[i].slo["ttft"].percentile(99) or 0.0
                for i in fleet_w._live()), 4),
            "affinity_hit_rate": (round(st_d["affinity_hit_rate"], 3)
                                  if st_d["affinity_hit_rate"]
                                  is not None else None),
            "spills": st_d["spills"],
            "routed_per_replica": st_d["routed_per_replica"],
        }

    # A/B: affinity vs seeded-random dispatch, fleet-wide cache reuse
    fleet_a, st_a, _ = det_run(4)
    fleet_r, st_r, toks_r = det_run(4, policy="random")
    aff_tokens = sum(e.cache.hit_tokens for e in fleet_a.engines)
    rnd_tokens = sum(e.cache.hit_tokens for e in fleet_r.engines)

    # chaos: kill replica 0 mid-burst, journal migration onto survivors
    fleet_c, st_c, toks_c = det_run(3, kill_at=(n_req, 0))

    # rolling fleet-wide weight swap under traffic (same weights, so
    # bit-identity doubles as the zero-drop check)
    fleet_s, st_s, toks_s = det_run(3, rolling_swap_at=3,
                                    swap_source=params)

    base = per_n["n1"]["wall_tokens_per_sec"]
    top = per_n["n4"]["wall_tokens_per_sec"]
    out = {
        "requests": n_req,
        "replica_counts": [1, 2, 4],
        **per_n,
        "wall_speedup_top": round(top / max(base, 1e-9), 2),
        "streams_identical": parity,
        "zero_lost": zero_lost,
        "pool_leak_free": leak_free,
        "affinity_ab": {
            "affinity_hit_tokens": aff_tokens,
            "random_hit_tokens": rnd_tokens,
            "affinity_wins": bool(aff_tokens >= rnd_tokens),
            "random_streams_identical": toks_r == ref,
        },
        "chaos_kill": {
            "migrations": st_c["migrations"],
            "lost": st_c["lost"],
            "streams_identical": toks_c == ref,
            "survivors_leak_free": all(
                fleet_c.engines[i].pool.used_blocks == 0
                for i in fleet_c._live()),
        },
        "rolling_swap": {
            "swapped": st_s["rolling_swaps"],
            "lost": st_s["lost"],
            "streams_identical": toks_s == ref,
            "drops": sum(e.last_swap["in_flight_running"]
                         + e.last_swap["in_flight_prefill"]
                         for e in fleet_s.engines
                         if e.last_swap is not None),
        },
        "arrival_trace": {"process": "poisson", "rate_per_s": rate,
                          "shared_prefix_tokens": sys_len},
    }
    if not on_tpu:
        out["note"] = ("tiny config with replicas time-slicing one "
                       "host — parity, zero-lost and hit-rate gates "
                       "are exact; wall-clock speedup measures router "
                       "overhead, not parallel scaling; TPU round "
                       "lands real numbers")
    return out


def _static_analysis_record():
    """Per-rule finding counts from paddle_tpu.analysis — the bench
    record carries the lint posture of the tree the numbers came from
    (a weak-scalar or host-sync regression shows up next to the MFU it
    distorted)."""
    try:
        from paddle_tpu.analysis import apply_baseline, run as run_analysis
        report = run_analysis()
        stale = apply_baseline(report)
    except Exception as exc:  # the record is telemetry, never a gate
        return {"error": f"{type(exc).__name__}: {exc}"}
    return {
        "rules": report.to_json()["rules"],
        "total_active": len(report.active),
        "total_suppressed": len(report.suppressed),
        "total_allowlisted": len(report.allowlisted),
        # PR-11 ratchet posture: findings the baseline absorbs (debt
        # still to burn down) and entries whose finding is gone (stale
        # — the ratchet demands their deletion)
        "total_baselined": len(report.baselined),
        "baseline_stale": len(stale),
    }


def main():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.llama import LlamaConfig

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    seq = 2048 if on_tpu else 128
    batch = 4 if on_tpu else 2
    if on_tpu:
        # flagship shape: head_dim=128 (Llama-2's), MXU-sized matmuls
        config = LlamaConfig(vocab_size=32000, hidden_size=2048,
                             intermediate_size=8192, num_hidden_layers=12,
                             num_attention_heads=16, num_key_value_heads=16,
                             max_position_embeddings=seq, dtype=jnp.bfloat16)
        # round-1 shape (head_dim=64), kept for cross-round comparability
        config_hd64 = LlamaConfig(vocab_size=32000, hidden_size=1024,
                                  intermediate_size=4096, num_hidden_layers=24,
                                  num_attention_heads=16,
                                  num_key_value_heads=16,
                                  max_position_embeddings=seq,
                                  dtype=jnp.bfloat16)
    else:
        from paddle_tpu.models.llama import llama_tiny
        config = llama_tiny(seq=seq)
        config_hd64 = None

    mfu, tok_s, dt, loss = run_config(config, batch, seq, dev)
    detail = {
        "tokens_per_sec_per_chip": round(tok_s, 1),
        "step_time_s": round(dt, 4),
        "device": str(getattr(dev, "device_kind", dev.platform)),
        "seq_len": seq, "batch": batch,
        "hidden": config.hidden_size, "layers": config.num_hidden_layers,
        "head_dim": config.head_dim,
        "loss": round(loss, 4),
    }
    if config_hd64 is not None:
        mfu64, tok_s64, dt64, _ = run_config(config_hd64, batch, seq, dev)
        detail["hd64_shape"] = {
            "mfu": round(float(mfu64), 4),
            "tokens_per_sec_per_chip": round(tok_s64, 1),
            "step_time_s": round(dt64, 4),
            "hidden": config_hd64.hidden_size,
            "layers": config_hd64.num_hidden_layers,
            "head_dim": config_hd64.head_dim,
        }

    if on_tpu:
        # North-star geometry (BASELINE.md): REAL Llama-2 7B / 13B layer
        # shapes. One v5e chip cannot hold the full models with AdamW
        # states (12 B/param), so these run as many true-geometry layers
        # as fit (measured: 7B fits L=4 at B=8, 13B L=2 at B=8; L+1 or
        # 2xB is RESOURCE_EXHAUSTED; the offload_attn remat policy fits
        # B=16 but host-offload traffic drops MFU to 0.49). vocab=8192
        # keeps the embedding from crowding out layers — per-layer MFU is
        # the quantity of interest. Per-chip MFU at these shapes is the
        # single-chip factor of the v5p-128 north-star target.
        for key, h, inter, heads, L7, b7, pol in (
                ("7b_shape", 4096, 11008, 32, 4, 8, "save_attn"),
                ("13b_layer", 5120, 13824, 40, 2, 8, "save_mlp")):
            cfg_ns = LlamaConfig(vocab_size=8192, hidden_size=h,
                                 intermediate_size=inter,
                                 num_hidden_layers=L7,
                                 num_attention_heads=heads,
                                 num_key_value_heads=heads,
                                 max_position_embeddings=seq,
                                 dtype=jnp.bfloat16)
            mfu_ns, tok_ns, dt_ns, _ = run_config(cfg_ns, b7, seq, dev,
                                                  policy=pol)
            detail[key] = {
                "mfu": round(float(mfu_ns), 4),
                "tokens_per_sec_per_chip": round(tok_ns, 1),
                "step_time_s": round(dt_ns, 4),
                "hidden": h, "intermediate": inter, "layers": L7,
                "batch": b7, "head_dim": 128,
            }

    # KV-cache greedy decode (whole continuation = one dispatch). ms/step is
    # bounded below by streaming all bf16 weights from HBM once per step
    # (weight_floor_ms); tok/s scales with batch at near-constant step time.
    decode = {}
    variants = [("flagship", config, False)] + (
        [("hd64", config_hd64, False)] if config_hd64 is not None else [])
    if on_tpu:
        # weight-only int8 (quantize_llama_int8): halves the weight stream
        # — decode lands BELOW the bf16 floor
        variants.append(("flagship_int8", config, True))
    for name, cfg, quant in variants:
        for b in (1, 8):
            mspt, tok_s_d, floor, mfloor = run_decode(cfg, b, dev,
                                                      quantize=quant)
            decode[f"{name}_b{b}"] = {
                "ms_per_step": round(mspt, 2),
                "tokens_per_sec": round(tok_s_d, 1),
                "weight_floor_ms": round(floor, 2),
                "measured_floor_ms": round(mfloor, 2),
                "x_of_floor": round(mspt / mfloor, 2),
            }
    if on_tpu:
        decode["measured_hbm_gbs"] = round(measured_hbm_bw(dev) / 1e9, 1)
        if config_hd64 is not None:
            decode["hd64_pair_stack_ab"] = decode_pair_stack_ab(
                dev, config_hd64)
            decode["hd64_block_sweep"] = decode_block_sweep(
                dev, config_hd64)
    detail["decode"] = decode

    # continuous-batching serving engine (paged KV cache) under a
    # Poisson arrival trace — runs on both backends
    detail["serve_continuous"] = bench_serve_continuous(dev, config, on_tpu)

    # preemption-tolerant training (PR 13): checkpoint-overlap cost,
    # resume-to-parity, live weight-swap drain — runs on both backends
    detail["preempt_resume"] = bench_preempt_resume(dev, config, on_tpu)

    # overload-hardened serving (PR 14): deterministic shedding, goodput
    # under a 2x burst, admission+journal cost — runs on both backends
    detail["serve_overload"] = bench_serve_overload(dev, config, on_tpu)

    # prefix-cached serving + int8 paged KV (PR 16): TTFT under shared
    # system prompts, capacity at fixed pool bytes — both backends
    detail["serve_prefix_cache"] = bench_serve_prefix_cache(
        dev, config, on_tpu)
    detail["serve_kv_int8"] = bench_serve_kv_int8(dev, config, on_tpu)

    # speculative decoding (PR 18): draft model + batched paged
    # verification vs the sequential engine on the same trace — both
    # backends; parity gate (streams bitwise-identical) always enforced
    detail["serve_speculative"] = bench_serve_speculative(
        dev, config, on_tpu)

    # tensor-parallel serving (PR 19): the engine inside the mp ring
    # plans, sharded KV pools, bitwise parity vs mp=1 — both backends
    # (off-TPU needs the virtual CPU mesh: XLA_FLAGS device count >= 2)
    detail["serve_tp"] = bench_serve_tp(dev, config, on_tpu)

    # multi-replica fleet serving (PR 20): prefix-affinity router over
    # N engines, chaos kill + journal migration, rolling weight swap
    detail["serve_fleet"] = bench_serve_fleet(dev, config, on_tpu)

    # fleet observability (PR 15): attributed FleetMonitor cost + loss
    # parity monitored vs bare — runs on both backends
    detail["fleet_observability"] = bench_fleet_observability(
        dev, config, on_tpu)

    # kernel-level performance attribution (PR 17): always-on roofline
    # ledger parity + attributed cost, measured-mode component
    # itemization — runs on both backends
    detail["ledger_roofline"] = bench_ledger_roofline(dev, config, on_tpu)

    if on_tpu:
        detail["step_ledger_flagship"] = bench_step_ledger(
            dev, config, batch, seq, dt)

    if on_tpu:
        # long-context: streaming-KV Pallas kernels (whole-KV residency
        # would exceed VMEM ~6k tokens earlier); causal, head_dim=128.
        # Timed via profiler DEVICE events: wall-clock over the axon tunnel
        # carries ~5-12 ms dispatch overhead per call, which buried these
        # kernels under ~10x noise in the round-2 numbers (0.082 "eff" for
        # a kernel actually running at 0.60).
        import jax as _jax
        from paddle_tpu.ops import flash_attention as _fa
        long_seq = {}
        for s_long in (16384, 32768, 131072):
            # 131072 halves bh: 8 heads of q/k/v/do + f32 grads at 128k
            # rows would not leave room for the dq streaming partials
            bh, d_ = (8, 128) if s_long <= 32768 else (4, 128)
            rng2 = np.random.RandomState(1)
            q = jnp.asarray(rng2.randn(bh, s_long, d_).astype(np.float32),
                            dtype=jnp.bfloat16)
            k = jnp.asarray(rng2.randn(bh, s_long, d_).astype(np.float32),
                            dtype=jnp.bfloat16)
            v = jnp.asarray(rng2.randn(bh, s_long, d_).astype(np.float32),
                            dtype=jnp.bfloat16)

            def fwd(q, k, v):
                return _fa._flash_fwd(q, k, v, True, 1 / 11.3, 1024, 1024)[0]

            def bwd(q, k, v):
                # grad w.r.t. ALL of q/k/v: grad-of-q-only would DCE the
                # dK/dV streaming kernel out of the program entirely
                loss = lambda q, k, v: (_fa._flash_attention(
                    q, k, v, True, 1 / 11.3, 1024, 1024)
                    .astype(jnp.float32) ** 2).sum()
                return _jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

            ms_f = device_time_ms(fwd, (q, k, v), f"lsfwd{s_long}")
            ms_b = device_time_ms(bwd, (q, k, v), f"lsbwd{s_long}")
            fl = 2 * 2 * bh * s_long * s_long * d_ / 2  # causal half
            # static schedule record for the r7 fused flat backward: which
            # path ran, blocks, and the fetch-once contract (r05 split-
            # kernel baseline for comparison: bwd_eff=0.599 at S=32768)
            sched = _fa.dense_bwd_schedule_stats(
                bh, s_long, s_long, d_, jnp.bfloat16, True, 1024, 1024)
            long_seq[f"S{s_long}"] = {
                "ms": round(ms_f, 1),
                "attn_eff": round(fl / (ms_f / 1e3) / peak_flops(dev), 3),
                "bwd_ms": round(ms_b, 1),
                # bwd does ~2.5x the fwd FLOPs (5 matmuls vs 2)
                "bwd_eff": round(2.5 * fl / (ms_b / 1e3) / peak_flops(dev), 3),
                "bwd_schedule": {k: v for k, v in sched.items()
                                 if k not in ("bh", "seq_q", "seq_k",
                                              "head_dim", "mode")},
            }
        long_seq["bwd_baseline_r05"] = {
            "bwd_eff_s32768": 0.599,
            "note": "split dkv+dq kernel pair (each block fetched twice, "
                    "7 matmuls/pair) before the r7 fused flat rewrite",
        }
        detail["long_seq_flash_fwd"] = long_seq

        # context-parallel strategy compare at 32k, sep=4: per-chip COMPUTE
        # proxy on one chip. Ring = the worst (last, causal) rank's n_sep
        # block-flash calls + lse merges; Ulysses = one full-S flash over
        # H/n_sep heads. Comm cost differs (ring overlaps ppermute with
        # block compute; Ulysses pays two all_to_alls) and needs a real
        # multi-chip slice to measure.
        from paddle_tpu.ops.flash_attention import flash_block_fwd
        from paddle_tpu.parallel.ring_attention import _merge_partials
        s_cp, n_sep, h_cp, d_cp = 32768, 4, 8, 128
        s_loc = s_cp // n_sep
        rng3 = np.random.RandomState(2)
        kr = jnp.asarray(rng3.randn(h_cp, s_cp, d_cp).astype(np.float32),
                         dtype=jnp.bfloat16)
        vr = jnp.asarray(rng3.randn(h_cp, s_cp, d_cp).astype(np.float32),
                         dtype=jnp.bfloat16)
        qr = jnp.asarray(rng3.randn(h_cp, s_loc, d_cp).astype(np.float32),
                         dtype=jnp.bfloat16)
        sc_cp = 1 / 11.3

        def cpring(q, k, v):
            o, lse = flash_block_fwd(q, k[:, -s_loc:], v[:, -s_loc:],
                                     causal=True, scale=sc_cp)
            o = o.astype(jnp.float32)
            for i in range(n_sep - 1):
                blk = slice(i * s_loc, (i + 1) * s_loc)
                ob, lb = flash_block_fwd(q, k[:, blk], v[:, blk],
                                         causal=False, scale=sc_cp)
                o, lse = _merge_partials(o, lse, ob, lb)
            return o

        qu = jnp.asarray(
            rng3.randn(h_cp // n_sep, s_cp, d_cp).astype(np.float32),
            dtype=jnp.bfloat16)

        def cpuly(q, k, v):
            return _fa._flash_fwd(q, k, v, True, sc_cp, 1024, 1024)[0]

        ms_ring = device_time_ms(cpring, (qr, kr, vr), "cpring")
        ms_uly = device_time_ms(
            cpuly, (qu, kr[:h_cp // n_sep], vr[:h_cp // n_sep]), "cpuly")
        detail["cp_compare_s32k_sep4"] = {
            "ring_worst_rank_ms": round(ms_ring, 2),
            "ulysses_ms": round(ms_uly, 2),
            "note": "compute proxy on one chip; ring overlaps ppermute "
                    "with block compute, Ulysses adds 2 all_to_alls. Real "
                    "sep=4 collective rung: cp_compare_sep4 in the "
                    "multichip dryrun (MULTICHIP json tail)",
        }

        # packed varlen attention (kernel-backed flash on the packed
        # layout, scalar-prefetched live-tile scheduling): a 16-sequence
        # 16k-token causal pack, fwd + full bwd
        from paddle_tpu.ops.flash_varlen import flash_varlen_attention
        vl_lens = [2048, 512, 1024, 3072, 256, 896, 1536, 2048,
                   128, 512, 768, 1024, 640, 384, 512, 640]
        vl_total, vl_max = sum(vl_lens), max(vl_lens)
        cu_vl = jnp.asarray(np.concatenate(
            [[0], np.cumsum(vl_lens)]).astype(np.int32))
        rng4 = np.random.RandomState(3)
        qv = jnp.asarray(rng4.randn(vl_total, 8, 128).astype(np.float32),
                         dtype=jnp.bfloat16)
        kv = jnp.asarray(rng4.randn(vl_total, 8, 128).astype(np.float32),
                         dtype=jnp.bfloat16)
        vv = jnp.asarray(rng4.randn(vl_total, 8, 128).astype(np.float32),
                         dtype=jnp.bfloat16)

        def vlfwd(q, k, v):
            return flash_varlen_attention(q, k, v, cu_vl, cu_vl, 1 / 11.3,
                                          True, self_attn=True,
                                          max_seqlen=vl_max)

        def vlbwd(q, k, v):
            loss = lambda *a: (flash_varlen_attention(
                *a, cu_vl, cu_vl, 1 / 11.3, True, self_attn=True,
                max_seqlen=vl_max).astype(jnp.float32) ** 2).sum()
            return _jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        ms_vf = device_time_ms(vlfwd, (qv, kv, vv), "pvfwd")
        ms_vb = device_time_ms(vlbwd, (qv, kv, vv), "pvbwd")
        fl_vl = sum(2 * 2 * 8 * L * L * 128 / 2 for L in vl_lens)
        detail["moe"] = bench_moe(dev)
        detail["moe_dropless"] = bench_moe_dropless(dev)
        detail["moe_skew_sweep"] = bench_moe_skew(dev)
        from paddle_tpu.ops.flash_varlen import varlen_schedule_stats
        vl_sched = varlen_schedule_stats(
            np.asarray(cu_vl), np.asarray(cu_vl), 8, 128,
            causal=True, self_attn=True, dtype=jnp.bfloat16,
            max_seqlen=vl_max)
        detail["packed_varlen_16seq_16k"] = {
            "fwd_ms": round(ms_vf, 2), "bwd_ms": round(ms_vb, 2),
            # round-5 record before the fused flat-schedule backward
            # landed (rectangular (H, n_k, n_q) dKV + (H, n_q, n_k) dQ
            # grids, dead tiles predicated but still stepped).
            "bwd_ms_r5_rect_baseline": 5.68,
            "varlen_fwd_eff": round(fl_vl / (ms_vf / 1e3)
                                    / peak_flops(dev), 3),
            # bwd recomputes p and runs 5 matmuls vs the fwd's 2:
            # useful-FLOP convention is 2.5x the fwd count.
            "varlen_bwd_eff": round(2.5 * fl_vl / (ms_vb / 1e3)
                                    / peak_flops(dev), 3),
            "schedule": vl_sched,
            # one-seq == dense layout through the SAME kernels: the
            # measured ceiling the 16-seq pack should be judged against
            "ceiling_ablation": varlen_ceiling_ablation(
                dev, long_seq["S16384"]["ms"],
                long_seq["S16384"]["bwd_ms"]),
        }

    if not on_tpu:
        # varlen-efficiency ceiling (ROADMAP VERDICT item 5) at an
        # interpret-affordable S: the dense flash fwd/bwd reference at
        # the SAME shape runs through the same interpret path, so the
        # schedule-overhead ratios are like-for-like even though the
        # absolute ms (and thus the eff_* fields, priced against the
        # nominal CPU peak) carry no hardware meaning off-TPU.
        import jax as _jax
        from paddle_tpu.ops import flash_attention as _fa
        s_vc = 512
        rngvc = np.random.RandomState(6)
        mkd = lambda: jnp.asarray(
            rngvc.randn(8, s_vc, 128).astype(np.float32), jnp.bfloat16)
        qd, kd, vd = mkd(), mkd(), mkd()

        def vcdfwd(q, k, v):
            return _fa._flash_fwd(q, k, v, True, 1 / 11.3, 256, 256)[0]

        def vcdbwd(q, k, v):
            loss = lambda q, k, v: (_fa._flash_attention(
                q, k, v, True, 1 / 11.3, 256, 256)
                .astype(jnp.float32) ** 2).sum()
            return _jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

        ms_vcf = device_time_ms(vcdfwd, (qd, kd, vd), "vcdf", reps=1)
        ms_vcb = device_time_ms(vcdbwd, (qd, kd, vd), "vcdb", reps=1)
        vc = varlen_ceiling_ablation(dev, ms_vcf, ms_vcb, S=s_vc)
        vc["note"] = ("interpret mode on CPU at S=512 — the "
                      "schedule_overhead_* ratios vs dense flash are the "
                      "meaningful fields; eff ceilings need the TPU "
                      "round at S=16384")
        detail["varlen_ceiling_ablation"] = vc

    detail["static_analysis"] = _static_analysis_record()

    # The driver records a BOUNDED TAIL of stdout: round 4's single giant
    # JSON line was truncated mid-object and the official record had
    # parsed:null. Emit the full detail FIRST (plus a sidecar file), then
    # a SHORT final summary line — one number per config-ladder rung — so
    # whatever capture window the driver uses, the last line parses.
    full = {
        "metric": "llama_train_mfu",
        "value": round(float(mfu), 4),
        "unit": "MFU",
        "vs_baseline": round(float(mfu) / 0.45, 4),
        "detail": detail,
    }
    import os
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_DETAIL.json"), "w") as fh:
            json.dump(full, fh, indent=1)
    except OSError:
        pass
    print(json.dumps(full))
    # ONE mapping from the detail dict to the flat rung record — shared
    # with the regression ratchet (python -m paddle_tpu.observability
    # .regress --check) so the bench and the baseline can never disagree
    # about what a rung is
    from paddle_tpu.observability.regress import rungs_from_bench_detail
    rungs = rungs_from_bench_detail(full)
    rungs.pop("llama_train_mfu", None)  # already the summary line's value
    print(json.dumps({
        "metric": "llama_train_mfu",
        "value": round(float(mfu), 4),
        "unit": "MFU",
        "vs_baseline": round(float(mfu) / 0.45, 4),
        "rungs": rungs,
        "detail_file": "BENCH_DETAIL.json",
    }))


if __name__ == "__main__":
    main()
