"""Comm–compute overlap bench: overlapped vs blocking on the virtual mesh.

Times the three overlap paths against their blocking twins on the 8-device
virtual CPU mesh (same harness as the multichip dryrun, whose output this
extends — see __graft_entry__.dryrun_multichip):

- TP: ring collective matmuls (parallel/collective_matmul.py) vs the fused
  psum/all-gather islands.
- DP: bucketed grad psum (distributed/sharding_utils.py) vs per-parameter
  psums (the unfused sync the reference's EagerReducer replaces).
- PP: the async-p2p 1F1B schedule (parallel/pipeline.py, overlap_p2p) vs the
  blocking schedule.

Caveat: the host-CPU collective emulation serializes every hop at a
rendezvous, so the latency hiding that motivates the ring/async variants
cannot materialize here — wall-clock on this mesh measures op-count overhead
only. Bucketed DP sync wins on op count and shows a real speedup; the TP
ring and PP async schedules show their overhead (the TPU win comes from
overlap the emulation can't express) and are asserted ≤ blocking only on a
real TPU backend. Run: `python benchmarks/overlap_bench.py`.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_DEV = 8
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={N_DEV}").strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402


def _timeit(f, *args, reps=5, inner=3):
    jax.block_until_ready(f(*args))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(inner):
            o = f(*args)
        jax.block_until_ready(o)
        best = min(best, (time.perf_counter() - t0) / inner)
    return best * 1e3


def bench_tp(cpus, mp=4, t=256, k=1024, out=1024):
    from paddle_tpu._compat import shard_map
    from paddle_tpu.parallel import collective_matmul as cm

    mesh = Mesh(np.array(cpus[:mp]), ("mp",))
    rng = np.random.RandomState(0)

    def island(kern, in_specs):
        return jax.jit(shard_map(
            lambda a, b: kern(a, b, mp, "mp"), mesh=mesh, in_specs=in_specs,
            out_specs=P(), axis_names=frozenset(["mp"]), check_vma=False))

    x = jax.device_put(jnp.asarray(rng.randn(t, k), jnp.float32),
                       NamedSharding(mesh, P(None, "mp")))
    w = jax.device_put(jnp.asarray(rng.randn(k, out), jnp.float32),
                       NamedSharding(mesh, P("mp", None)))
    row_specs = (P(None, "mp"), P("mp", None))
    row_ring = _timeit(island(cm.ring_allreduce_matmul, row_specs), x, w)
    row_blk = _timeit(island(cm.blocking_allreduce_matmul, row_specs), x, w)

    x2 = jnp.asarray(rng.randn(t, k), jnp.float32)
    w2 = jax.device_put(jnp.asarray(rng.randn(k, out), jnp.float32),
                        NamedSharding(mesh, P(None, "mp")))
    col_specs = (P(), P(None, "mp"))
    col_ring = _timeit(island(cm.ring_allgather_matmul, col_specs), x2, w2)
    col_blk = _timeit(island(cm.blocking_allgather_matmul, col_specs), x2, w2)
    return dict(row_ring=row_ring, row_blk=row_blk,
                col_ring=col_ring, col_blk=col_blk)


def bench_dp(cpus, dp=8, width=256, depth=8, batch=64, cap_mb=0.5):
    """End-to-end dp train step: blocking GSPMD sync (grads reduced at the
    step-end barrier the partitioner schedules) vs the explicit bucketed
    island (per-bucket variadic psums issued as backward produces them)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import AdamW

    mesh = Mesh(np.array(cpus[:dp]).reshape(dp, 1), ("dp", "mp"))
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(batch, width).astype(np.float32))
    y = paddle.to_tensor(rng.randn(batch, 16).astype(np.float32))

    def loss_fn(o, l):
        return paddle.mean((o - l) ** 2)

    res = {}
    for mode in (None, "bucketed"):
        paddle.set_device("cpu")
        paddle.seed(7)
        layers = []
        for _ in range(depth):
            layers += [nn.Linear(width, width), nn.GELU()]
        model = nn.Sequential(*layers, nn.Linear(width, 16))
        opt = AdamW(learning_rate=1e-2,
                    parameters=model.parameters(), weight_decay=0.01)
        step = TrainStep(model, loss_fn, opt, mesh=mesh, batch_spec=P("dp"),
                         grad_sync=mode, grad_bucket_mb=cap_mb)
        loss = step(x, labels=y)  # compile + warm
        res[mode or "blocking"] = _timeit(
            lambda: step(x, labels=y), reps=3, inner=5)
        res[(mode or "blocking") + "_loss"] = float(loss)
        if mode == "bucketed":
            res["n_buckets"] = len(step.grad_buckets)
    return res


def bench_tp_chunks(cpus, mps=(4, 8), chunks=(1, 2, 4)):
    """mp=4/8 chunk sweep of the ring all-reduce matmul (delegates to
    ring_bench.chunk_sweep): blocking vs unchunked ring vs chunked ring,
    with per-hop comm_span bytes snapshotted from the trace counters."""
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "ring_bench.py")
    spec = importlib.util.spec_from_file_location("ring_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return {mp: mod.chunk_sweep(cpus, mp=mp, chunks=chunks) for mp in mps}


def bench_stage3_prefetch(cpus, dp=2, sh=4, width=256, depth=6, batch=64,
                          bucket_mb=0.05):
    """End-to-end ZeRO-3 train step: GSPMD's as-consumed param all-gathers
    vs the bucketed one-ahead prefetch (sharding_utils.prefetch_param_
    gathers). Loss must be bit-identical — prefetch is pure data movement."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed.sharding import group_sharded_parallel
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import AdamW

    mesh = Mesh(np.array(cpus[:dp * sh]).reshape(dp, sh), ("dp", "sharding"))
    rng = np.random.RandomState(5)
    x = paddle.to_tensor(rng.randn(batch, width).astype(np.float32))
    y = paddle.to_tensor(rng.randn(batch, 16).astype(np.float32))

    res = {}
    for pf in (False, True):
        paddle.set_device("cpu")
        paddle.seed(7)
        layers = []
        for _ in range(depth):
            layers += [nn.Linear(width, width), nn.GELU()]
        model = nn.Sequential(*layers, nn.Linear(width, 16))
        opt = AdamW(learning_rate=1e-2, parameters=model.parameters(),
                    weight_decay=0.01)
        model, opt, _ = group_sharded_parallel(model, opt, "p_g_os")
        obs.reset_counters()
        step = TrainStep(model,
                         loss_fn=lambda o, l: paddle.mean((o - l) ** 2),
                         optimizer=opt, mesh=mesh,
                         batch_spec=P(("dp", "sharding")),
                         param_prefetch=pf, param_bucket_mb=bucket_mb)
        loss = step(x, labels=y)  # compile + warm (trace fills counters)
        key = "prefetch" if pf else "blocking"
        res[key] = _timeit(lambda: step(x, labels=y), reps=3, inner=5)
        res[key + "_loss"] = float(loss)
        if pf:
            res["n_buckets"] = len(step.param_gather_buckets or [])
            res["bucket_counters"] = {
                k: v for k, v in obs.counters().items()
                if k.startswith("param_gather.")}
    return res


def bench_pp(cpus, S=2, M=8, H=256):
    from paddle_tpu._compat import shard_map
    from paddle_tpu.parallel.pipeline import (last_stage_value, microbatch,
                                              pipeline_apply,
                                              stack_stage_params)

    mesh = Mesh(np.array(cpus[:S]), ("pp",))
    rng = np.random.RandomState(2)
    stacked = stack_stage_params(
        [{"w": jnp.asarray(rng.randn(H, H), jnp.float32) * 0.1}
         for _ in range(S)])
    x_mb = microbatch(jnp.asarray(rng.randn(M * 4, H), jnp.float32), M)

    def build(ovl):
        pipe = pipeline_apply(lambda p, h: jnp.tanh(h @ p["w"]), S, M, "pp",
                              remat=False, overlap_p2p=ovl)

        def island(params, xm):
            return last_stage_value(jnp.sum(pipe(params, xm) ** 2), S, "pp")

        return jax.jit(shard_map(
            island, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
            axis_names=frozenset(["pp"]), check_vma=False))

    t_blk = _timeit(build(False), stacked, x_mb)
    t_ovl = _timeit(build(True), stacked, x_mb)
    return dict(blocking=t_blk, overlapped=t_ovl)


def bench_telemetry(cpus, dp=8, width=256, depth=4, batch=64, cap_mb=0.25,
                    steps=8, logdir=None):
    """Telemetry acceptance run: a bucketed-dp train step with telemetry on
    emits a JSONL step log carrying step_time_ms / tokens_per_sec / MFU plus
    a summary record with the per-bucket grad-sync bytes and MoE routing
    stats (drops / load imbalance from a skewed router)."""
    import tempfile

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import observability as obs
    from paddle_tpu.distributed import sharding_utils
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.parallel import moe

    logdir = logdir or tempfile.mkdtemp(prefix="paddle_tpu_telemetry_")
    obs.reset_counters()
    mesh = Mesh(np.array(cpus[:dp]).reshape(dp, 1), ("dp", "mp"))
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(batch, width).astype(np.float32))
    y = paddle.to_tensor(rng.randn(batch, 16).astype(np.float32))

    paddle.set_device("cpu")
    paddle.seed(7)
    layers = []
    for _ in range(depth):
        layers += [nn.Linear(width, width), nn.GELU()]
    model = nn.Sequential(*layers, nn.Linear(width, 16))
    opt = AdamW(learning_rate=1e-2, parameters=model.parameters(),
                weight_decay=0.01)
    step = TrainStep(model, loss_fn=lambda o, l: paddle.mean((o - l) ** 2),
                     optimizer=opt, mesh=mesh, batch_spec=P("dp"),
                     grad_sync="bucketed", grad_bucket_mb=cap_mb,
                     telemetry=True, telemetry_dir=logdir)
    for _ in range(steps):
        step(x, labels=y)

    # MoE routing stats from a deliberately skewed router (expert 0 favored
    # beyond capacity -> real drops and imbalance), on the same mesh
    T, D, E, k = 256, 32, 4, 2
    tok = jnp.asarray(rng.randn(T, D), jnp.float32)
    logits = jnp.asarray(rng.randn(T, E), jnp.float32) + \
        jnp.array([4.0] + [0.0] * (E - 1), jnp.float32)
    ew1 = jnp.asarray(rng.randn(E, D, 64), jnp.float32) * 0.02
    ew2 = jnp.asarray(rng.randn(E, 64, D), jnp.float32) * 0.02

    def expert_fn(params, t_):
        a, b = params
        return jax.nn.gelu(t_ @ a) @ b

    _, _, moe_stats = jax.jit(lambda t_, l_: moe.moe_dispatch_combine(
        t_, l_, expert_fn, (ew1, ew2), E, k=k, strict_capacity=True,
        return_stats=True))(tok, logits)

    m = step.telemetry
    shapes = {kk: (tuple(step.params[kk].shape), step.params[kk].dtype.itemsize)
              for kk in step.trainable_keys}
    bucket_sizes = sharding_utils.bucket_bytes(shapes, step.grad_buckets)
    summary_rec = dict(m.summary())
    summary_rec["record"] = "summary"
    summary_rec["grad_sync_bucket_bytes"] = bucket_sizes
    summary_rec.update({kk: float(v) for kk, v in moe_stats.items()})
    for e in m._exporters:
        e.write(summary_rec)
    m.close()
    obs.set_active(None)

    path = os.path.join(
        logdir, f"steps_rank{obs.process_rank():03d}.jsonl")
    records = obs.load_jsonl(path)
    step_recs = [r for r in records if r.get("record") != "summary"]
    timed = [r for r in step_recs if r.get("step_time_ms")]
    return dict(logdir=logdir, path=path, n_records=len(records),
                n_steps=len(step_recs),
                step_time_ms=(min(r["step_time_ms"] for r in timed)
                              if timed else None),
                tokens_per_sec=(max(r["tokens_per_sec"] for r in timed
                                    if r.get("tokens_per_sec")) or None
                                if timed else None),
                mfu=next((r["mfu"] for r in reversed(step_recs)
                          if r.get("mfu") is not None), None),
                grad_sync_bucket_bytes=bucket_sizes,
                moe_dropped_tokens=float(moe_stats["moe_dropped_tokens"]),
                moe_load_imbalance=float(moe_stats["moe_load_imbalance"]))


def bench_overhead(cpus, dp=8, width=256, depth=4, batch=64, cap_mb=0.25):
    """Telemetry-on vs telemetry-off step time on the CPU mesh, plus the
    serve-side twin: request tracing + SLO histograms + flight recorder on
    vs off in the engine dryrun — the acceptance bound is <2% overhead on
    both (the collectors are interval timing + in-memory appends; nothing
    touches the device, and tokens must be bit-identical)."""
    import tempfile

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import observability as obs
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import AdamW

    mesh = Mesh(np.array(cpus[:dp]).reshape(dp, 1), ("dp", "mp"))
    rng = np.random.RandomState(4)
    x = paddle.to_tensor(rng.randn(batch, width).astype(np.float32))
    y = paddle.to_tensor(rng.randn(batch, 16).astype(np.float32))

    res = {}
    for on in (False, True):
        paddle.set_device("cpu")
        paddle.seed(7)
        layers = []
        for _ in range(depth):
            layers += [nn.Linear(width, width), nn.GELU()]
        model = nn.Sequential(*layers, nn.Linear(width, 16))
        opt = AdamW(learning_rate=1e-2, parameters=model.parameters(),
                    weight_decay=0.01)
        step = TrainStep(model,
                         loss_fn=lambda o, l: paddle.mean((o - l) ** 2),
                         optimizer=opt, mesh=mesh, batch_spec=P("dp"),
                         grad_sync="bucketed", grad_bucket_mb=cap_mb,
                         telemetry=on,
                         telemetry_dir=(tempfile.mkdtemp() if on else None))
        step(x, labels=y)  # compile + warm
        res["on" if on else "off"] = _timeit(
            lambda: step(x, labels=y), reps=3, inner=10)
        if on and step.telemetry is not None:
            step.telemetry.close()
            obs.set_active(None)
    res["overhead_pct"] = (res["on"] / res["off"] - 1.0) * 100.0
    res.update(bench_serve_overhead())
    return res


class _TimedProxy:
    """Attribute proxy that wall-times every method call on the target.

    The timing clamp itself (two ``perf_counter`` reads + an attribute
    hop per call) is billed to the target, so the attributed total is an
    UPPER bound on what the unwrapped instrumentation costs."""

    def __init__(self, target, counter):
        self._target = target
        self._counter = counter  # single-element list, shared across proxies

    def __getattr__(self, name):
        attr = getattr(self._target, name)
        if not callable(attr):
            return attr
        counter = self._counter

        def timed(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return attr(*args, **kwargs)
            finally:
                counter[0] += time.perf_counter() - t0
        # cache the bound wrapper so repeat calls skip __getattr__ —
        # the clamp should time the instrumentation, not itself
        object.__setattr__(self, name, timed)
        return timed


def bench_serve_overhead(reps=3):
    """Request-tracing + histogram + flight-recorder overhead in the serve
    dryrun. Two measurements:

    - **attributed** (the <2% gate): wall time spent inside observability
      calls during a traced run, clamped per call via ``_TimedProxy``
      (conservative — the clamp bills its own cost to the layers), as a
      share of the run's wall. Stable to well under a percent even on a
      noisy 1-vCPU host because it sums µs-scale intervals instead of
      differencing two ~100ms walls.
    - **A/B tokens/s** (reported for reference): traced vs untraced runs
      of the same deterministic arrival trace. Identical schedules, so
      generated tokens must match bit for bit; on a shared host the
      ratio itself carries several percent of scheduler noise.
    """
    import tempfile

    from paddle_tpu.inference import InferenceEngine, Request, ServeConfig
    from paddle_tpu.models.llama import init_llama_params, llama_tiny
    from paddle_tpu.ops import _common

    # two layers, hidden 128: still a toy, but the per-iteration device
    # work is no longer degenerate next to the fixed ~25us of host
    # instrumentation (the serve dryrun's 1-layer hidden-64 config exists
    # to make the FUNCTIONAL checks fast, not to proxy a real step time)
    cfg = llama_tiny(vocab=96, hidden=128, layers=2, heads=4, kv_heads=2,
                     seq=256)
    params = init_llama_params(cfg, seed=3)
    serve = ServeConfig(block_size=128, num_blocks=17, max_batch=4,
                        prefill_chunk=32, max_seq_len=256)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 96, size=n).tolist()
               for n in (7, 40, 130, 25, 60, 90)]

    def one(on, attribute=False):
        # "on" also enables the PR-14 robustness layers (append-only
        # journal + admission control) so the attributed share covers
        # the FULL instrumented surface, not just observability
        jdir = tempfile.mkdtemp() if on else None
        eng = InferenceEngine(
            params, cfg, serve, trace_requests=on, flight_recorder=on,
            journal=(os.path.join(jdir, "engine.jsonl") if on else None))
        counter = [0.0]
        if attribute:
            eng.tracer = _TimedProxy(eng.tracer, counter)
            eng.recorder = _TimedProxy(eng.recorder, counter)
            eng.slo = {k: _TimedProxy(h, counter)
                       for k, h in eng.slo.items()}
            eng._journal = _TimedProxy(eng._journal, counter)
            eng.admission = _TimedProxy(eng.admission, counter)
        reqs = [Request(p, max_new_tokens=48, arrival=float(i))
                for i, p in enumerate(prompts)]
        t0 = time.perf_counter()
        st = eng.run(reqs, deterministic=True)
        wall = time.perf_counter() - t0
        toks = {s.req.request_id: list(s.generated) for s in eng.finished}
        return st["generated_tokens"] / wall, toks, counter[0] / wall

    prev = _common._FORCE_INTERPRET
    _common.set_interpret(True)
    try:
        one(False)  # compile + warm outside the timed reps
        attributed, offs, ons = [], [], []
        toks_off = toks_on = None
        for _ in range(reps):
            tps, toks_off, _ = one(False)
            offs.append(tps)
            tps, toks_on, _ = one(True)
            ons.append(tps)
            _, _, share = one(True, attribute=True)
            attributed.append(share)
    finally:
        _common.set_interpret(prev)
    return dict(serve_off_tps=max(offs), serve_on_tps=max(ons),
                serve_overhead_pct=max(attributed) * 100.0,
                serve_ab_overhead_pct=(max(offs) / max(ons) - 1.0) * 100.0,
                serve_tokens_identical=toks_on == toks_off)


def run(cpus=None, prefix="overlap_bench"):
    if cpus is None:
        cpus = jax.devices("cpu")
    assert len(cpus) >= N_DEV, (len(cpus), N_DEV)
    tp = bench_tp(cpus)
    chunk = bench_tp_chunks(cpus)
    dp = bench_dp(cpus)
    s3 = bench_stage3_prefetch(cpus)
    pp = bench_pp(cpus)
    tel = bench_telemetry(cpus)
    ovh = bench_overhead(cpus)
    print(f"{prefix}({N_DEV}): tp mp=4 row ring {tp['row_ring']:.1f}ms vs "
          f"fused {tp['row_blk']:.1f}ms, col ring {tp['col_ring']:.1f}ms vs "
          f"fused {tp['col_blk']:.1f}ms (virtual-cpu serializes hops; "
          f"overlap needs real ICI)")
    verdict = "OK" if dp["bucketed"] <= dp["blocking"] else "SLOWER"
    print(f"{prefix}({N_DEV}): dp=8 e2e step: bucketed-overlap "
          f"({dp['n_buckets']} fused psums) {dp['bucketed']:.1f}ms vs "
          f"blocking GSPMD {dp['blocking']:.1f}ms, loss "
          f"{dp['bucketed_loss']:.6f}=={dp['blocking_loss']:.6f} "
          f"overlapped<=blocking: {verdict}")
    print(f"{prefix}({N_DEV}): pp=2 1F1B async-p2p {pp['overlapped']:.1f}ms "
          f"vs blocking {pp['blocking']:.1f}ms (+1 skew tick on emulation; "
          f"transfer hides behind compute on real ICI)")
    mfu = tel["mfu"]
    print(f"{prefix}({N_DEV}): telemetry JSONL {tel['path']}: "
          f"{tel['n_records']} records, step best "
          f"{tel['step_time_ms']:.2f}ms, {tel['tokens_per_sec']:.0f} tok/s, "
          f"mfu {mfu:.2e}" + (" (cpu-nominal peak)" if mfu else "") +
          f", buckets {tel['grad_sync_bucket_bytes']} B, moe dropped "
          f"{tel['moe_dropped_tokens']:.0f} imbalance "
          f"{tel['moe_load_imbalance']:.2f}")
    verdict2 = "OK" if ovh["overhead_pct"] < 2.0 else "OVER"
    print(f"{prefix}({N_DEV}): telemetry overhead: on "
          f"{ovh['on']:.2f}ms vs off {ovh['off']:.2f}ms = "
          f"{ovh['overhead_pct']:+.2f}% (<2%: {verdict2})")
    v_tr = "OK" if ovh["serve_overhead_pct"] < 2.0 else "OVER"
    print(f"{prefix}({N_DEV}): serve tracing overhead: traced "
          f"{ovh['serve_on_tps']:.1f} tok/s vs untraced "
          f"{ovh['serve_off_tps']:.1f} tok/s = "
          f"{ovh['serve_overhead_pct']:+.2f}% (<2%: {v_tr}), tokens "
          f"identical: {ovh['serve_tokens_identical']}")
    for mp, sweep in chunk.items():
        parts = []
        for nc, rec in sweep["sweep"].items():
            bw = "bitwise" if rec["bitwise_vs_unchunked"] else "DIVERGED"
            parts.append(f"c{nc} {rec['ms']:.1f}ms[{bw}]")
        best = min(r["ms"] for r in sweep["sweep"].values())
        v = ("OK" if best <= sweep["blocking_ms"] else
             "SLOWER (virtual-cpu serializes hops; chunking only adds ops "
             "here — the overlap win needs real ICI)")
        print(f"{prefix}({N_DEV}): tp mp={mp} chunk sweep: blocking "
              f"{sweep['blocking_ms']:.1f}ms vs ring " + ", ".join(parts) +
              f" chunked<=blocking: {v}")
    v3 = ("OK" if s3["prefetch"] <= s3["blocking"] else
          "SLOWER (gathers already as-consumed on the emulated mesh)")
    print(f"{prefix}({N_DEV}): zero-3 sharding=4 step: bucketed prefetch "
          f"({s3['n_buckets']} param-gather buckets) {s3['prefetch']:.1f}ms "
          f"vs as-consumed {s3['blocking']:.1f}ms, loss "
          f"{s3['prefetch_loss']:.6f}=={s3['blocking_loss']:.6f} "
          f"(bitwise: {s3['prefetch_loss'] == s3['blocking_loss']}) "
          f"prefetch<=blocking: {v3}")
    # persist the chunk-sweep + prefetch attribution next to the telemetry
    # step log: one JSONL record carrying the per-hop and per-bucket
    # comm_span bytes the dryrun archives
    from paddle_tpu import observability as obs
    rec_path = os.path.join(tel["logdir"], "overlap_rings.jsonl")
    writer = obs.JsonlWriter(rec_path)
    writer.write(dict(
        record="ring_chunk_sweep",
        per_mp={str(mp): dict(
            blocking_ms=sweep["blocking_ms"],
            sweep={str(nc): dict(ms=rec["ms"],
                                 bitwise=rec["bitwise_vs_unchunked"],
                                 hop_counters=rec["hop_counters"])
                   for nc, rec in sweep["sweep"].items()})
            for mp, sweep in chunk.items()},
        stage3_prefetch=dict(
            prefetch_ms=s3["prefetch"], blocking_ms=s3["blocking"],
            loss_bitwise=s3["prefetch_loss"] == s3["blocking_loss"],
            n_buckets=s3["n_buckets"],
            bucket_counters=s3["bucket_counters"])))
    writer.close()
    n_ring_recs = len(obs.load_jsonl(rec_path))
    print(f"{prefix}({N_DEV}): ring/prefetch attribution JSONL {rec_path}: "
          f"{n_ring_recs} record(s)")
    return dict(tp=tp, tp_chunks=chunk, dp=dp, stage3=s3, pp=pp,
                telemetry=tel, overhead=ovh)


if __name__ == "__main__":
    jax.config.update("jax_default_device", jax.devices("cpu")[0])
    run()
