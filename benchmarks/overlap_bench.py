"""Comm–compute overlap bench: overlapped vs blocking on the virtual mesh.

Times the three overlap paths against their blocking twins on the 8-device
virtual CPU mesh (same harness as the multichip dryrun, whose output this
extends — see __graft_entry__.dryrun_multichip):

- TP: ring collective matmuls (parallel/collective_matmul.py) vs the fused
  psum/all-gather islands.
- DP: bucketed grad psum (distributed/sharding_utils.py) vs per-parameter
  psums (the unfused sync the reference's EagerReducer replaces).
- PP: the async-p2p 1F1B schedule (parallel/pipeline.py, overlap_p2p) vs the
  blocking schedule.

Caveat: the host-CPU collective emulation serializes every hop at a
rendezvous, so the latency hiding that motivates the ring/async variants
cannot materialize here — wall-clock on this mesh measures op-count overhead
only. Bucketed DP sync wins on op count and shows a real speedup; the TP
ring and PP async schedules show their overhead (the TPU win comes from
overlap the emulation can't express) and are asserted ≤ blocking only on a
real TPU backend. Run: `python benchmarks/overlap_bench.py`.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_DEV = 8
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={N_DEV}").strip()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402


def _timeit(f, *args, reps=5, inner=3):
    jax.block_until_ready(f(*args))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(inner):
            o = f(*args)
        jax.block_until_ready(o)
        best = min(best, (time.perf_counter() - t0) / inner)
    return best * 1e3


def bench_tp(cpus, mp=4, t=256, k=1024, out=1024):
    from paddle_tpu._compat import shard_map
    from paddle_tpu.parallel import collective_matmul as cm

    mesh = Mesh(np.array(cpus[:mp]), ("mp",))
    rng = np.random.RandomState(0)

    def island(kern, in_specs):
        return jax.jit(shard_map(
            lambda a, b: kern(a, b, mp, "mp"), mesh=mesh, in_specs=in_specs,
            out_specs=P(), axis_names=frozenset(["mp"]), check_vma=False))

    x = jax.device_put(jnp.asarray(rng.randn(t, k), jnp.float32),
                       NamedSharding(mesh, P(None, "mp")))
    w = jax.device_put(jnp.asarray(rng.randn(k, out), jnp.float32),
                       NamedSharding(mesh, P("mp", None)))
    row_specs = (P(None, "mp"), P("mp", None))
    row_ring = _timeit(island(cm.ring_allreduce_matmul, row_specs), x, w)
    row_blk = _timeit(island(cm.blocking_allreduce_matmul, row_specs), x, w)

    x2 = jnp.asarray(rng.randn(t, k), jnp.float32)
    w2 = jax.device_put(jnp.asarray(rng.randn(k, out), jnp.float32),
                        NamedSharding(mesh, P(None, "mp")))
    col_specs = (P(), P(None, "mp"))
    col_ring = _timeit(island(cm.ring_allgather_matmul, col_specs), x2, w2)
    col_blk = _timeit(island(cm.blocking_allgather_matmul, col_specs), x2, w2)
    return dict(row_ring=row_ring, row_blk=row_blk,
                col_ring=col_ring, col_blk=col_blk)


def bench_dp(cpus, dp=8, width=256, depth=8, batch=64, cap_mb=0.5):
    """End-to-end dp train step: blocking GSPMD sync (grads reduced at the
    step-end barrier the partitioner schedules) vs the explicit bucketed
    island (per-bucket variadic psums issued as backward produces them)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.optimizer import AdamW

    mesh = Mesh(np.array(cpus[:dp]).reshape(dp, 1), ("dp", "mp"))
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(batch, width).astype(np.float32))
    y = paddle.to_tensor(rng.randn(batch, 16).astype(np.float32))

    def loss_fn(o, l):
        return paddle.mean((o - l) ** 2)

    res = {}
    for mode in (None, "bucketed"):
        paddle.set_device("cpu")
        paddle.seed(7)
        layers = []
        for _ in range(depth):
            layers += [nn.Linear(width, width), nn.GELU()]
        model = nn.Sequential(*layers, nn.Linear(width, 16))
        opt = AdamW(learning_rate=1e-2,
                    parameters=model.parameters(), weight_decay=0.01)
        step = TrainStep(model, loss_fn, opt, mesh=mesh, batch_spec=P("dp"),
                         grad_sync=mode, grad_bucket_mb=cap_mb)
        loss = step(x, labels=y)  # compile + warm
        res[mode or "blocking"] = _timeit(
            lambda: step(x, labels=y), reps=3, inner=5)
        res[(mode or "blocking") + "_loss"] = float(loss)
        if mode == "bucketed":
            res["n_buckets"] = len(step.grad_buckets)
    return res


def bench_pp(cpus, S=2, M=8, H=256):
    from paddle_tpu._compat import shard_map
    from paddle_tpu.parallel.pipeline import (last_stage_value, microbatch,
                                              pipeline_apply,
                                              stack_stage_params)

    mesh = Mesh(np.array(cpus[:S]), ("pp",))
    rng = np.random.RandomState(2)
    stacked = stack_stage_params(
        [{"w": jnp.asarray(rng.randn(H, H), jnp.float32) * 0.1}
         for _ in range(S)])
    x_mb = microbatch(jnp.asarray(rng.randn(M * 4, H), jnp.float32), M)

    def build(ovl):
        pipe = pipeline_apply(lambda p, h: jnp.tanh(h @ p["w"]), S, M, "pp",
                              remat=False, overlap_p2p=ovl)

        def island(params, xm):
            return last_stage_value(jnp.sum(pipe(params, xm) ** 2), S, "pp")

        return jax.jit(shard_map(
            island, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
            axis_names=frozenset(["pp"]), check_vma=False))

    t_blk = _timeit(build(False), stacked, x_mb)
    t_ovl = _timeit(build(True), stacked, x_mb)
    return dict(blocking=t_blk, overlapped=t_ovl)


def run(cpus=None, prefix="overlap_bench"):
    if cpus is None:
        cpus = jax.devices("cpu")
    assert len(cpus) >= N_DEV, (len(cpus), N_DEV)
    tp = bench_tp(cpus)
    dp = bench_dp(cpus)
    pp = bench_pp(cpus)
    print(f"{prefix}({N_DEV}): tp mp=4 row ring {tp['row_ring']:.1f}ms vs "
          f"fused {tp['row_blk']:.1f}ms, col ring {tp['col_ring']:.1f}ms vs "
          f"fused {tp['col_blk']:.1f}ms (virtual-cpu serializes hops; "
          f"overlap needs real ICI)")
    verdict = "OK" if dp["bucketed"] <= dp["blocking"] else "SLOWER"
    print(f"{prefix}({N_DEV}): dp=8 e2e step: bucketed-overlap "
          f"({dp['n_buckets']} fused psums) {dp['bucketed']:.1f}ms vs "
          f"blocking GSPMD {dp['blocking']:.1f}ms, loss "
          f"{dp['bucketed_loss']:.6f}=={dp['blocking_loss']:.6f} "
          f"overlapped<=blocking: {verdict}")
    print(f"{prefix}({N_DEV}): pp=2 1F1B async-p2p {pp['overlapped']:.1f}ms "
          f"vs blocking {pp['blocking']:.1f}ms (+1 skew tick on emulation; "
          f"transfer hides behind compute on real ICI)")
    return dict(tp=tp, dp=dp, pp=pp)


if __name__ == "__main__":
    jax.config.update("jax_default_device", jax.devices("cpu")[0])
    run()
