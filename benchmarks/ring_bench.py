"""Ring-attention per-step micro-bench: Pallas flash block vs fp32 einsum.

Ring wall-time is n steps of per-block compute (rotation overlaps); a single
chip can't host the 4-device ring, so this measures the per-step block
compute both ways at long-context shard sizes (>= 8k per shard), fwd and
fwd+bwd. Run on the TPU: `python benchmarks/ring_bench.py`.
"""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bench import peak_flops
from paddle_tpu.ops.flash_attention import flash_block_fwd, flash_block_bwd
from paddle_tpu.parallel.ring_attention import _merge_partials

N = 8


def bench(f, *args, n=5):
    o = f(*args)
    jax.device_get(jax.tree_util.tree_leaves(o)[0].ravel()[0])
    t0 = time.perf_counter()
    for _ in range(n):
        o = f(*args)
    jax.device_get(jax.tree_util.tree_leaves(o)[0].ravel()[0])
    return (time.perf_counter() - t0) / n / N


def einsum_block_step(q, k_blk, v_blk, o, m, l, scale):
    """One ring step of the fp32-einsum path (pre-r2 implementation)."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k_blk.astype(jnp.float32)) * scale
    blk_max = jnp.max(s, axis=-1)
    new_m = jnp.maximum(m, blk_max)
    alpha = jnp.exp(m - new_m)
    p = jnp.exp(s - new_m[..., None])
    new_l = l * alpha + p.sum(-1)
    new_o = o * alpha[..., None] + jnp.einsum(
        "bqk,bkd->bqd", p, v_blk.astype(jnp.float32))
    return new_o, new_m, new_l


def flash_block_step(q, k_blk, v_blk, o, lse, scale):
    """One ring step of the flash path: Pallas block kernel + lse merge."""
    o_blk, lse_blk = flash_block_fwd(q, k_blk, v_blk, causal=False,
                                     scale=scale)
    return _merge_partials(o, lse, o_blk, lse_blk)


def chunk_sweep(cpus=None, mp=4, t=512, k=512, out=512, chunks=(1, 2, 4),
                reps=3, inner=3):
    """Chunked ring collective-matmul sweep at mp>2 (importable; the n=8
    multichip dryrun calls this through overlap_bench for mp=4 and mp=8).

    For each sub-tile count, times the row-parallel all-reduce ring against
    the fused-psum blocking twin and snapshots the per-hop comm_span trace
    counters (tp_ring_allreduce.hop / .gather_hop calls and bytes), which is
    how the chunking shows up in the step log: same total bytes, n_chunks x
    the collective-permute count at 1/n_chunks the payload each.
    """
    import functools

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu import observability as obs
    from paddle_tpu._compat import shard_map
    from paddle_tpu.parallel import collective_matmul as cm

    if cpus is None:
        cpus = jax.devices("cpu")
    mesh = Mesh(np.array(cpus[:mp]), ("mp",))
    rng = np.random.RandomState(0)
    x = jax.device_put(jnp.asarray(rng.randn(t, k), jnp.float32),
                       NamedSharding(mesh, P(None, "mp")))
    w = jax.device_put(jnp.asarray(rng.randn(k, out), jnp.float32),
                       NamedSharding(mesh, P("mp", None)))
    specs = (P(None, "mp"), P("mp", None))

    def island(kern, **kw):
        return jax.jit(shard_map(
            functools.partial(kern, n=mp, axis_name="mp", **kw), mesh=mesh,
            in_specs=specs, out_specs=P(),
            axis_names=frozenset(["mp"]), check_vma=False))

    def timeit(f):
        jax.block_until_ready(f(x, w))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(inner):
                o = f(x, w)
            jax.block_until_ready(o)
            best = min(best, (time.perf_counter() - t0) / inner)
        return best * 1e3

    res = {"mp": mp, "blocking_ms": timeit(island(cm.blocking_allreduce_matmul)),
           "sweep": {}}
    ref = None
    for nc in chunks:
        if (t // mp) % nc:
            continue
        obs.reset_counters()
        f = island(cm.ring_allreduce_matmul, nchunks=nc)
        ms = timeit(f)
        snap = {name: v for name, v in obs.counters().items()
                if name.startswith("tp_ring_allreduce.")}
        out_val = f(x, w)
        if ref is None:
            ref = out_val
        res["sweep"][nc] = dict(
            ms=ms, bitwise_vs_unchunked=bool((out_val == ref).all()),
            hop_counters=snap)
    return res


def main():
    dev = jax.devices()[0]
    print(f"device: {getattr(dev, 'device_kind', dev.platform)}")
    # NOTE: per-shard S is VMEM-bounded (~12k at D=128) because the fwd
    # kernel stages the full KV block in VMEM; ring shards the sequence so
    # 8k/shard x sep=4 already covers 32k contexts.
    for (bh, s, d) in [(8, 8192, 128), (8, 4096, 128)]:
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
        k = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
        v = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
        do = jnp.asarray(rng.randn(bh, s, d), jnp.bfloat16)
        scale = 1.0 / d ** 0.5

        @jax.jit
        def einsum_N(q, k, v):
            o = jnp.zeros((bh, s, d), jnp.float32)
            m = jnp.full((bh, s), -jnp.inf, jnp.float32)
            l = jnp.zeros((bh, s), jnp.float32)

            def body(i, carry):
                return einsum_block_step(q, k, v, *carry, scale)
            return lax.fori_loop(0, N, body, (o, m, l))

        @jax.jit
        def flash_N(q, k, v):
            o0, lse0 = flash_block_fwd(q, k, v, causal=False, scale=scale)

            def body(i, carry):
                return flash_block_step(q, k, v, *carry, scale)
            return lax.fori_loop(0, N - 1, body,
                                 (o0.astype(jnp.float32), lse0))

        @jax.jit
        def flash_bwd_N(q, k, v, do):
            o, lse = flash_block_fwd(q, k, v, causal=False, scale=scale)
            delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                            axis=-1)

            def body(i, carry):
                dq, dk, dv = flash_block_bwd(q, k, v, do, lse, delta,
                                             causal=False, scale=scale)
                return (carry[0] + dq.astype(jnp.float32),
                        carry[1] + dk.astype(jnp.float32),
                        carry[2] + dv.astype(jnp.float32))
            z = jnp.zeros((bh, s, d), jnp.float32)
            return lax.fori_loop(0, N, body, (z, z, z))

        peak = peak_flops(dev)
        t_e = bench(einsum_N, q, k, v)
        t_f = bench(flash_N, q, k, v)
        t_b = bench(flash_bwd_N, q, k, v, do)
        fl = 2 * 2 * s * s * d * bh
        print(f"BH{bh} S{s} D{d}: einsum {t_e*1e3:.2f}ms | "
              f"flash {t_f*1e3:.2f}ms ({t_e/t_f:.2f}x, "
              f"eff={fl/t_f/peak:.3f}) | blk bwd {t_b*1e3:.2f}ms")


if __name__ == "__main__":
    main()
