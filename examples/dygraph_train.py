"""Dygraph-style training (the reference's eager workflow): Layer + eager
backward + optimizer, no explicit jit."""
import os
import sys

import numpy as np

# runnable from the repo root without installation
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 64), nn.GELU(), nn.Linear(64, 4))
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(64, 16).astype(np.float32))
    y = paddle.to_tensor(rng.randn(64, 4).astype(np.float32))
    for i in range(20):
        loss = paddle.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if i % 5 == 0:
            print(f"step {i}: loss {float(loss.numpy()):.4f}")


if __name__ == "__main__":
    main()
