"""The Fleet collective workflow (the reference's primary distributed API):
fleet.init with a hybrid strategy -> fleet.distributed_model ->
fleet.distributed_optimizer -> compiled train step over the hybrid mesh.

Runs on virtual CPU devices so it works anywhere:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 python examples/fleet_hybrid_tp.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")


def main():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.fleet.meta_parallel import (
        ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)
    from paddle_tpu.jit import TrainStep

    paddle.set_device("cpu")
    vocab, hidden, seq = 128, 64, 32

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.ln = nn.LayerNorm(hidden)
            self.fc_in = ColumnParallelLinear(hidden, 4 * hidden,
                                              gather_output=False)
            self.fc_out = RowParallelLinear(4 * hidden, hidden,
                                            input_is_parallel=True)

        def forward(self, x):
            return x + self.fc_out(F.gelu(self.fc_in(self.ln(x))))

    class GPT2Tiny(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = VocabParallelEmbedding(vocab, hidden)
            self.block = Block()
            self.head = ColumnParallelLinear(hidden, vocab, has_bias=False)

        def forward(self, ids):
            return self.head(self.block(self.emb(ids)))

    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                               "pp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.get_hybrid_communicate_group()
    print("hybrid mesh:", dict(hcg.mesh.shape))

    paddle.seed(0)
    model = fleet.distributed_model(GPT2Tiny())
    opt = fleet.distributed_optimizer(paddle.optimizer.AdamW(
        learning_rate=1e-2, parameters=model.parameters()))

    def loss_fn(logits, labels):
        return F.cross_entropy(logits.reshape([-1, vocab]),
                               labels.reshape([-1])).mean()

    step = TrainStep(model, loss_fn, opt, mesh=hcg.mesh, batch_spec=P("dp"))
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, vocab, (8, seq)).astype(np.int32))
    labels = paddle.to_tensor(np.roll(ids.numpy(), -1, 1).astype(np.int64))
    for i in range(5):
        loss = step(ids, labels=labels)
        print(f"step {i}: loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
