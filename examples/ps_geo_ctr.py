"""Parameter-server flavors in one script: classic async sparse training,
geo-SGD dense sync, and a CTR table with show/click statistics + shrink.

Runs self-contained (server and workers share the process via the rpc
layer, exactly how tests drive the PS):
  python examples/ps_geo_ctr.py
"""
import os
import socket
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    import paddle_tpu as paddle
    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.ps import PSClient

    paddle.set_device("cpu")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    rpc.init_rpc("ps_server:0", rank=0, world_size=1,
                 master_endpoint=f"127.0.0.1:{port}")

    # -- CTR sparse table: embeddings + show/click statistics ------------
    worker = PSClient("ps_server:0", async_push=True)
    worker.create_sparse_table(
        "ctr_emb", emb_dim=8,
        accessor={"type": "ctr", "lr": 0.1, "show_coeff": 0.2,
                  "click_coeff": 1.0})
    rng = np.random.RandomState(0)
    for step in range(5):
        ids = rng.randint(0, 100, 16)
        rows = worker.pull_sparse("ctr_emb", ids)      # gather embeddings
        grads = rng.randn(16, 8).astype(np.float32) * 0.01
        shows = np.ones(16, np.float32)
        clicks = (rng.rand(16) < 0.1).astype(np.float32)
        worker.push_sparse("ctr_emb", ids, grads, shows=shows,
                           clicks=clicks)
    worker.barrier()
    evicted = worker.shrink_sparse_table("ctr_emb", score_threshold=0.3,
                                         decay=0.9)
    print(f"CTR table: {evicted} low-score rows evicted on shrink")

    # -- geo-SGD: two workers train locally, sync deltas every 2 steps ---
    a = PSClient("ps_server:0")
    b = PSClient("ps_server:0")
    _, wa = a.init_geo("dense_w", [4, 4], sync_steps=2)
    _, wb = b.init_geo("dense_w", [4, 4], sync_steps=2)
    for _ in range(2):
        wa = a.geo_step("dense_w", wa - 0.1 * np.ones_like(wa))
    for _ in range(2):
        wb = b.geo_step("dense_w", wb - 0.2 * np.ones_like(wb))
    print("geo-SGD merged weight mean:",
          float(a.pull_dense("dense_w").mean()))  # -0.6 = A's -0.2 + B's -0.4

    worker.stop()


if __name__ == "__main__":
    main()
