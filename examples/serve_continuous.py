"""Serve a Llama with continuous batching over a paged KV cache.

Requests arrive on a Poisson trace with mixed prompt lengths; the engine
admits them against its free-block budget, interleaves chunked prefill
with bucketed decode batches (one compiled step family, recompiles
bounded and counted), and preempts-by-eviction if the block pool runs
dry. Tiny model on CPU (pallas interpret); the same engine drives the
flagship config on TPU (see bench.py serve_continuous).

The run also demos the observability stack: request-lifecycle tracing
(exported as a Chrome/Perfetto trace plus JSONL spans), the streaming
SLO histograms behind a Prometheus text snapshot, and the failure
flight recorder (clean shutdown here, so nothing is dumped).

Two robustness acts follow. First an overload burst against a
deliberately under-provisioned engine: the bounded queue and the
block-overcommit cap reject at submit() with a cause, deadline shedding
reclaims queued work that can no longer meet its TTFT budget, and the
outcomes() audit shows every request terminal — finished, rejected,
shed, or failed, never silently dropped. Then a crash: an engine
journaling to disk is abandoned mid-decode, and a fresh engine rebuilds
the schedule from the journal (recover()) and finishes every stream
bit-identically to an uninterrupted run — greedy decoding is
deterministic in (prompt + history), so tokens lost with the dead
engine's buffer are simply re-derived.

A final act shows prefix caching: requests sharing a long system prompt
hit the COW-shared block index, skip the shared span's prefill, and
still produce bitwise the tokens a cache-off engine produces.
"""
import os
import sys
import tempfile

import numpy as np

# runnable from the repo root without installation
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    from paddle_tpu.inference import InferenceEngine, Request, ServeConfig
    from paddle_tpu.models.llama import init_llama_params, llama_tiny
    from paddle_tpu.observability.metrics import StepMetrics
    from paddle_tpu.ops import _common

    _common.set_interpret(True)  # noqa: PTA007 -- process-lifetime: script entry point, paged pallas kernels off-TPU

    config = llama_tiny(vocab=96, hidden=64, layers=1, heads=4, kv_heads=2,
                        seq=256)
    params = init_llama_params(config, seed=0)
    serve = ServeConfig(block_size=128, num_blocks=17, max_batch=4,
                        prefill_chunk=64, max_seq_len=256)
    metrics = StepMetrics(name="serve", n_devices=1)
    engine = InferenceEngine(params, config, serve, telemetry=metrics,
                             trace_requests=True, flight_recorder=True)

    rng = np.random.RandomState(0)
    arrivals = np.cumsum(rng.exponential(1.0 / 8.0, size=6))  # Poisson 8/s
    lengths = rng.choice([8, 24, 96, 130], size=6)
    requests = [
        Request(rng.randint(1, config.vocab_size, size=int(n)).tolist(),
                max_new_tokens=8, arrival=float(t))
        for n, t in zip(lengths, arrivals)
    ]
    stats = engine.run(requests)

    print(f"served {stats['requests']} requests, "
          f"{stats['generated_tokens']} tokens "
          f"in {stats['iterations']} iterations")
    print(f"throughput: {stats['tokens_per_sec']:.1f} tok/s  "
          f"ttft p50/p99: {stats['ttft_p50_s']:.3f}/"
          f"{stats['ttft_p99_s']:.3f} s  "
          f"tpot p50/p99: {stats['tpot_p50_s']:.3f}/"
          f"{stats['tpot_p99_s']:.3f} s")
    print(f"compiled shapes: {sorted(stats['compiles'])}  "
          f"preemptions: {stats['preemptions']}  "
          f"pool leak-free: {engine.pool.used_blocks == 0}")
    for seq in sorted(engine.finished, key=lambda s: s.req.request_id):
        print(f"request {seq.req.request_id}: prompt {seq.n_prompt} tokens"
              f" -> continuation: {seq.generated}")

    # observability exports: open the chrome trace in Perfetto
    # (ui.perfetto.dev) — one row per engine phase, one row per request
    out = tempfile.mkdtemp(prefix="paddle_tpu_serve_")
    trace = engine.tracer.export_chrome(os.path.join(out, "serve_trace.json"))
    spans = engine.tracer.export_jsonl(os.path.join(out, "serve_spans.jsonl"))
    print(f"request trace: {engine.tracer.span_count()} spans -> {trace} "
          f"(Perfetto) and {spans} (JSONL)")
    print(f"streaming SLO estimates (fixed-memory histograms): "
          f"ttft p50 {stats['ttft_stream_p50_s']:.3f} s, "
          f"tpot p50 {stats['tpot_stream_p50_s']:.3f} s")
    prom = engine.render_prometheus()
    print(f"prometheus snapshot: {len(prom.splitlines())} lines, e.g.")
    for line in prom.splitlines():
        if line.startswith("# TYPE paddle_tpu_serve_ttft"):
            print(f"  {line}")
    print(f"flight recorder: ring {len(engine.recorder.ring)} records, "
          f"dumped: {engine.recorder.dumped or 'nothing (clean run)'}")

    # ---- act 2: overload burst against an under-provisioned engine ----
    # 8 requests into a 2-deep queue over a 4-block pool, with TTFT
    # deadlines the tail of the burst cannot meet: admission rejects
    # with a cause, the scheduler sheds expired queued work, and the
    # outcomes() audit accounts for every request. Deterministic mode:
    # arrivals/deadlines are iteration counts, so the shed set is
    # replayable bit-for-bit.
    over = ServeConfig(block_size=128, num_blocks=4, max_batch=1,
                       prefill_chunk=64, max_seq_len=256,
                       max_queue=2, overcommit=4.0)
    eng2 = InferenceEngine(params, config, over)
    burst = [Request(rng.randint(1, config.vocab_size, size=24).tolist(),
                     max_new_tokens=6, request_id=i, arrival=float(i),
                     ttft_deadline=8.0, deadline=30.0)
             for i in range(8)]
    st2 = eng2.run(burst, deterministic=True)
    audit = eng2.outcomes()
    terminal = {"finished", "rejected", "shed", "failed"}
    print(f"overload burst: {len(burst)} submitted -> "
          f"{st2['requests']} finished, {st2['rejected']} rejected, "
          f"{st2['shed']} shed, {st2['failed']} failed")
    for rid in sorted(audit):
        state, cause = audit[rid]
        print(f"  request {rid}: {state}"
              + (f" ({cause})" if cause else ""))
    print(f"no silent drops: "
          f"{all(s in terminal for s, _ in audit.values())}  "
          f"overload pool leak-free: {eng2.pool.used_blocks == 0}")

    # ---- act 3: crash mid-decode, recover from the engine journal ----
    jpath = os.path.join(out, "engine.jsonl")
    victim = InferenceEngine(params, config, serve, journal=jpath)
    work = [Request(rng.randint(1, config.vocab_size, size=n).tolist(),
                    max_new_tokens=8, request_id=i, arrival=0.0)
            for i, n in enumerate((12, 40, 72))]
    for r in work:
        victim.submit(r)
    for _ in range(4):          # a few iterations of real progress...
        victim.step()
    del victim                  # ...then the "crash": buffered tokens die
    successor = InferenceEngine(params, config, serve, journal=jpath)
    rec = successor.recover()
    successor.run([], deterministic=True)
    reference = InferenceEngine(params, config, serve)
    reference.run([Request(list(r.prompt), max_new_tokens=8,
                           request_id=r.request_id, arrival=0.0)
                   for r in work], deterministic=True)
    streams = lambda e: {s.req.request_id: list(s.generated)
                         for s in e.finished}
    print(f"journal recovery: replayed {rec['replayed']} requests "
          f"({rec['torn_lines']} torn lines) from {jpath}")
    print(f"recovered streams bit-identical to uninterrupted run: "
          f"{streams(successor) == streams(reference)}  "
          f"recovery pool leak-free: {successor.pool.used_blocks == 0}")

    # ---- act 4: prefix reuse — COW-shared KV blocks (PR 16) ----
    # Five requests share a 128-token "system prompt": the first prefill
    # registers its full block in the prefix index; every later request
    # matches it, acquires the block copy-on-write (no bytes copied —
    # writes land past the shared span by construction), and skips that
    # prefill work. Greedy tokens stay bitwise identical to a cache-off
    # run of the same trace; when the last reference drops the block
    # PARKS for future hits instead of freeing, so the leak audit still
    # reads zero used blocks.
    system = rng.randint(1, config.vocab_size, size=128).tolist()
    reuse = [Request(system + rng.randint(1, config.vocab_size,
                                          size=12).tolist(),
                     max_new_tokens=6, request_id=i, arrival=float(4 * i))
             for i in range(5)]
    cold = InferenceEngine(params, config, serve)
    cold.run([Request(list(r.prompt), max_new_tokens=6,
                      request_id=r.request_id, arrival=r.arrival)
              for r in reuse], deterministic=True)
    warm = InferenceEngine(
        params, config,
        ServeConfig(block_size=128, num_blocks=17, max_batch=4,
                    prefill_chunk=64, max_seq_len=256, prefix_cache=True))
    st4 = warm.run(reuse, deterministic=True)
    pc = st4["prefix_cache"]
    print(f"prefix reuse: {pc['hits']}/{pc['lookups']} admissions hit "
          f"the shared system prompt ({pc['hit_tokens']} prefill tokens "
          f"skipped, {pc['entries']} cached blocks resident, "
          f"{pc['cow_copies']} COW copies)")
    print(f"cached streams bitwise equal cache-off run: "
          f"{streams(warm) == streams(cold)}  "
          f"prefix-cache pool leak-free: {warm.pool.used_blocks == 0}")


if __name__ == "__main__":
    main()
