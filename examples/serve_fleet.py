"""Serve one trace with a multi-replica fleet: prefix-affinity routing,
a replica crash healed from its journal, and a zero-downtime rolling
weight swap.

The FleetRouter owns three InferenceEngine replicas. Every submit
probes each live replica's prefix cache host-side and routes to the
one already holding the longest cached prefix (ties broken by a
composite load signal, then replica index — fully deterministic), so
requests sharing a system prompt concentrate where their COW blocks
live instead of spreading the cache 1/N thin. A spill threshold keeps
adversarial skew from starving the other replicas.

Act 2 kills a replica mid-burst: its journal fd dies unflushed, the
router re-drives every accepted-but-unfinished request in the journal
onto survivors, and — because greedy decode is a pure function of
(prompt + weights) — the migrated streams come out bit-identical to a
run with no failure at all. Zero accepted requests are lost.

Act 3 rolls new weights across the fleet one replica at a time: each
is steered out of routing, drains to its idle boundary, swaps, and
rejoins while the others keep serving. Zero downtime, zero drops.

Tiny model on CPU (pallas interpret); the same router drives real
fleets on TPU (see bench.py serve_fleet).
"""
import os
import sys
import tempfile

import numpy as np

# runnable from the repo root without installation
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    from paddle_tpu.inference import FleetRouter, ServeConfig
    from paddle_tpu.inference import InferenceEngine, Request
    from paddle_tpu.models.llama import init_llama_params, llama_tiny
    from paddle_tpu.ops import _common

    _common.set_interpret(True)  # noqa: PTA007 -- process-lifetime: script entry point, paged pallas kernels off-TPU

    config = llama_tiny(vocab=96, hidden=64, layers=1, heads=4, kv_heads=2,
                        seq=512)
    params = init_llama_params(config, seed=0)
    serve = ServeConfig(block_size=128, num_blocks=10, max_batch=2,
                        prefill_chunk=32, max_seq_len=256,
                        prefix_cache=True)

    rng = np.random.RandomState(0)
    system = rng.randint(1, config.vocab_size, size=140).tolist()

    def mk_trace():
        # even requests share the 140-token system prompt (affinity
        # bait spanning a full KV block); odd ones are short one-offs
        out = []
        for i in range(8):
            if i % 2 == 0:
                prompt = system + rng.randint(
                    1, config.vocab_size, size=8).tolist()
            else:
                prompt = rng.randint(1, config.vocab_size,
                                     size=24).tolist()
            out.append(Request(prompt, max_new_tokens=5,
                               arrival=float(i)))
        return out
    trace = mk_trace()

    def fresh():
        return [Request(list(r.prompt), max_new_tokens=r.max_new_tokens,
                        arrival=r.arrival) for r in trace]

    # the bit-identity oracle: the same trace on ONE lone engine
    lone = InferenceEngine(params, config, serve)
    ref_reqs = fresh()
    for i, r in enumerate(ref_reqs):
        r.request_id = i
    lone.run(ref_reqs, deterministic=True)
    reference = {s.req.request_id: list(s.generated)
                 for s in lone.finished}

    # ---- act 1: prefix-affinity routing over 3 replicas ----
    out = tempfile.mkdtemp(prefix="paddle_tpu_fleet_")
    os.mkdir(os.path.join(out, "a1"))
    fleet = FleetRouter(params, config, serve, n_replicas=3,
                        journal_dir=os.path.join(out, "a1"))
    stats = fleet.run(fresh(), deterministic=True)
    print(f"fleet of {stats['replicas']}: {stats['requests']} requests, "
          f"{stats['generated_tokens']} tokens in "
          f"{stats['iterations']} iterations")
    print(f"routing: {stats['routed_per_replica']} per replica, "
          f"affinity hits {stats['affinity_hits']} "
          f"(hit rate {stats['affinity_hit_rate']:.2f}), "
          f"spills {stats['spills']}")
    print(f"fleet streams bit-identical to lone engine: "
          f"{fleet.streams() == reference}")

    # ---- act 2: kill a replica mid-burst, journal migration ----
    os.mkdir(os.path.join(out, "a2"))
    chaos = FleetRouter(params, config, serve, n_replicas=3,
                        journal_dir=os.path.join(out, "a2"))
    st2 = chaos.run(fresh(), deterministic=True, kill_at=(6, 0))
    print(f"replica 0 killed at iteration 6: "
          f"{st2['migrations']} requests re-driven from its journal, "
          f"{st2['lost']} lost")
    print(f"migrated streams bit-identical to no-failure run: "
          f"{chaos.streams() == reference}  survivors leak-free: "
          f"{all(chaos.engines[i].pool.used_blocks == 0 for i in chaos._live())}")

    # ---- act 3: rolling fleet-wide weight swap, zero drops ----
    os.mkdir(os.path.join(out, "a3"))
    roll = FleetRouter(params, config, serve, n_replicas=3,
                       journal_dir=os.path.join(out, "a3"))
    st3 = roll.run(fresh(), deterministic=True, rolling_swap_at=3,
                   swap_source=params)
    drops = sum(e.last_swap["in_flight_running"]
                + e.last_swap["in_flight_prefill"]
                for e in roll.engines)
    print(f"rolling swap: {st3['rolling_swaps']} replicas swapped at "
          f"their idle boundaries, {drops} requests caught in flight, "
          f"{st3['lost']} lost")
    print(f"post-swap streams bit-identical (same weights): "
          f"{roll.streams() == reference}")

    # one fleet scrape: every replica's metrics label-split + the
    # router's own block
    prom = roll.render_prometheus()
    lines = [ln for ln in prom.splitlines()
             if ln.startswith("paddle_tpu_fleet_ro")]
    print(f"merged exposition: {len(prom.splitlines())} lines, e.g.")
    for ln in lines:
        print(f"  {ln}")


if __name__ == "__main__":
    main()
