"""Serve a Llama with weight-only int8 decode (half the weight stream —
decodes below the bf16 HBM floor on TPU)."""
import os
import sys

import numpy as np

# runnable from the repo root without installation
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    from paddle_tpu.models.llama import (greedy_generate, init_llama_params,
                                         llama_tiny, quantize_llama_int8)
    config = llama_tiny(vocab=512, hidden=64, layers=4, heads=4, kv_heads=4,
                        inter=128, seq=96)
    params = quantize_llama_int8(init_llama_params(config, seed=0))
    prompt = np.random.RandomState(0).randint(0, 512, (1, 8)).astype(np.int32)
    toks = greedy_generate(params, prompt, config, max_new_tokens=16)
    print("prompt:", prompt[0].tolist())
    print("continuation:", toks[0].tolist())


if __name__ == "__main__":
    main()
