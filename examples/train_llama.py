"""Train the flagship Llama on synthetic data — the compiled SPMD step.

Single chip:      python examples/train_llama.py
Virtual 8-chip:   XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                  python examples/train_llama.py --dp 2 --mp 2 --pp 2
"""
import argparse

import os
import sys

import numpy as np

# runnable from the repo root without installation
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--mp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()

    import jax
    from paddle_tpu.models.llama import (ParallelConfig, build_train_step,
                                         llama_tiny, make_mesh)
    parallel = ParallelConfig(dp=args.dp, mp=args.mp, pp=args.pp,
                              microbatches=2 if args.pp > 1 else 1)
    if parallel.total > 1:
        from paddle_tpu.ops import _common
        _common.set_interpret(True)  # noqa: PTA007 -- process-lifetime: script entry point on virtual CPU devices
        cpus = jax.devices("cpu")
        jax.config.update("jax_default_device", cpus[0])  # noqa: PTA007 -- process-lifetime device pin for the script run
        mesh = make_mesh(parallel, devices=cpus[:parallel.total])
    else:
        mesh = None
    config = llama_tiny(vocab=512, hidden=64, layers=4, heads=4, kv_heads=4,
                        inter=128, seq=64)
    step, params, opt = build_train_step(config, parallel, mesh=mesh,
                                         lr=1e-3)
    rng = np.random.RandomState(0)
    batch = max(4, parallel.dp * 2)
    ids = rng.randint(0, config.vocab_size, (batch, 32)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    for i in range(args.steps):
        params, opt, loss = step(params, opt, ids, labels)
        print(f"step {i}: loss {float(jax.device_get(loss)):.4f}")


if __name__ == "__main__":
    main()
