"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's capabilities.

Built from scratch on jax/XLA/Pallas (see SURVEY.md). The public surface mirrors
the reference's ``paddle.*`` so users can switch with an import change:
eager Tensors with ``.backward()``, ``nn.Layer``, optimizers, AMP, DataLoader,
``vision`` models, a Fleet-equivalent hybrid-parallel stack, and jit-to-XLA
compilation — all running SPMD over TPU meshes.
"""
from __future__ import annotations

import jax as _jax

# Paddle's integer default is int64; without x64 jax silently downcasts to
# int32. Float creation paths still default to float32 (see tensor/creation).
_jax.config.update("jax_enable_x64", True)

from .framework import set_printoptions  # noqa: F401
from .framework import LazyGuard, batch  # noqa: F401
from .framework.random import (  # noqa: F401
    get_cuda_rng_state, set_cuda_rng_state)
from .framework import (  # noqa: F401
    CPUPlace, TPUPlace, GPUPlace, CUDAPlace, CustomPlace,
    set_device, get_device, device_count, get_flags, set_flags, seed,
    get_rng_state, set_rng_state, set_default_dtype, get_default_dtype,
    is_compiled_with_cuda, is_compiled_with_tpu,
    is_compiled_with_xpu, is_compiled_with_rocm,
    is_compiled_with_custom_device,
)
from .framework.dtype import iinfo, finfo  # noqa: F401
from .framework.dtype import (  # noqa: F401
    bool_ as bool, uint8, int8, int16, int32, int64, float16, bfloat16,
    float32, float64, complex64, complex128, float8_e4m3fn, float8_e5m2, DType,
)
from .tensor import *  # noqa: F401,F403
from .tensor import Tensor  # noqa: F401
from .autograd import no_grad, enable_grad, grad, is_grad_enabled, set_grad_enabled  # noqa: F401
from .framework.io import save, load  # noqa: F401

from . import amp  # noqa: F401
from . import autograd  # noqa: F401

# Subsystems land incrementally during the build; import what exists.
import importlib as _importlib

from . import sysconfig  # noqa: F401
from . import version  # noqa: F401
from . import utils  # noqa: F401

for _sub in ("nn", "optimizer", "io", "jit", "vision", "metric", "distributed",
             "incubate", "ops", "profiler", "observability", "device", "hapi",
             "static",
             "inference", "runtime", "fft", "signal", "distribution", "sparse",
             "quantization", "audio", "text", "onnx", "linalg", "geometric"):
    try:
        globals()[_sub] = _importlib.import_module(f".{_sub}", __name__)
    except ImportError:
        pass

if "hapi" in globals():
    from .hapi.model import Model  # noqa: F401
    from .hapi.summary import flops, summary  # noqa: F401
if "nn" in globals():
    from .nn.layer.layers import ParamAttr  # noqa: F401

# dygraph/static mode switches (ref: paddle.enable_static / disable_static).
# Eager is the default; static mode activates Program capture on the eager
# dispatcher (see static/program.py).
def in_dynamic_mode():
    from .static import program as _sp
    return not _sp.in_static_mode()


def disable_static(place=None):
    from .static import program as _sp
    _sp.disable_static()


def enable_static():
    from .static import program as _sp
    _sp.enable_static()


def is_grad_enabled_():
    from .autograd import engine
    return engine.is_grad_enabled()


__version__ = "0.1.0"
