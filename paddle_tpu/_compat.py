"""Version-compatibility shims over the installed jax.

The codebase targets the modern jax surface (``jax.shard_map`` with
``axis_names=``/``check_vma=``, ``jax.enable_x64``, ``lax.axis_size``); 0.4.x
jaxlibs only expose ``jax.experimental.shard_map.shard_map(..., check_rep=,
auto=)`` and ``jax.experimental.enable_x64``. Everything in-tree imports these
three names from here so the same source runs on both.
"""
from __future__ import annotations

import jax

try:
    from jax import shard_map as _new_shard_map  # jax >= 0.6
    _HAS_NEW_SHARD_MAP = True
except ImportError:
    _HAS_NEW_SHARD_MAP = False
    from jax.experimental.shard_map import shard_map as _old_shard_map


def shard_map(f, mesh=None, in_specs=None, out_specs=None, axis_names=None,
              check_vma=None, **kwargs):
    """``jax.shard_map`` signature, lowered to the experimental API on 0.4.x.

    ``axis_names`` (manual axes) maps to the old ``auto=`` complement;
    ``check_vma`` maps to ``check_rep``. Mesh axes outside ``axis_names`` with
    size 1 are treated as manual rather than auto — partially-manual regions
    over trivial axes CHECK-fail old XLA SPMD partitioners
    (spmd_partitioner.cc: IsManualSubgroup mismatch) and are semantically
    identical at size 1.
    """
    if _HAS_NEW_SHARD_MAP:
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return _new_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kwargs)
    auto = frozenset()
    if axis_names is not None and mesh is not None:
        auto = frozenset(ax for ax in mesh.axis_names
                         if ax not in frozenset(axis_names)
                         and mesh.shape[ax] > 1)
    check_rep = bool(check_vma) if check_vma is not None else False
    return _old_shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_rep, auto=auto)


def enable_x64(new_val=True):
    """``jax.enable_x64`` context manager (experimental module on 0.4.x)."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(new_val)
    from jax.experimental import enable_x64 as _enable_x64
    return _enable_x64(new_val)


def axis_size(axis_name):
    """``lax.axis_size``; on 0.4.x ``psum(1, axis)`` folds to the static size."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams``; 0.4.x spells it ``TPUCompilerParams``."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)
