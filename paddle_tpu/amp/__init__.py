"""Automatic mixed precision (ref: python/paddle/amp/)."""
from .auto_cast import auto_cast, amp_guard, decorate
from .grad_scaler import GradScaler
from . import state

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler"]
