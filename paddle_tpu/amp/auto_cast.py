"""Autocast context + model decoration (ref: python/paddle/amp/auto_cast.py).

O1: matmul/conv cast to low precision at op level (see amp/state.py hooks in
linalg.matmul and nn.functional.conv). O2: parameters themselves are cast; the
optimizer keeps fp32 master weights (optimizer/optimizer.py multi_precision).
bfloat16 is the TPU default — no loss scaling required.
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from . import state
from ..framework import dtype as dtype_mod


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    prev = (state._enabled, state._dtype, state._level)
    state.set_autocast(enable, dtype_mod.convert_dtype(dtype), level)
    try:
        yield
    finally:
        state._enabled, state._dtype, state._level = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """Cast model parameters to the AMP dtype (O2); enable optimizer master weights."""
    nd = dtype_mod.convert_dtype(dtype)
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        for m in model_list:
            for p in m.parameters():
                if jnp.issubdtype(p._data.dtype, jnp.floating):
                    p._data = p._data.astype(nd)
    if optimizers is not None:
        single_opt = not isinstance(optimizers, (list, tuple))
        opt_list = [optimizers] if single_opt else list(optimizers)
        for opt in opt_list:
            if master_weight is not False:
                opt._multi_precision = True
        if single_model:
            return models, optimizers
        return model_list, opt_list
    return models if single_model else model_list
