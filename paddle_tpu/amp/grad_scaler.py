"""Dynamic loss scaling (ref: python/paddle/amp/grad_scaler.py).

On TPU with bfloat16 scaling is typically unnecessary (enable=False makes all
methods pass-through, like the reference on CPU); full fp16-style dynamic
scaling is implemented for parity: scale up on stable steps, skip + scale down
on inf/nan.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor
from ..autograd import no_grad


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5,
                 incr_every_n_steps=1000, decr_every_n_nan_or_inf=1,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def is_enable(self):
        return self._enable

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    @no_grad()
    def unscale_(self, optimizer):
        if not self._enable or self._unscaled:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._data * inv
            p.grad._data = g
            found = found or bool(jnp.logical_not(jnp.all(jnp.isfinite(g))))
        self._found_inf = found
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._update_scale()
        self._unscaled = False

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        self.update()

    def update(self):
        pass  # scale bookkeeping happens in step(); kept for API parity

    def _update_scale(self):
        if not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def get_loss_scaling(self):
        return Tensor(np.asarray(self._scale, dtype=np.float32))

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps, "enable": self._enable}

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)
