"""AMP autocast state, consulted by hot ops (matmul/conv) at trace time.

Ref: python/paddle/amp/auto_cast.py. O1 = cast MXU-bound ops (matmul, conv) to
the low-precision dtype; O2 = whole-model low precision with fp32 master
weights (handled in amp/decorate). bfloat16 is the TPU-native choice: no loss
scaling needed (same exponent range as fp32).
"""
from __future__ import annotations

import jax.numpy as jnp

_enabled = False
_dtype = jnp.bfloat16
_level = "O1"


def set_autocast(enabled: bool, dtype=None, level: str = "O1"):
    global _enabled, _dtype, _level
    _enabled = enabled
    if dtype is not None:
        _dtype = jnp.dtype(dtype)
    _level = level


def autocast_enabled() -> bool:
    return _enabled


def autocast_dtype():
    return _dtype


def autocast_level() -> str:
    return _level


def maybe_autocast(x):
    """Cast a float array to the autocast dtype when autocast is active."""
    if _enabled and jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != _dtype:
        return x.astype(_dtype)
    return x


def maybe_autocast_pair(a, b):
    if _enabled:
        return maybe_autocast(a), maybe_autocast(b)
    return a, b
