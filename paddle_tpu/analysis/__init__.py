"""``paddle_tpu.analysis`` — repo-specific static analysis.

A rule-registry framework (AST-based; the checked modules are never
imported) that turns the bug classes this repo has actually hit into
enforced lint rules:

    PTA001 weak-scalar       untyped int/float literals at known weak-type
                             sinks in ops/ and parallel/ (the PR-6/PR-7
                             x64 re-canonicalization / MLIR-verifier class)
    PTA002 vmem-budget       static per-pallas_call VMEM estimate from
                             BlockSpec block shapes, unless the site
                             routes through a registered fitter (_fit_*)
    PTA003 cost-estimate     every pallas_call in ops/ passes
                             cost_estimate= (MFU attribution, PR 4)
    PTA004 comm-span-nbytes  every comm_span(...) passes nbytes= (PR 3)
    PTA005 env-knobs         every PADDLE_TPU_* read goes through the
                             paddle_tpu.envs validated-getter registry
    PTA006 host-sync         .item()/np.asarray/jax.device_get/... in the
                             hot-path modules (PR-2 zero-host-syncs bar)

Findings can be suppressed inline with a REASONED noqa::

    x = np.asarray(cu)  # noqa: PTA006 -- host-side plan on concrete cu

(a reason after ``--`` is mandatory; a bare ``# noqa: PTA006`` suppresses
the finding but raises a PTA000 "suppression lacks a reason" finding in
its place) or via the per-rule allowlist file ``allowlist.json`` next to
this module (whole-file grants, each with a reason).

CLI::

    python -m paddle_tpu.analysis [--strict] [--rule PTA001] [--json]
                                  [--baseline write|check] [paths]

``--strict`` exits non-zero when any active (unsuppressed, unallowlisted,
unbaselined) finding remains — the tier-1 gate
(tests/test_static_analysis.py) and the multichip-dryrun preamble both run
in this mode.

The **baseline ratchet** (``--baseline write|check``, PR 11) lets a new
strict rule land immediately with existing debt frozen: ``write``
snapshots every active finding's *fingerprint* (rule + path + normalized
source line — line-number shifts don't invalidate it) into
``baseline.json`` next to this module; ``check`` marks findings matching
the snapshot as ``baselined`` (not active, so --strict passes) and FAILS
on (a) any new finding — not in the snapshot — and (b) any stale snapshot
entry whose finding no longer exists, which forces a re-``write`` and
makes the frozen count monotonically decrease. Deleting a baseline entry
whose finding still exists turns that finding active again: the ratchet
only moves one way.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

from . import _astutil

__all__ = ["Finding", "Module", "Rule", "Report", "run", "all_rules",
           "register", "REPO_ROOT", "DEFAULT_ALLOWLIST", "DEFAULT_BASELINE",
           "load_baseline", "write_baseline", "apply_baseline",
           "DEFAULT_SCAN_PATHS"]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_ALLOWLIST = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "allowlist.json")
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")

# The default sweep covers everything the rules guard: the package, plus
# the test suite / entry points / benches that PTA007 (global-state leak)
# polices. Relative to the repo root; missing entries are skipped so the
# analyzer still runs on a partial checkout.
DEFAULT_SCAN_PATHS = ("paddle_tpu", "tests", "examples", "benchmarks",
                      "bench.py", "__graft_entry__.py")

# `# noqa: PTA001 -- reason` (multiple codes comma-separated). The reason
# is MANDATORY; a reasonless suppression trades the finding for a PTA000.
_NOQA_RE = re.compile(
    r"#\s*noqa:\s*(?P<codes>PTA\d{3}(?:\s*,\s*PTA\d{3})*)"
    r"(?:\s*--\s*(?P<reason>\S.*?))?\s*$")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str          # repo-relative, posix separators
    line: int
    col: int
    message: str
    status: str = "active"     # active | suppressed | allowlisted | baselined
    reason: str = ""           # the suppression/allowlist/baseline reason
    fingerprint: str = ""      # stable id for the baseline ratchet

    def format(self) -> str:
        tag = "" if self.status == "active" else f" [{self.status}]"
        return f"{self.path}:{self.line}:{self.col}: {self.rule}{tag} " \
               f"{self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Module:
    """One parsed source file: AST with parent links plus the noqa map."""

    def __init__(self, source: str, rel: str, path: Optional[str] = None):
        self.source = source
        self.rel = rel.replace(os.sep, "/")
        self.path = path
        self.tree = ast.parse(source, filename=rel)
        # One walk serves every rule: parent links plus cached node/call
        # lists (9 rules re-walking 300+ files dominated scan time).
        self.nodes = _astutil.link_and_collect(self.tree)
        self.calls = [n for n in self.nodes if isinstance(n, ast.Call)]
        self.noqa: Dict[int, Tuple[Tuple[str, ...], str]] = {}
        for lineno, line in enumerate(source.splitlines(), start=1):
            m = _NOQA_RE.search(line)
            if m:
                codes = tuple(c.strip()
                              for c in m.group("codes").split(","))
                self.noqa[lineno] = (codes, m.group("reason") or "")

    @classmethod
    def from_file(cls, path: str, root: str) -> "Module":
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        return cls(source, os.path.relpath(path, root), path=path)

    @classmethod
    def from_source(cls, source: str, rel: str = "<synthetic>.py"
                    ) -> "Module":
        return cls(source, rel)


class Rule:
    """Base class. Subclasses set ``code``/``title``/``rationale`` and the
    repo-relative ``scope`` prefixes they sweep, then yield Findings from
    ``check_module`` (per file) and ``finalize`` (repo-level properties
    such as coverage floors — only run on full-default scans)."""

    code = "PTA000"
    title = ""
    rationale = ""
    scope: Tuple[str, ...] = ("paddle_tpu/",)
    exclude: Tuple[str, ...] = ()

    def __init__(self, root: str):
        self.root = root

    def in_scope(self, rel: str) -> bool:
        if any(rel.startswith(p) for p in self.exclude):
            return False
        return any(rel.startswith(p) for p in self.scope)

    def check_module(self, module: Module) -> Iterable[Finding]:
        return ()

    def finalize(self) -> Iterable[Finding]:
        return ()

    def finding(self, module: Module, node, message: str) -> Finding:
        return Finding(self.code, module.rel, getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), message)


_RULE_CLASSES: Dict[str, type] = {}


def register(cls):
    """Class decorator adding a rule to the registry (keyed by code)."""
    _RULE_CLASSES[cls.code] = cls
    return cls


def all_rules() -> Dict[str, type]:
    from . import rules as _rules  # noqa: F401  (registration side effect)
    return dict(sorted(_RULE_CLASSES.items()))


@dataclasses.dataclass
class Report:
    findings: List[Finding]
    rules: List[str]                      # codes that ran
    titles: Dict[str, str]

    @property
    def active(self) -> List[Finding]:
        return [f for f in self.findings if f.status == "active"]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.status == "suppressed"]

    @property
    def allowlisted(self) -> List[Finding]:
        return [f for f in self.findings if f.status == "allowlisted"]

    @property
    def baselined(self) -> List[Finding]:
        return [f for f in self.findings if f.status == "baselined"]

    def counts(self) -> Dict[str, Dict[str, int]]:
        def zero():
            return {"active": 0, "suppressed": 0, "allowlisted": 0,
                    "baselined": 0}
        out = {code: zero() for code in self.rules}
        for f in self.findings:
            out.setdefault(f.rule, zero())[f.status] += 1
        return out

    def to_json(self) -> dict:
        counts = self.counts()
        return {
            "rules": {code: dict(counts[code],
                                 title=self.titles.get(code, ""))
                      for code in sorted(counts)},
            "total_active": len(self.active),
            "total_suppressed": len(self.suppressed),
            "total_allowlisted": len(self.allowlisted),
            "total_baselined": len(self.baselined),
            "findings": [f.to_dict() for f in self.findings],
        }

    def render_text(self, show_all: bool = False) -> str:
        lines = []
        shown = self.findings if show_all else self.active
        for f in sorted(shown, key=lambda f: (f.rule, f.path, f.line)):
            lines.append(f.format())
        counts = self.counts()
        for code in sorted(counts):
            c = counts[code]
            title = self.titles.get(code, "")
            lines.append(f"{code} {title}: active={c['active']} "
                         f"suppressed={c['suppressed']} "
                         f"allowlisted={c['allowlisted']} "
                         f"baselined={c['baselined']}")
        lines.append(f"static-analysis: {len(self.rules)} rules, "
                     f"{len(self.active)} active, "
                     f"{len(self.suppressed)} suppressed, "
                     f"{len(self.allowlisted)} allowlisted, "
                     f"{len(self.baselined)} baselined")
        return "\n".join(lines)


def _collect_py(paths: Iterable[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(os.path.abspath(p))
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append(os.path.abspath(os.path.join(dirpath, f)))
    return sorted(set(out))


def _load_allowlist(path: Optional[str]):
    """{(code, rel-path): reason} from the JSON allowlist; entries missing
    a reason are returned separately so they can surface as PTA000."""
    grants: Dict[Tuple[str, str], str] = {}
    unreasoned: List[Tuple[str, str]] = []
    if path is None or not os.path.exists(path):
        return grants, unreasoned
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    for code, entries in (data.get("rules") or {}).items():
        for entry in entries:
            rel = entry.get("path", "").replace(os.sep, "/")
            reason = (entry.get("reason") or "").strip()
            if not reason:
                unreasoned.append((code, rel))
            grants[(code, rel)] = reason
    return grants, unreasoned


def run(paths: Optional[List[str]] = None,
        rules: Optional[List[str]] = None,
        root: Optional[str] = None,
        allowlist: Optional[str] = DEFAULT_ALLOWLIST,
        respect_scope: bool = True,
        with_floors: Optional[bool] = None) -> Report:
    """Run the selected rules and return a :class:`Report`.

    paths: files/dirs to sweep (default: the paddle_tpu package).
    rules: rule codes to run (default: all registered).
    respect_scope: apply each rule's scope prefixes (turn off to point a
        rule at fixture files outside its normal scope).
    with_floors: run repo-level finalize() checks (coverage floors);
        defaults to True exactly when scanning the default paths.
    """
    root = os.path.abspath(root or REPO_ROOT)
    default_scan = paths is None
    if default_scan:
        paths = [os.path.join(root, p) for p in DEFAULT_SCAN_PATHS
                 if os.path.exists(os.path.join(root, p))]
    if with_floors is None:
        with_floors = default_scan

    classes = all_rules()
    codes = list(classes) if rules is None else list(rules)
    unknown = [c for c in codes if c not in classes]
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(unknown)}; "
                         f"known: {', '.join(classes)}")

    modules = []
    for path in _collect_py(paths):
        try:
            modules.append(Module.from_file(path, root))
        except SyntaxError as exc:
            modules_rel = os.path.relpath(path, root).replace(os.sep, "/")
            raise SyntaxError(
                f"static analysis cannot parse {modules_rel}: {exc}")

    grants, unreasoned = _load_allowlist(allowlist)
    allow_rel = os.path.relpath(allowlist, root).replace(os.sep, "/") \
        if allowlist else "allowlist.json"

    findings: List[Finding] = []
    titles: Dict[str, str] = {}
    for code in codes:
        rule = classes[code](root)
        titles[code] = rule.title
        raw: List[Finding] = []
        for mod in modules:
            if respect_scope and not rule.in_scope(mod.rel):
                continue
            raw.extend(rule.check_module(mod))
        if with_floors:
            raw.extend(rule.finalize())
        findings.extend(raw)

    # suppression + allowlist pass
    noqa_by_rel = {m.rel: m.noqa for m in modules}
    out: List[Finding] = []
    meta: List[Finding] = []
    for f in findings:
        noqa = noqa_by_rel.get(f.path, {}).get(f.line)
        if noqa is not None and f.rule in noqa[0]:
            codes_at_line, reason = noqa
            f.status = "suppressed"
            f.reason = reason
            if not reason:
                meta.append(Finding(
                    "PTA000", f.path, f.line, f.col,
                    f"suppression of {f.rule} lacks a reason — write "
                    f"'# noqa: {f.rule} -- <why>'"))
        elif (f.rule, f.path) in grants:
            f.status = "allowlisted"
            f.reason = grants[(f.rule, f.path)]
        out.append(f)
    for code, rel in unreasoned:
        meta.append(Finding(
            "PTA000", allow_rel, 0, 0,
            f"allowlist entry ({code}, {rel}) lacks a reason"))
    if meta:
        titles["PTA000"] = "reasonless suppression"
    report_rules = codes + (["PTA000"] if meta else [])
    report = Report(out + meta, report_rules, titles)
    _attach_fingerprints(report, {m.rel: m for m in modules})
    return report


# ---------------------------------------------------------------------------
# baseline ratchet (PR 11)
# ---------------------------------------------------------------------------

def _norm_line(source_line: str) -> str:
    return " ".join(source_line.split())


def _attach_fingerprints(report: Report,
                         modules_by_rel: Dict[str, "Module"]) -> None:
    """Stable per-finding ids: sha1 of rule|path|normalized source line|k
    where k disambiguates repeated identical lines in one file (ordered
    by line number, so an unrelated edit above a finding cannot shift its
    fingerprint the way a raw line number would)."""
    groups: Dict[Tuple[str, str, str], List[Finding]] = {}
    for f in report.findings:
        mod = modules_by_rel.get(f.path)
        if mod is None:
            text = ""
        else:
            lines = mod.source.splitlines()
            text = _norm_line(lines[f.line - 1]) if \
                0 < f.line <= len(lines) else ""
        groups.setdefault((f.rule, f.path, text), []).append(f)
    for (rule, path, text), fs in groups.items():
        for k, f in enumerate(sorted(fs, key=lambda f: (f.line, f.col))):
            raw = f"{rule}|{path}|{text}|{k}"
            f.fingerprint = hashlib.sha1(raw.encode()).hexdigest()[:16]


def load_baseline(path: Optional[str] = None) -> Dict[str, dict]:
    """{fingerprint: entry} from baseline.json (empty when absent)."""
    path = path or DEFAULT_BASELINE
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    out = {}
    for code, entries in (data.get("rules") or {}).items():
        for entry in entries:
            out[entry["fingerprint"]] = dict(entry, rule=code)
    return out


def write_baseline(report: Report, path: Optional[str] = None) -> dict:
    """Snapshot the report's active findings as the new frozen debt."""
    path = path or DEFAULT_BASELINE
    rules: Dict[str, List[dict]] = {}
    for f in sorted(report.active, key=lambda f: (f.rule, f.path, f.line)):
        rules.setdefault(f.rule, []).append({
            "fingerprint": f.fingerprint,
            "path": f.path,
            "line": f.line,
            "message": f.message,
        })
    data = {
        "_comment": ("frozen pre-existing findings (--baseline write); "
                     "CI fails on NEW findings and on stale entries, so "
                     "this list only ever shrinks"),
        "count": sum(len(v) for v in rules.values()),
        "rules": rules,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return data


def apply_baseline(report: Report,
                   baseline: Optional[Dict[str, dict]] = None,
                   path: Optional[str] = None) -> List[dict]:
    """Mark active findings matching the baseline as ``baselined``
    (in place) and return the STALE baseline entries — fingerprints whose
    finding no longer exists. Callers fail the ratchet check when either
    ``report.active`` (new findings) or the returned stale list is
    non-empty."""
    if baseline is None:
        baseline = load_baseline(path)
    matched = set()
    for f in report.findings:
        if f.status == "active" and f.fingerprint in baseline:
            f.status = "baselined"
            f.reason = "frozen in baseline.json (pre-existing debt)"
            matched.add(f.fingerprint)
    return [entry for fp, entry in sorted(baseline.items())
            if fp not in matched]
