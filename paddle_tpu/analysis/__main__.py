"""CLI for ``paddle_tpu.analysis``.

    python -m paddle_tpu.analysis [--strict] [--rule PTA001] [--json] [paths]

Exit status: 0 when no active findings (or not --strict); 1 when --strict
and active findings remain; 2 on usage errors.
"""
from __future__ import annotations

import argparse
import json
import sys

from . import DEFAULT_ALLOWLIST, all_rules, run


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="repo-specific static analysis (AST-based; never "
                    "imports the checked modules)")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to sweep (default: the "
                             "paddle_tpu package)")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 if any active finding remains")
    parser.add_argument("--rule", action="append", dest="rules",
                        metavar="PTA###",
                        help="run only this rule (repeatable)")
    parser.add_argument("--json", action="store_true",
                        help="emit the machine-readable findings record")
    parser.add_argument("--list-rules", action="store_true",
                        help="list registered rules and exit")
    parser.add_argument("--no-scope", action="store_true",
                        help="ignore per-rule scope prefixes (fixture runs)")
    parser.add_argument("--no-floors", action="store_true",
                        help="skip repo-level coverage-floor checks")
    parser.add_argument("--allowlist", default=DEFAULT_ALLOWLIST,
                        help="allowlist JSON path (default: the in-package "
                             "allowlist.json)")
    args = parser.parse_args(argv)

    if args.list_rules:
        for code, cls in all_rules().items():
            print(f"{code} {cls.title}: {cls.rationale}")
        return 0

    try:
        report = run(paths=args.paths or None,
                     rules=args.rules,
                     allowlist=args.allowlist,
                     respect_scope=not args.no_scope,
                     with_floors=False if args.no_floors else None)
    except (ValueError, SyntaxError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps(report.to_json(), indent=2, sort_keys=True))
    else:
        print(report.render_text())
    if args.strict and report.active:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
