"""CLI for ``paddle_tpu.analysis``.

    python -m paddle_tpu.analysis [--strict] [--rule PTA001] [--json]
                                  [--baseline write|check] [paths]

Exit status: 0 when no active findings (or not --strict); 1 when --strict
and active findings remain, or when --baseline check finds new findings /
stale baseline entries; 2 on usage errors.
"""
from __future__ import annotations

import argparse
import json
import sys

from . import (DEFAULT_ALLOWLIST, DEFAULT_BASELINE, all_rules,
               apply_baseline, run, write_baseline)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.analysis",
        description="repo-specific static analysis (AST-based; never "
                    "imports the checked modules)")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to sweep (default: the "
                             "paddle_tpu package)")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 if any active finding remains")
    parser.add_argument("--rule", action="append", dest="rules",
                        metavar="PTA###",
                        help="run only this rule (repeatable)")
    parser.add_argument("--json", action="store_true",
                        help="emit the machine-readable findings record")
    parser.add_argument("--list-rules", action="store_true",
                        help="list registered rules and exit")
    parser.add_argument("--no-scope", action="store_true",
                        help="ignore per-rule scope prefixes (fixture runs)")
    parser.add_argument("--no-floors", action="store_true",
                        help="skip repo-level coverage-floor checks")
    parser.add_argument("--allowlist", default=DEFAULT_ALLOWLIST,
                        help="allowlist JSON path (default: the in-package "
                             "allowlist.json)")
    parser.add_argument("--baseline", choices=("write", "check"),
                        help="ratchet: 'write' snapshots active findings "
                             "into baseline.json; 'check' passes pre-frozen "
                             "findings but fails on new findings and on "
                             "stale (already-fixed) baseline entries")
    parser.add_argument("--baseline-file", default=DEFAULT_BASELINE,
                        help="baseline JSON path (default: the in-package "
                             "baseline.json)")
    args = parser.parse_args(argv)

    if args.list_rules:
        for code, cls in all_rules().items():
            print(f"{code} {cls.title}: {cls.rationale}")
        return 0

    try:
        report = run(paths=args.paths or None,
                     rules=args.rules,
                     allowlist=args.allowlist,
                     respect_scope=not args.no_scope,
                     with_floors=False if args.no_floors else None)
    except (ValueError, SyntaxError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    stale = []
    if args.baseline == "write":
        data = write_baseline(report, path=args.baseline_file)
        print(f"baseline: wrote {data['count']} finding(s) to "
              f"{args.baseline_file}")
        apply_baseline(report, path=args.baseline_file)
    elif args.baseline == "check":
        stale = apply_baseline(report, path=args.baseline_file)

    if args.json:
        print(json.dumps(report.to_json(), indent=2, sort_keys=True))
    else:
        print(report.render_text())

    rc = 0
    if args.baseline == "check":
        if report.active:
            print(f"baseline check: {len(report.active)} NEW finding(s) "
                  f"not in the frozen baseline", file=sys.stderr)
            rc = 1
        if stale:
            for entry in stale:
                print(f"baseline check: stale entry "
                      f"{entry['rule']} {entry['path']}:{entry['line']} — "
                      f"finding fixed; re-run --baseline write to shrink "
                      f"the snapshot", file=sys.stderr)
            rc = 1
    if args.strict and report.active:
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
