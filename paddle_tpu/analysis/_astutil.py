"""Shared AST helpers for the static-analysis rules.

Everything here operates on plain ``ast`` trees — the checked modules are
never imported, so rules run identically whether or not jax (or the
repo's native runtime) is importable.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

PARENT_ATTR = "_pta_parent"


def link_parents(tree: ast.AST) -> ast.AST:
    """Attach a ``_pta_parent`` attribute to every node."""
    link_and_collect(tree)
    return tree


def link_and_collect(tree: ast.AST) -> List[ast.AST]:
    """Attach parent links and return every node, in one BFS walk.

    Same visit order as ``ast.walk``.  ``Module`` caches the result so
    rules iterate ``module.nodes``/``module.calls`` instead of
    re-walking the full tree once per rule."""
    from collections import deque
    nodes: List[ast.AST] = []
    todo = deque([tree])
    while todo:
        node = todo.popleft()
        nodes.append(node)
        for child in ast.iter_child_nodes(node):
            setattr(child, PARENT_ATTR, node)
            todo.append(child)
    return nodes


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, PARENT_ATTR, None)


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jnp.full' for Attribute chains rooted at a Name; None otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_ident(call: ast.Call) -> Optional[str]:
    """Last path segment of the callee: pl.pallas_call -> 'pallas_call'."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def call_root(call: ast.Call) -> Optional[str]:
    """First path segment of the callee: jnp.full -> 'jnp'."""
    fn = call.func
    while isinstance(fn, ast.Attribute):
        fn = fn.value
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def keyword(call: ast.Call, name: str) -> Optional[ast.keyword]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw
    return None


def number_of(node: ast.AST):
    """(value, True) when the node is a bare int/float literal, unwrapping
    unary +/-; (None, False) otherwise. bools are NOT numbers here."""
    neg = False
    while isinstance(node, ast.UnaryOp) and isinstance(
            node.op, (ast.USub, ast.UAdd)):
        if isinstance(node.op, ast.USub):
            neg = not neg
        node = node.operand
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float)) and not isinstance(node.value, bool):
        return (-node.value if neg else node.value), True
    return None, False


def is_bare_number(node: ast.AST) -> bool:
    return number_of(node)[1]


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def iter_calls(tree: ast.AST) -> Iterator[ast.Call]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node


def enclosing_function(node: ast.AST):
    """Nearest enclosing FunctionDef/AsyncFunctionDef (or None)."""
    cur = parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = parent(cur)
    return None


def numpy_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to the numpy module ('np', '_np', 'numpy', ...)."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    out.add(a.asname or "numpy")
    return out


def envs_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to the paddle_tpu.envs module."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if node.level > 0 or mod == "paddle_tpu" or \
                    mod.endswith(".paddle_tpu"):
                for a in node.names:
                    if a.name == "envs":
                        out.add(a.asname or "envs")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "paddle_tpu.envs" or a.name.endswith(".envs"):
                    out.add(a.asname or "envs")
    return out


class ConstEnv:
    """Best-effort constant folder over one function's (and the module's)
    straight-line ``name = <literal expr>`` assignments. Supports ints
    through +,-,*,//,%,**, min/max and tuple unpacking — enough to resolve
    the literal BlockSpec shapes the VMEM rule prices. Anything else
    resolves to None ("unknown"), never a wrong number.

    ``bindings`` pre-seeds names with caller-side expressions (the
    dataflow layer binds helper parameters to call-site arguments so a
    rule can see through one level of helper calls); bindings win over
    same-named assignments collected from the trees."""

    def __init__(self, module_tree: ast.AST, func: Optional[ast.AST] = None,
                 bindings: Optional[Dict[str, ast.AST]] = None):
        self._env: Dict[str, ast.AST] = {}
        self._collect(module_tree, toplevel_only=True)
        if func is not None:
            self._collect(func, toplevel_only=False)
        if bindings:
            self._env.update(bindings)
        self._resolving: Set[str] = set()

    def lookup(self, name: str) -> Optional[ast.AST]:
        """The AST node ``name`` was last straight-line-assigned to."""
        return self._env.get(name)

    def _collect(self, tree, toplevel_only):
        nodes = tree.body if toplevel_only else ast.walk(tree)
        for node in nodes:
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self._env[tgt.id] = node.value
                elif isinstance(tgt, ast.Tuple) and isinstance(
                        node.value, ast.Tuple) and \
                        len(tgt.elts) == len(node.value.elts):
                    for t, v in zip(tgt.elts, node.value.elts):
                        if isinstance(t, ast.Name):
                            self._env[t.id] = v

    def resolve(self, node: ast.AST):
        """int/float value of the expression, or None when unknown."""
        val, ok = number_of(node)
        if ok:
            return val
        if isinstance(node, ast.Name):
            if node.id in self._resolving or node.id not in self._env:
                return None
            self._resolving.add(node.id)
            try:
                return self.resolve(self._env[node.id])
            finally:
                self._resolving.discard(node.id)
        if isinstance(node, ast.BinOp):
            lhs = self.resolve(node.left)
            rhs = self.resolve(node.right)
            if lhs is None or rhs is None:
                return None
            try:
                if isinstance(node.op, ast.Add):
                    return lhs + rhs
                if isinstance(node.op, ast.Sub):
                    return lhs - rhs
                if isinstance(node.op, ast.Mult):
                    return lhs * rhs
                if isinstance(node.op, ast.FloorDiv):
                    return lhs // rhs
                if isinstance(node.op, ast.Mod):
                    return lhs % rhs
                if isinstance(node.op, ast.Pow):
                    return lhs ** rhs
            except (ZeroDivisionError, OverflowError):
                return None
            return None
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in ("min", "max") and not node.keywords:
            vals = [self.resolve(a) for a in node.args]
            if any(v is None for v in vals) or not vals:
                return None
            return (min if node.func.id == "min" else max)(vals)
        return None

    def resolve_str(self, node: ast.AST) -> Optional[str]:
        """String value of the expression (literal or through one or more
        straight-line assignments / bindings), or None when unknown."""
        s = str_const(node)
        if s is not None:
            return s
        if isinstance(node, ast.Name):
            if node.id in self._resolving or node.id not in self._env:
                return None
            self._resolving.add(node.id)
            try:
                return self.resolve_str(self._env[node.id])
            finally:
                self._resolving.discard(node.id)
        return None

    def resolve_node(self, node: ast.AST, depth: int = 4) -> ast.AST:
        """Chase Name -> assigned-node chains, returning the deepest
        non-Name node reachable (or the original node)."""
        while depth > 0 and isinstance(node, ast.Name) \
                and node.id in self._env:
            nxt = self._env[node.id]
            if nxt is node:
                break
            node = nxt
            depth -= 1
        return node


# ---------------------------------------------------------------------------
# dataflow layer (PR 11): per-module call-graph resolution, parameter
# binding, symbolic affine arithmetic, dtype propagation and the
# with/try-finally scope model. Everything stays AST-only — helpers are
# resolved by PARSING, never importing, the modules involved.
# ---------------------------------------------------------------------------

#: dtype-constructor suffixes recognized by :func:`resolve_dtype_name`
DTYPE_NAMES = frozenset({
    "float64", "float32", "float16", "bfloat16",
    "int64", "int32", "int16", "int8",
    "uint64", "uint32", "uint8", "bool_", "bool",
})


def resolve_dtype_name(node: ast.AST,
                       env: Optional["ConstEnv"] = None) -> Optional[str]:
    """'float32' for ``jnp.float32`` / ``np.float32`` / ``'float32'`` /
    a Name straight-line-assigned to one of those; None when unknown.
    This is the assignment-chain dtype propagation the Pallas grid
    auditor uses to type accumulation scratch."""
    if env is not None:
        node = env.resolve_node(node)
    lit = str_const(node)
    if lit is not None:
        return lit if lit in DTYPE_NAMES else None
    name = dotted_name(node)
    if name is not None:
        tail = name.rsplit(".", 1)[-1]
        if tail in DTYPE_NAMES:
            return tail
    return None


class FunctionIndex:
    """Module-level ``def``s by name (the intra-module half of call-graph
    resolution). Nested defs and methods are deliberately out: the helper
    conventions this repo lints (_mask_*, _fit_*, island bodies) are all
    module-level functions."""

    def __init__(self, module_tree: ast.AST):
        self.functions: Dict[str, ast.FunctionDef] = {}
        for node in ast.iter_child_nodes(module_tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node

    def get(self, name: Optional[str]) -> Optional[ast.FunctionDef]:
        if name is None:
            return None
        return self.functions.get(name)


def bind_call_args(func: ast.FunctionDef,
                   call: ast.Call) -> Dict[str, ast.AST]:
    """{param name: caller-side AST node} for one call of a resolved
    local function — positional args, keywords and defaults, skipping
    */** (best-effort; a partial binding is still useful)."""
    params = [a.arg for a in func.args.args]
    binding: Dict[str, ast.AST] = {}
    defaults = func.args.defaults
    if defaults:
        for name, default in zip(params[-len(defaults):], defaults):
            binding[name] = default
    for kwarg, default in zip(func.args.kwonlyargs, func.args.kw_defaults):
        if default is not None:
            binding[kwarg.arg] = default
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            break
        if i < len(params):
            binding[params[i]] = arg
    kwonly = {a.arg for a in func.args.kwonlyargs}
    for kw in call.keywords:
        if kw.arg is not None and (kw.arg in params or kw.arg in kwonly):
            binding[kw.arg] = kw.value
    return binding


def resolve_local_call(call: ast.Call, index: FunctionIndex,
                       env: Optional[ConstEnv] = None
                       ) -> Optional[Tuple[ast.FunctionDef,
                                           Dict[str, ast.AST]]]:
    """(FunctionDef, param binding) when ``call`` resolves to a module-
    level function — directly (``helper(...)``), through a straight-line
    alias, or through ``functools.partial(helper, ...)`` (the shard_map
    island-body idiom, where the partial's args pre-bind parameters)."""
    fn = call.func
    if env is not None and isinstance(fn, ast.Name):
        resolved = env.resolve_node(fn)
        if isinstance(resolved, ast.Lambda):
            return None
        if isinstance(resolved, ast.Call):
            # name assigned to a partial(...) — unwrap below
            return _resolve_partial(resolved, index, call)
    target = index.get(fn.id if isinstance(fn, ast.Name) else None)
    if target is not None:
        return target, bind_call_args(target, call)
    return None


def _resolve_partial(partial_call: ast.Call, index: FunctionIndex,
                     outer_call: Optional[ast.Call]):
    if call_ident(partial_call) != "partial" or not partial_call.args:
        return None
    inner = partial_call.args[0]
    target = index.get(inner.id if isinstance(inner, ast.Name) else None)
    if target is None:
        return None
    params = [a.arg for a in target.args.args]
    # defaults + the partial's keyword args only: the partial's
    # positionals are shifted by one (args[0] is the callee) and are
    # bound explicitly below
    binding = bind_call_args(target, ast.Call(
        func=partial_call.func, args=[], keywords=partial_call.keywords))
    # partial's leading positionals bind the leading params
    for i, arg in enumerate(partial_call.args[1:]):
        if i < len(params):
            binding[params[i]] = arg
    n_bound_pos = len(partial_call.args) - 1
    if outer_call is not None:
        for i, arg in enumerate(outer_call.args):
            j = n_bound_pos + i
            if j < len(params) and params[j] not in binding:
                binding[params[j]] = arg
        kwonly = {a.arg for a in target.args.kwonlyargs}
        for kw in outer_call.keywords:
            if kw.arg is not None and (kw.arg in params or kw.arg in kwonly):
                binding[kw.arg] = kw.value
    return target, binding


def resolve_callable(node: ast.AST, index: FunctionIndex,
                     env: Optional[ConstEnv] = None
                     ) -> Optional[Tuple[ast.AST, Dict[str, ast.AST]]]:
    """Resolve a callable-position expression (a shard_map body, an
    index_map) to (Lambda | FunctionDef, binding). Handles a direct
    lambda, a module-level def name, a straight-line alias to either,
    and ``functools.partial(def, ...)``."""
    if env is not None:
        node = env.resolve_node(node)
    if isinstance(node, ast.Lambda):
        return node, {}
    if isinstance(node, ast.Name):
        target = index.get(node.id)
        if target is not None:
            return target, {}
        return None
    if isinstance(node, ast.Call):
        resolved = _resolve_partial(node, index, None)
        if resolved is not None:
            return resolved
    return None


def affine_of(node: ast.AST, env: Optional[ConstEnv] = None
              ) -> Optional[Tuple[Optional[str], int]]:
    """(symbol, offset) for expressions of the shape ``sym + c`` /
    ``sym - c`` / plain constants (symbol None). The symbol is the
    canonical ``ast.dump`` of the non-constant part after chasing
    straight-line assignments — enough symbolic arithmetic to compare a
    comprehension's range bound against a mesh-axis size without knowing
    either number."""
    if env is not None:
        node = env.resolve_node(node)
    val, ok = number_of(node)
    if ok and isinstance(val, int):
        return None, val
    if isinstance(node, ast.BinOp) and isinstance(node.op, (ast.Add,
                                                            ast.Sub)):
        lhs = affine_of(node.left, env)
        rhs = affine_of(node.right, env)
        if lhs is None or rhs is None:
            return None
        sign = 1 if isinstance(node.op, ast.Add) else -1
        if rhs[0] is None:
            return lhs[0], lhs[1] + sign * rhs[1]
        if lhs[0] is None and sign == 1:
            return rhs[0], lhs[1] + rhs[1]
        return None
    return ast.dump(node), 0


def contains_name(node: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(node))


# --- with / try-finally scope model ----------------------------------------

def enclosing_tries(node: ast.AST) -> List[ast.Try]:
    """Innermost-first Try statements whose *protected region* (body or
    orelse — NOT the finalbody or handlers) contains ``node``."""
    out = []
    cur, prev = parent(node), node
    while cur is not None:
        if isinstance(cur, ast.Try):
            region = list(cur.body) + list(cur.orelse)
            if any(prev is stmt or _contains(stmt, prev)
                   for stmt in region):
                out.append(cur)
        prev, cur = cur, parent(cur)
    return out


def _contains(tree: ast.AST, node: ast.AST) -> bool:
    return any(n is node for n in ast.walk(tree))


def enclosing_withs(node: ast.AST) -> List[ast.With]:
    """Innermost-first With statements whose body contains ``node``."""
    out = []
    cur = parent(node)
    while cur is not None:
        if isinstance(cur, ast.With):
            out.append(cur)
        cur = parent(cur)
    return out


def decorator_names(func: ast.AST) -> Set[str]:
    """Last-segment names of a function's decorators:
    ``@contextlib.contextmanager`` -> {'contextmanager'};
    ``@pytest.fixture(scope=...)`` -> {'fixture'}."""
    out = set()
    for dec in getattr(func, "decorator_list", ()):
        if isinstance(dec, ast.Call):
            dec = dec.func
        name = dotted_name(dec)
        if name:
            out.add(name.rsplit(".", 1)[-1])
    return out


def statements_after_yield(func: ast.AST) -> List[ast.stmt]:
    """Top-to-bottom statements of ``func`` that appear strictly after
    its first ``yield`` (generator-fixture teardown code). Statements in
    the same Try as the yield count when they are in the finalbody."""
    yields = [n for n in ast.walk(func) if isinstance(n, ast.Yield)]
    if not yields:
        return []
    first = min(yields, key=lambda n: n.lineno)
    out = []
    for node in ast.walk(func):
        if isinstance(node, ast.stmt) and node.lineno > first.lineno:
            out.append(node)
    return out
