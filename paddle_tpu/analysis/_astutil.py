"""Shared AST helpers for the static-analysis rules.

Everything here operates on plain ``ast`` trees — the checked modules are
never imported, so rules run identically whether or not jax (or the
repo's native runtime) is importable.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set

PARENT_ATTR = "_pta_parent"


def link_parents(tree: ast.AST) -> ast.AST:
    """Attach a ``_pta_parent`` attribute to every node."""
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            setattr(child, PARENT_ATTR, node)
    return tree


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, PARENT_ATTR, None)


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jnp.full' for Attribute chains rooted at a Name; None otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_ident(call: ast.Call) -> Optional[str]:
    """Last path segment of the callee: pl.pallas_call -> 'pallas_call'."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def call_root(call: ast.Call) -> Optional[str]:
    """First path segment of the callee: jnp.full -> 'jnp'."""
    fn = call.func
    while isinstance(fn, ast.Attribute):
        fn = fn.value
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def keyword(call: ast.Call, name: str) -> Optional[ast.keyword]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw
    return None


def number_of(node: ast.AST):
    """(value, True) when the node is a bare int/float literal, unwrapping
    unary +/-; (None, False) otherwise. bools are NOT numbers here."""
    neg = False
    while isinstance(node, ast.UnaryOp) and isinstance(
            node.op, (ast.USub, ast.UAdd)):
        if isinstance(node.op, ast.USub):
            neg = not neg
        node = node.operand
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float)) and not isinstance(node.value, bool):
        return (-node.value if neg else node.value), True
    return None, False


def is_bare_number(node: ast.AST) -> bool:
    return number_of(node)[1]


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def iter_calls(tree: ast.AST) -> Iterator[ast.Call]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node


def enclosing_function(node: ast.AST):
    """Nearest enclosing FunctionDef/AsyncFunctionDef (or None)."""
    cur = parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = parent(cur)
    return None


def numpy_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to the numpy module ('np', '_np', 'numpy', ...)."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    out.add(a.asname or "numpy")
    return out


def envs_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to the paddle_tpu.envs module."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if node.level > 0 or mod == "paddle_tpu" or \
                    mod.endswith(".paddle_tpu"):
                for a in node.names:
                    if a.name == "envs":
                        out.add(a.asname or "envs")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "paddle_tpu.envs" or a.name.endswith(".envs"):
                    out.add(a.asname or "envs")
    return out


class ConstEnv:
    """Best-effort constant folder over one function's (and the module's)
    straight-line ``name = <literal expr>`` assignments. Supports ints
    through +,-,*,//,**, min/max and tuple unpacking — enough to resolve
    the literal BlockSpec shapes the VMEM rule prices. Anything else
    resolves to None ("unknown"), never a wrong number."""

    def __init__(self, module_tree: ast.AST, func: Optional[ast.AST] = None):
        self._env: Dict[str, ast.AST] = {}
        self._collect(module_tree, toplevel_only=True)
        if func is not None:
            self._collect(func, toplevel_only=False)
        self._resolving: Set[str] = set()

    def _collect(self, tree, toplevel_only):
        nodes = tree.body if toplevel_only else ast.walk(tree)
        for node in nodes:
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self._env[tgt.id] = node.value
                elif isinstance(tgt, ast.Tuple) and isinstance(
                        node.value, ast.Tuple) and \
                        len(tgt.elts) == len(node.value.elts):
                    for t, v in zip(tgt.elts, node.value.elts):
                        if isinstance(t, ast.Name):
                            self._env[t.id] = v

    def resolve(self, node: ast.AST):
        """int/float value of the expression, or None when unknown."""
        val, ok = number_of(node)
        if ok:
            return val
        if isinstance(node, ast.Name):
            if node.id in self._resolving or node.id not in self._env:
                return None
            self._resolving.add(node.id)
            try:
                return self.resolve(self._env[node.id])
            finally:
                self._resolving.discard(node.id)
        if isinstance(node, ast.BinOp):
            lhs = self.resolve(node.left)
            rhs = self.resolve(node.right)
            if lhs is None or rhs is None:
                return None
            try:
                if isinstance(node.op, ast.Add):
                    return lhs + rhs
                if isinstance(node.op, ast.Sub):
                    return lhs - rhs
                if isinstance(node.op, ast.Mult):
                    return lhs * rhs
                if isinstance(node.op, ast.FloorDiv):
                    return lhs // rhs
                if isinstance(node.op, ast.Pow):
                    return lhs ** rhs
            except (ZeroDivisionError, OverflowError):
                return None
            return None
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in ("min", "max") and not node.keywords:
            vals = [self.resolve(a) for a in node.args]
            if any(v is None for v in vals) or not vals:
                return None
            return (min if node.func.id == "min" else max)(vals)
        return None
