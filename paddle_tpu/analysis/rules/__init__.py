"""Rule modules — importing this package registers every rule."""
from . import pta001_weak_scalar  # noqa: F401
from . import pta002_vmem_budget  # noqa: F401
from . import pta003_cost_estimate  # noqa: F401
from . import pta004_comm_span  # noqa: F401
from . import pta005_env_knobs  # noqa: F401
from . import pta006_host_sync  # noqa: F401
from . import pta007_global_state  # noqa: F401
from . import pta008_collectives  # noqa: F401
from . import pta009_pallas_grid  # noqa: F401
