"""PTA001: weak-typed Python scalars at known weak-type sinks.

The bug class: the package enables x64 globally (Paddle's int64 default),
so a bare Python literal flowing into a jax op is a WEAK f64/i64 scalar.
Inside a Pallas kernel body that is usually harmless at trace time — the
strong operand wins the promotion — but when the kernel is lowered again
under a consumer jit (shard_map islands, the serving engine's compiled
families), the constant can be re-canonicalized to f64/i64 and trip the
MLIR verifier. This bit PR 6 (decode_attention/paged_attention scalar
args) and PR 7 (_mask_scores' bare ``-1e30``) in consecutive rounds.

The rule flags bare int/float literals in ops/ and parallel/ at the sinks
the class has actually used:

  * ``where(cond, x, <literal>)`` / ``where(cond, <literal>, y)``
    (and ``lax.select``) — the _mask_scores shape;
  * ``full``/``full_like`` fill values without an explicit ``dtype=``;
  * ``asarray``/``array`` of a literal without an explicit dtype;
  * float literals with |v| >= 1e6 anywhere else (mask constants passed
    as scalar args) unless already wrapped in a dtype constructor.

Fix by wrapping: ``jnp.float32(-1e30)`` / ``np.int32(0)`` (bitwise
identical for exactly-representable values, and strongly typed so x64
cannot re-canonicalize them).
"""
from __future__ import annotations

import ast

from .. import Rule, register
from .._astutil import (FunctionIndex, call_ident, call_root, is_bare_number,
                        iter_calls, keyword, number_of, parent,
                        resolve_local_call)

# dtype constructors that make a literal strongly typed
_CASTERS = frozenset({
    "float32", "float64", "float16", "bfloat16",
    "int8", "int16", "int32", "int64", "uint8", "uint32", "uint64",
})

# sinks whose literal args the x64 class has actually hit
_WHERE_LIKE = frozenset({"where", "select"})
_FULL_LIKE = frozenset({"full", "full_like"})
_ASARRAY_LIKE = frozenset({"asarray", "array"})

_BIG_FLOAT = 1e6  # mask constants (-1e30, 1e9, ...) are never "just math"


def _wrap_hint(value):
    if isinstance(value, float):
        return f"jnp.float32({value!r})"
    return f"np.int32({value!r})"


def _params_at_where_sinks(func):
    """Parameter names of ``func`` that appear as a where()/select()
    branch argument in its body — a literal bound to one of these at a
    call site is the same weak-scalar bug, one hop removed (the v1
    engine's known false-negative class)."""
    names = set()
    for call in iter_calls(func):
        if call_ident(call) not in _WHERE_LIKE:
            continue
        for arg in call.args[1:3]:
            if isinstance(arg, ast.Name):
                names.add(arg.id)
    return names


@register
class WeakScalarRule(Rule):
    code = "PTA001"
    title = "weak-scalar"
    rationale = ("bare Python literals are weak-typed under the package-"
                 "global x64 and re-canonicalize to f64/i64 when kernels "
                 "lower under consumer jits (PR-6/PR-7 MLIR-verifier "
                 "class)")
    scope = ("paddle_tpu/ops/", "paddle_tpu/parallel/")

    def check_module(self, module):
        flagged = set()
        index = FunctionIndex(module.tree)
        sink_params = {}  # helper name -> params reaching a where sink
        for call in module.calls:
            ident = call_ident(call)
            # interprocedural hop: a bare literal bound to a local
            # helper's parameter that lands in a where()/select() branch
            resolved = resolve_local_call(call, index)
            if resolved is not None:
                helper, binding = resolved
                if helper.name not in sink_params:
                    sink_params[helper.name] = _params_at_where_sinks(helper)
                for pname in sink_params[helper.name]:
                    arg = binding.get(pname)
                    if arg is None or id(arg) in flagged:
                        continue
                    val, ok = number_of(arg)
                    if ok and arg in call.args + [
                            kw.value for kw in call.keywords]:
                        flagged.add(id(arg))
                        yield self.finding(
                            module, arg,
                            f"weak {type(val).__name__} literal {val!r} "
                            f"bound to {helper.name}(...{pname}...) which "
                            f"uses it as a where()/select() branch; wrap "
                            f"it ({_wrap_hint(val)}) at the call site")
            if ident in _WHERE_LIKE:
                for arg in call.args[1:3]:
                    val, ok = number_of(arg)
                    if ok:
                        flagged.add(id(arg))
                        yield self.finding(
                            module, arg,
                            f"weak {type(val).__name__} literal {val!r} as "
                            f"a {ident}() branch; wrap it "
                            f"({_wrap_hint(val)}) so the package-global "
                            f"x64 cannot re-canonicalize it")
            elif ident in _FULL_LIKE:
                if len(call.args) >= 2 and is_bare_number(call.args[1]) \
                        and len(call.args) < 3 \
                        and keyword(call, "dtype") is None:
                    val, _ = number_of(call.args[1])
                    yield self.finding(
                        module, call.args[1],
                        f"weak {type(val).__name__} literal {val!r} as "
                        f"{ident}() fill value without dtype=; pass an "
                        f"explicit dtype or wrap it ({_wrap_hint(val)})")
            elif ident in _ASARRAY_LIKE:
                if call.args and is_bare_number(call.args[0]) \
                        and len(call.args) < 2 \
                        and keyword(call, "dtype") is None:
                    val, _ = number_of(call.args[0])
                    yield self.finding(
                        module, call.args[0],
                        f"weak {type(val).__name__} literal {val!r} in "
                        f"{ident}() without dtype=; it canonicalizes to "
                        f"f64/i64 under x64")
        # big float constants anywhere else (scalar-arg class): literal
        # mask values must ride wrapped in a dtype constructor
        for node in module.nodes:
            # a Constant under a unary +/- is visited via its UnaryOp
            if isinstance(node, ast.Constant) and \
                    isinstance(parent(node), ast.UnaryOp):
                continue
            val, ok = number_of(node)
            if not ok or not isinstance(val, float) or abs(val) < _BIG_FLOAT:
                continue
            if id(node) in flagged:
                continue
            # walk out of the unary +/- wrapper to the real parent
            outer = node
            p = parent(outer)
            while isinstance(p, ast.UnaryOp):
                outer = p
                p = parent(outer)
            if isinstance(p, ast.Call):
                ident = call_ident(p)
                if ident in _CASTERS or ident in _WHERE_LIKE \
                        or ident in _FULL_LIKE or ident in _ASARRAY_LIKE:
                    continue  # wrapped, or already handled above
                if keyword(p, "dtype") is not None and call_root(p) in (
                        "np", "jnp", "numpy"):
                    continue  # np/jnp ctor with explicit dtype
            if isinstance(node, ast.Constant) and isinstance(p, ast.Expr):
                continue  # docstring-adjacent bare constant statement
            yield self.finding(
                module, outer,
                f"weak float mask constant {val!r} outside a dtype "
                f"constructor; wrap it ({_wrap_hint(val)}) before it "
                f"flows into a kernel")
