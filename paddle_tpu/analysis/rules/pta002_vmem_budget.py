"""PTA002: static per-``pallas_call`` VMEM budget.

Each grid step of a Pallas kernel holds every BlockSpec window twice
(Mosaic double-buffers the in/out DMA windows) plus its scratch. A site
whose statically-priced windows exceed the budget will compile-fail (or
silently thrash) only on hardware — the interpret-mode CPU tests never
see it. This bit the repo twice before PR 4/PR 7 grew *fitters*
(``_fit_block_t``, ``_fit_bwd_flat_blocks``) that shrink blocks until
the windows fit a measured budget.

The rule prices every ``pallas_call``'s BlockSpec shapes (constant-folded
through straight-line assignments) at ``2 x prod(shape) x itemsize`` for
in/out specs plus ``prod x itemsize`` for VMEM scratch, and flags sites
over budget. Sites whose block shapes come from a registered fitter
(``_fit_*``) are exempt — sizing is the fitter's contract — and shapes
that cannot be resolved statically (caller-threaded block params) are
skipped rather than guessed.
"""
from __future__ import annotations

import ast

from .. import Rule, register
from .._astutil import (ConstEnv, FunctionIndex, call_ident, dotted_name,
                        enclosing_function, iter_calls, keyword,
                        resolve_local_call)

# conservative ceiling: the largest fitted budget in tree is the dense
# flash backward's 52 MB scratch+window set; anything statically priced
# above this is far outside what any TPU generation's scoped VMEM plus
# compiler spilling absorbs, and must route through a fitter instead.
BUDGET_BYTES = 64 * 1024 * 1024

# itemsize when a BlockSpec's operand dtype is unknown (f32 accumulators
# dominate the kernels here; bf16 operands under-price by 2x, which only
# makes the rule more permissive, never a false positive)
DEFAULT_ITEMSIZE = 4

_DTYPE_SIZES = {
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2,
    "int8": 1, "uint8": 1, "bool_": 1, "bool": 1,
}

# names a block-shape element may come from to mark the site fitter-sized
FITTER_PREFIX = "_fit"
REGISTERED_FITTERS = frozenset({"_fit_block_t", "_fit_bwd_flat_blocks",
                               "_fit_paged_kv_blocks",
                               "_fit_paged_verify_blocks"})


def _is_fitter(name):
    return name is not None and (name in REGISTERED_FITTERS
                                 or name.startswith(FITTER_PREFIX))


def _names_in(node):
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _fitter_derived_names(func):
    """Names assigned (directly or via tuple unpack) from a _fit_* call
    anywhere in the enclosing function."""
    out = set()
    if func is None:
        return out
    for node in ast.walk(func):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        calls = [c for c in ast.walk(value) if isinstance(c, ast.Call)]
        if not any(_is_fitter(call_ident(c)) for c in calls):
            continue
        for tgt in node.targets:
            out.update(_names_in(tgt))
    return out


def _scratch_itemsize(call):
    if len(call.args) >= 2:
        name = (dotted_name(call.args[1]) or "").rsplit(".", 1)[-1]
        return _DTYPE_SIZES.get(name, DEFAULT_ITEMSIZE)
    return DEFAULT_ITEMSIZE


@register
class VmemBudgetRule(Rule):
    code = "PTA002"
    title = "vmem-budget"
    rationale = ("statically-priced BlockSpec windows over the VMEM "
                 "budget compile-fail only on hardware; block sizing "
                 "must route through a registered fitter (_fit_*)")
    scope = ("paddle_tpu/ops", "paddle_tpu/parallel/")

    budget = BUDGET_BYTES

    def check_module(self, module):
        index = FunctionIndex(module.tree)
        for call in module.calls:
            if call_ident(call) != "pallas_call":
                continue
            func = enclosing_function(call)
            env = ConstEnv(module.tree, func)
            fitted = _fitter_derived_names(func)
            total, unresolved, fitter_routed = self._price_site(
                call, env, fitted)
            if fitter_routed:
                continue  # the fitter owns the budget for this site
            if unresolved:
                # caller-threaded blocks: re-price per intra-module call
                # site with the caller's arguments bound to the helper's
                # parameters (the dataflow hop v1 could not make)
                if func is not None and index.get(func.name) is func:
                    yield from self._reprice_at_callers(
                        module, call, func, fitted, index)
                continue
            if total > self.budget:
                yield self.finding(
                    module, call,
                    f"pallas_call windows statically price at "
                    f"{total / 2**20:.0f} MiB (double-buffered in/out "
                    f"specs + scratch) > {self.budget / 2**20:.0f} MiB "
                    f"budget; shrink blocks or route sizing through a "
                    f"registered fitter (_fit_*)")

    def _price_site(self, call, env, fitted):
        """(total_bytes, unresolved, fitter_routed) for one pallas_call."""
        windows = []
        unresolved = False
        fitter_routed = False
        for key in ("in_specs", "out_specs"):
            kw = keyword(call, key)
            if kw is None:
                continue
            for spec in iter_calls(kw.value):
                ident = call_ident(spec)
                if ident == "BlockSpec" and spec.args and \
                        isinstance(spec.args[0], (ast.Tuple, ast.List)):
                    prod, state = self._price(spec.args[0], env, fitted)
                    if state == "fitted":
                        fitter_routed = True
                    elif state == "unknown":
                        unresolved = True
                    else:
                        windows.append(prod * DEFAULT_ITEMSIZE * 2)
        kw = keyword(call, "scratch_shapes")
        if kw is not None:
            for spec in iter_calls(kw.value):
                if call_ident(spec) not in ("VMEM", "SMEM"):
                    continue
                if not spec.args or not isinstance(
                        spec.args[0], (ast.Tuple, ast.List)):
                    continue
                prod, state = self._price(spec.args[0], env, fitted)
                if state == "fitted":
                    fitter_routed = True
                elif state == "unknown":
                    unresolved = True
                else:
                    windows.append(prod * _scratch_itemsize(spec))
        return sum(windows), unresolved, fitter_routed

    def _reprice_at_callers(self, module, pallas_call, helper, fitted,
                            index):
        """Re-price a caller-threaded pallas_call at each intra-module
        call site of its enclosing helper, with the site's constant-
        resolvable arguments bound to the helper's parameters."""
        for site in module.calls:
            resolved = resolve_local_call(site, index)
            if resolved is None or resolved[0] is not helper:
                continue
            caller_env = ConstEnv(module.tree, enclosing_function(site))
            bindings = {}
            for pname, arg in resolved[1].items():
                val = caller_env.resolve(arg)
                if isinstance(val, (int, float)):
                    bindings[pname] = ast.Constant(value=val)
            env = ConstEnv(module.tree, helper, bindings=bindings)
            total, unresolved, fitter_routed = self._price_site(
                pallas_call, env, fitted)
            if fitter_routed or unresolved:
                continue
            if total > self.budget:
                yield self.finding(
                    module, site,
                    f"call binds {helper.name}() block params so its "
                    f"pallas_call windows price at {total / 2**20:.0f} "
                    f"MiB > {self.budget / 2**20:.0f} MiB budget; shrink "
                    f"the blocks passed here or route sizing through a "
                    f"registered fitter (_fit_*)")

    @staticmethod
    def _price(shape_node, env, fitted_names):
        """(product, state) where state is 'const' | 'fitted' | 'unknown'."""
        prod = 1
        state = "const"
        for elt in shape_node.elts:
            names = _names_in(elt)
            if names & fitted_names:
                return 0, "fitted"
            val = env.resolve(elt)
            if val is None:
                state = "unknown"
            elif isinstance(val, (int, float)):
                prod *= max(int(val), 0)
        return prod, state
