"""PTA003: every ``pallas_call`` site in ops/ passes ``cost_estimate=``.

A custom call without a cost estimate is costed at ZERO by XLA's cost
model, silently deflating the StepMetrics MFU attribution for every
kernel-backed step (the PR-2 observability contract; estimates attached
in PR 4). Migrated from tests/test_pallas_cost_lint.py — that test is now
a thin shim over this rule.

The finalize() coverage floor guards the rule itself: if the AST walk
ever stops seeing the known kernel population (>= MIN_SITES sites), the
rule fails loudly instead of silently matching nothing.
"""
from __future__ import annotations

from .. import Finding, Rule, register
from .._astutil import call_ident, keyword

# flash fwd/bwd (resident, streaming, fused flat, split pair), varlen
# fwd/bwd (streaming + stacked + fused + split), decode slabs, rms_norm,
# grouped matmul x3, paged attention read + fused update + the PR-18
# speculative family (verify read fp/int8, verify commit fp/int8)
MIN_SITES = 18


@register
class CostEstimateRule(Rule):
    code = "PTA003"
    title = "cost-estimate"
    rationale = ("pallas_call without cost_estimate= is costed at zero "
                 "FLOPs, deflating StepMetrics MFU (PR-2/PR-4 "
                 "observability contract)")
    scope = ("paddle_tpu/ops/",)

    min_sites = MIN_SITES

    def __init__(self, root):
        super().__init__(root)
        self.sites_seen = 0

    def check_module(self, module):
        for call in module.calls:
            if call_ident(call) != "pallas_call":
                continue
            self.sites_seen += 1
            if keyword(call, "cost_estimate") is None:
                yield self.finding(
                    module, call,
                    "pallas_call without cost_estimate=; XLA costs the "
                    "custom call at zero FLOPs and StepMetrics MFU "
                    "under-attributes the step")

    def finalize(self):
        if self.sites_seen < self.min_sites:
            yield Finding(
                self.code, "paddle_tpu/ops/", 0, 0,
                f"coverage floor: found only {self.sites_seen} "
                f"pallas_call sites (expected >= {self.min_sites}); the "
                f"AST walk may be silently matching nothing")
