"""PTA004: every ``comm_span(...)`` call site passes ``nbytes=``.

A span with no byte count shows up as a hole in the per-hop/per-bucket
traffic accounting the benches and the multichip dryrun assert on (the
PR-3 telemetry contract). Migrated from tests/test_comm_span_lint.py —
that test is now a thin shim over this rule.
"""
from __future__ import annotations

from .. import Finding, Rule, register
from .._astutil import call_ident, keyword


@register
class CommSpanRule(Rule):
    code = "PTA004"
    title = "comm-span-nbytes"
    rationale = ("comm_span without nbytes= leaves a hole in the per-hop "
                 "traffic attribution the benches and dryrun assert on "
                 "(PR-3 telemetry contract)")
    scope = ("paddle_tpu/",)
    exclude = ("paddle_tpu/analysis/",)

    def __init__(self, root):
        super().__init__(root)
        self.sites_seen = 0

    def check_module(self, module):
        # only call sites count; the def site in observability/trace.py
        # never appears as a Call node
        for call in module.calls:
            if call_ident(call) != "comm_span":
                continue
            self.sites_seen += 1
            if keyword(call, "nbytes") is None:
                yield self.finding(
                    module, call,
                    "comm_span without nbytes=; the span's traffic volume "
                    "is unattributed in the step telemetry")

    def finalize(self):
        if self.sites_seen < 1:
            yield Finding(
                self.code, "paddle_tpu/", 0, 0,
                "coverage floor: found no comm_span call sites at all; "
                "the AST walk may be silently matching nothing")
