"""PTA004: every ``comm_span(...)`` call site passes ``nbytes=`` and a
static ``site=`` label.

A span with no byte count shows up as a hole in the per-hop/per-bucket
traffic accounting the benches and the multichip dryrun assert on (the
PR-3 telemetry contract). A span with no ``site=`` is invisible to the
FleetMonitor's cross-rank straggler attribution (PR 15), and a DYNAMIC
site label (f-string, variable) would fan one logical collective family
out into unbounded per-instance keys that never line up across ranks —
hence the label must be a string literal. Migrated from
tests/test_comm_span_lint.py — that test is now a thin shim over this
rule.
"""
from __future__ import annotations

from .. import Finding, Rule, register
from .._astutil import call_ident, keyword, str_const


@register
class CommSpanRule(Rule):
    code = "PTA004"
    title = "comm-span-nbytes"
    rationale = ("comm_span without nbytes= leaves a hole in the per-hop "
                 "traffic attribution the benches and dryrun assert on "
                 "(PR-3 telemetry contract); without a static site= label "
                 "the span is invisible to cross-rank straggler "
                 "attribution (PR 15)")
    scope = ("paddle_tpu/",)
    exclude = ("paddle_tpu/analysis/",)

    def __init__(self, root):
        super().__init__(root)
        self.sites_seen = 0

    def check_module(self, module):
        # only call sites count; the def site in observability/trace.py
        # never appears as a Call node
        for call in module.calls:
            if call_ident(call) != "comm_span":
                continue
            self.sites_seen += 1
            if keyword(call, "nbytes") is None:
                yield self.finding(
                    module, call,
                    "comm_span without nbytes=; the span's traffic volume "
                    "is unattributed in the step telemetry")
            site = keyword(call, "site")
            if site is None:
                yield self.finding(
                    module, call,
                    "comm_span without site=; the span has no stable "
                    "straggler-attribution key for cross-rank comparison")
            elif str_const(site.value) is None:
                yield self.finding(
                    module, call,
                    "comm_span site= must be a static string literal "
                    "(one shared key per collective family, identical "
                    "on every rank)")

    def finalize(self):
        if self.sites_seen < 1:
            yield Finding(
                self.code, "paddle_tpu/", 0, 0,
                "coverage floor: found no comm_span call sites at all; "
                "the AST walk may be silently matching nothing")
