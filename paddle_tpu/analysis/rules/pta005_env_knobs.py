"""PTA005: every ``PADDLE_TPU_*`` read goes through ``paddle_tpu.envs``.

PR 3 hardened env parsing per site; PR 6/PR 7 added more knobs with more
one-off parsers. ``paddle_tpu/envs.py`` is now the single registry —
(name, type, default, validator, doc) — and this rule enforces it
statically, without importing either side:

  * raw ``os.environ.get``/``os.getenv``/``os.environ[...]`` reads of a
    ``PADDLE_TPU_*`` key anywhere in the package (outside envs.py) are
    flagged — they bypass validation and the documented-knob table;
  * any exact ``PADDLE_TPU_*`` string literal naming a knob that is NOT
    registered in envs.py is flagged as undocumented (this catches both
    ``envs.get("PADDLE_TPU_TYPO")`` and a new module inventing a knob
    without registering it);
  * registered knobs whose ``doc=`` is empty are flagged at their
    registration line.

The registry is read by PARSING envs.py (the `_register(...)` calls use
literal names and docs), keeping the rule import-free.
"""
from __future__ import annotations

import ast
import os
import re

from .. import Finding, Rule, register
from .._astutil import call_ident, dotted_name, iter_calls, str_const

_KNOB_RE = re.compile(r"^PADDLE_TPU_[A-Z0-9_]*[A-Z0-9]$")


def _load_registry(root):
    """{name: (lineno, doc)} parsed statically from paddle_tpu/envs.py."""
    path = os.path.join(root, "paddle_tpu", "envs.py")
    out = {}
    if not os.path.exists(path):
        return out
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read(), filename=path)
    for call in iter_calls(tree):
        if call_ident(call) != "_register" or not call.args:
            continue
        name = str_const(call.args[0])
        if name is None:
            continue
        doc = ""
        for kw in call.keywords:
            if kw.arg == "doc":
                # literal str or implicit-concat BinOp of literals
                parts = [str_const(n) or ""
                         for n in ast.walk(kw.value)
                         if isinstance(n, ast.Constant)]
                doc = "".join(parts)
        out[name] = (call.lineno, doc.strip())
    return out


def _environ_read(call):
    """True for os.environ.get(...) / os.getenv(...) call shapes."""
    name = dotted_name(call.func) or ""
    if name in ("os.getenv", "getenv"):
        return True
    return name.endswith("environ.get") or name == "environ.get"


@register
class EnvKnobRule(Rule):
    code = "PTA005"
    title = "env-knob-registry"
    rationale = ("raw PADDLE_TPU_* environ reads bypass the envs.py "
                 "validated-getter registry (typed defaults, ValueError "
                 "naming the variable, documented knob table)")
    scope = ("paddle_tpu/",)
    exclude = ("paddle_tpu/envs.py", "paddle_tpu/analysis/")

    def __init__(self, root):
        super().__init__(root)
        self.registry = _load_registry(root)

    def check_module(self, module):
        # (a) raw environ reads of PADDLE_TPU_* keys
        for call in module.calls:
            if not _environ_read(call) or not call.args:
                continue
            key = str_const(call.args[0])
            if key is not None and key.startswith("PADDLE_TPU_"):
                yield self.finding(
                    module, call,
                    f"raw environ read of {key}; route it through "
                    f"paddle_tpu.envs.get({key!r}) (validated getter "
                    f"registry)")
        for node in module.nodes:
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load):
                target = dotted_name(node.value) or ""
                if target.endswith("environ"):
                    key = str_const(node.slice)
                    if key is not None and key.startswith("PADDLE_TPU_"):
                        yield self.finding(
                            module, node,
                            f"raw os.environ[{key!r}] read; route it "
                            f"through paddle_tpu.envs.get({key!r})")
        # (b) undocumented knobs: exact PADDLE_TPU_* literals that name a
        # knob missing from the envs.py registry
        for node in module.nodes:
            lit = str_const(node)
            if lit is None or not _KNOB_RE.match(lit):
                continue
            if lit not in self.registry:
                yield self.finding(
                    module, node,
                    f"undocumented env knob {lit}: register it in "
                    f"paddle_tpu/envs.py (name, type, default, "
                    f"validator, doc)")

    def finalize(self):
        for name, (lineno, doc) in sorted(self.registry.items()):
            if not doc:
                yield Finding(
                    self.code, "paddle_tpu/envs.py", lineno, 0,
                    f"registered knob {name} has an empty doc string; "
                    f"every knob must be documented")
