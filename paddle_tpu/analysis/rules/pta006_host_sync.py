"""PTA006: no host syncs in the hot-path modules.

PR 2's bar — zero host syncs on the train step (enforced dynamically by
the conftest transfer guard) — extends to the serving engine's decode
loop: one stray ``.item()`` / ``np.asarray`` / ``jax.device_get`` in a
per-step path serializes the device queue against Python. The dynamic
guard only sees paths a test exercises; this rule sweeps all of jit/,
parallel/, ops/ and inference/ statically.

Sinks flagged:
  * ``.item()`` / ``.tolist()`` / ``.numpy()`` method calls;
  * ``np.asarray(...)`` / ``np.array(...)`` (numpy aliases resolved from
    the module's imports) — device arrays cross to host here;
  * ``jax.device_get`` / ``block_until_ready``;
  * ``float(...)``/``int(...)`` whose argument contains a jnp/lax call
    (a traced value being pulled to a Python scalar).

Host-side planning and checkpoint I/O are legitimately host-bound: those
sites carry a reasoned ``# noqa: PTA006`` inline, or a whole-file grant
in the allowlist (the legacy numpy predictor API).
"""
from __future__ import annotations

import ast

from .. import Rule, register
from .._astutil import call_ident, call_root, dotted_name, iter_calls, \
    numpy_aliases

_SYNC_METHODS = frozenset({"item", "tolist", "numpy"})
_NP_SINKS = frozenset({"asarray", "array"})
_JAX_SINKS = frozenset({"device_get", "block_until_ready"})
_TRACED_ROOTS = frozenset({"jnp", "lax"})


def _contains_traced_call(node):
    for call in iter_calls(node):
        root = call_root(call)
        if root in _TRACED_ROOTS or call_ident(call) in _JAX_SINKS:
            return True
    return False


@register
class HostSyncRule(Rule):
    code = "PTA006"
    title = "host-sync"
    rationale = ("host syncs in per-step paths serialize the device "
                 "queue against Python (PR-2 zero-host-syncs-on-step "
                 "bar); the dynamic transfer guard only sees exercised "
                 "paths")
    scope = ("paddle_tpu/jit/", "paddle_tpu/parallel/",
             "paddle_tpu/ops/", "paddle_tpu/inference/")

    def check_module(self, module):
        np_names = numpy_aliases(module.tree) | {"np"}
        for call in module.calls:
            ident = call_ident(call)
            fn = call.func
            if isinstance(fn, ast.Attribute) and not call.args \
                    and not call.keywords and fn.attr in _SYNC_METHODS:
                yield self.finding(
                    module, call,
                    f".{fn.attr}() forces a device->host sync; keep the "
                    f"value on device or move the sync out of the hot "
                    f"path")
            elif ident in _NP_SINKS and call_root(call) in np_names:
                name = dotted_name(fn) or ident
                yield self.finding(
                    module, call,
                    f"{name}(...) pulls its operand to host (sync when "
                    f"it is a device array); use jnp on device or move "
                    f"host staging out of the step")
            elif ident in _JAX_SINKS:
                name = dotted_name(fn) or ident
                yield self.finding(
                    module, call,
                    f"{name}(...) blocks on the device queue; hot-path "
                    f"modules must stay async")
            elif isinstance(fn, ast.Name) and fn.id in ("float", "int") \
                    and len(call.args) == 1 \
                    and _contains_traced_call(call.args[0]):
                yield self.finding(
                    module, call,
                    f"{fn.id}() of a traced jnp/lax expression pulls it "
                    f"to a Python scalar (host sync)")
