"""PTA007: process-global state mutated without a restoring scope.

The bug class: the package carries real process-global knobs —
``ops._common._FORCE_INTERPRET`` (via ``set_interpret``), ``os.environ``
(the PADDLE_TPU_* / XLA overlap knobs), ``jax.config``, and the
collective-matmul plan cache. A test or dryrun that mutates one and does
not restore it poisons every later test in the same pytest process: the
PR-10 ``_serve_dryrun`` leak (``finally: set_interpret(False)`` —
restoring a hard-coded value instead of the saved previous one) broke
~20 order-dependent tier-1 tests before it was found by hand.

The rule flags every mutator call that is not *protected*:

  * inside a ``try`` whose ``finally`` restores the same state domain
    (same env key / jax.config name; any ``set_interpret`` for the
    interpret override; a paired ``clear_plan_cache`` for the plan
    cache);
  * inside a ``@contextlib.contextmanager`` or generator
    ``@pytest.fixture`` whose post-``yield`` teardown restores it;
  * itself in teardown position (a ``finally`` body or after the
    fixture's ``yield``) — it IS the restore.

Teardown restores of the interpret override must restore a SAVED value:
``set_interpret(False)`` / ``set_interpret(True)`` with a literal in
teardown position is flagged as the exact PR-10 shape (it clobbers any
outer override). Module-scope mutations are flagged under ``tests/``
only — a module-level mutation in a test file leaks across the whole
session — while entry scripts set process-lifetime config by design.

Fix with the ``ops/_common.interpret_mode(value)`` contextmanager (saves
and restores the previous override), or save/restore explicitly in a
``finally``.
"""
from __future__ import annotations

import ast
from typing import Optional, Tuple

from .. import Rule, register
from .._astutil import (ConstEnv, call_ident, decorator_names, dotted_name,
                        enclosing_function, parent, _contains)

# jax.config.update call paths (conftest uses `jax.config.update`,
# package code may alias `from jax import config`)
_CONFIG_ROOTS = ("jax.config.update", "config.update")


def _is_environ(node: ast.AST) -> bool:
    name = dotted_name(node)
    return name is not None and (name == "environ"
                                 or name.endswith(".environ"))


def _key_sym(node: ast.AST, env: Optional[ConstEnv]) -> str:
    """Canonical symbol for an env key / config name: its resolved string
    value when statically known, else the ast.dump of the expression (so
    ``os.environ[var] = x`` ... ``del os.environ[var]`` still pair up)."""
    if env is not None:
        s = env.resolve_str(node)
        if s is not None:
            return "str:" + s
    else:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return "str:" + node.value
    return "dump:" + ast.dump(node)


def _mutation_of(node: ast.AST,
                 env: Optional[ConstEnv]) -> Optional[Tuple[str, str, str]]:
    """(domain, key, description) when ``node`` mutates process-global
    state; None otherwise. Domains: interpret | env | jaxconfig |
    plan_cache."""
    if isinstance(node, ast.Call):
        ident = call_ident(node)
        if ident == "set_interpret":
            return "interpret", "", "set_interpret(...)"
        if ident == "clear_plan_cache":
            return "plan_cache", "", "clear_plan_cache()"
        if ident in ("pop", "setdefault") and isinstance(
                node.func, ast.Attribute) and _is_environ(node.func.value) \
                and node.args:
            key = _key_sym(node.args[0], env)
            return "env", key, f"os.environ.{ident}(...)"
        name = dotted_name(node.func)
        if name in _CONFIG_ROOTS and node.args:
            key = _key_sym(node.args[0], env)
            return "jaxconfig", key, "jax.config.update(...)"
        return None
    if isinstance(node, ast.Assign):
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript) and _is_environ(tgt.value):
                return ("env", _key_sym(tgt.slice, env),
                        "os.environ[...] write")
        return None
    if isinstance(node, ast.Delete):
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript) and _is_environ(tgt.value):
                return ("env", _key_sym(tgt.slice, env),
                        "del os.environ[...]")
    return None


def _restores(stmt: ast.stmt, domain: str, key: str,
              env: Optional[ConstEnv]) -> bool:
    """Does this (teardown-position) statement restore the domain/key?
    Any same-domain mutation counts as the restore — teardown writes are
    by construction putting the state back."""
    for node in ast.walk(stmt):
        m = _mutation_of(node, env)
        if m is not None and m[0] == domain and (
                domain not in ("env", "jaxconfig") or m[1] == key):
            return True
    return False


def _first_yield_line(func) -> Optional[int]:
    yields = [n for n in ast.walk(func)
              if isinstance(n, (ast.Yield, ast.YieldFrom))]
    if not yields:
        return None
    return min(n.lineno for n in yields)


def _teardown_statements(func):
    """Post-yield statements of a generator contextmanager/fixture."""
    first = _first_yield_line(func)
    if first is None:
        return []
    return [n for n in ast.walk(func)
            if isinstance(n, ast.stmt) and n.lineno > first]


def _following_try_restores(node, domain, key, env):
    """The canonical idiom puts the mutation IMMEDIATELY BEFORE the try::

        os.environ[k] = v        # possibly under an `if`
        try:
            ...
        finally:
            del os.environ[k]

    Accept it: walking out from the mutation, a later sibling Try at ANY
    statement level (up to the enclosing function) whose finalbody
    restores the domain/key protects the mutation."""
    cur = node
    while cur is not None:
        p = parent(cur)
        if p is None or isinstance(cur, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
            return False
        if isinstance(cur, ast.stmt):
            for field in ("body", "orelse", "finalbody"):
                body = getattr(p, field, None)
                if not body or cur not in body:
                    continue
                for later in body[body.index(cur) + 1:]:
                    if isinstance(later, ast.Try) and any(
                            _restores(s, domain, key, env)
                            for s in later.finalbody):
                        return True
        cur = p
    return False


def _enclosing_tries_with_region(node):
    """[(Try, in_finalbody)] innermost-first for every Try on the parent
    chain, recording whether ``node`` sits in its protected region
    (body/orelse) or its finalbody."""
    out = []
    cur, prev = parent(node), node
    while cur is not None:
        if isinstance(cur, ast.Try):
            in_final = any(s is prev or _contains(s, prev)
                           for s in cur.finalbody)
            in_region = any(s is prev or _contains(s, prev)
                            for s in list(cur.body) + list(cur.orelse))
            if in_final or in_region:
                out.append((cur, in_final))
        prev, cur = cur, parent(cur)
    return out


_SCOPED_DECORATORS = ("contextmanager", "asynccontextmanager", "fixture")


@register
class GlobalStateLeakRule(Rule):
    code = "PTA007"
    title = "global-state-leak"
    rationale = ("process-global mutations (set_interpret, os.environ, "
                 "jax.config, plan cache) without a restoring try/finally "
                 "or contextmanager poison later tests in the same "
                 "process (the PR-10 _serve_dryrun leak class)")
    scope = ("paddle_tpu/", "tests/", "examples/", "benchmarks/",
             "bench.py", "__graft_entry__.py")
    exclude = ("tests/analysis_fixtures/", "paddle_tpu/ops/_common.py",
               "paddle_tpu/analysis/")

    def check_module(self, module):
        envs = {}  # per-function ConstEnv cache
        for node in module.nodes:
            if not isinstance(node, (ast.Call, ast.Assign, ast.Delete)):
                continue
            if _mutation_of(node, None) is None:
                continue  # env only refines the key, never mutator-ness
            func = enclosing_function(node)
            env = envs.get(id(func))
            if env is None:
                env = envs[id(func)] = ConstEnv(module.tree, func)
            m = _mutation_of(node, env)
            if m is None:
                continue
            yield from self._check_mutation(module, node, func, env, m)

    def _check_mutation(self, module, node, func, env, m):
        domain, key, desc = m

        if func is None:
            # module scope: only test modules leak across the session
            if module.rel.startswith("tests/"):
                yield self.finding(
                    module, node,
                    f"module-scope {desc} in a test module mutates "
                    f"process-global state for every later test; move it "
                    f"into a fixture that restores it")
            return

        tries = _enclosing_tries_with_region(node)
        decs = decorator_names(func) & set(_SCOPED_DECORATORS)
        first_yield = _first_yield_line(func) if decs else None
        in_teardown = any(in_final for _, in_final in tries) or (
            first_yield is not None and node.lineno > first_yield)

        if in_teardown:
            # the PR-10 shape: teardown restoring a hard-coded override
            if domain == "interpret" and isinstance(node, ast.Call) and \
                    node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, bool):
                yield self.finding(
                    module, node,
                    f"teardown hard-codes set_interpret("
                    f"{node.args[0].value}) — restoring a literal instead "
                    f"of the saved previous value clobbers any outer "
                    f"override (the PR-10 _serve_dryrun leak); use "
                    f"`with _common.interpret_mode(...)` or restore the "
                    f"saved value")
            return  # otherwise: it IS the restore

        for t, in_final in tries:
            if in_final:
                continue
            if any(_restores(s, domain, key, env) for s in t.finalbody):
                return  # protected by this try/finally
        if _following_try_restores(node, domain, key, env):
            return  # set-then-try/finally-restore idiom

        if first_yield is not None and node.lineno <= first_yield:
            if any(_restores(s, domain, key, env)
                   for s in _teardown_statements(func)):
                return  # contextmanager/fixture with post-yield restore

        yield self.finding(
            module, node,
            f"{desc} mutates process-global state with no restoring "
            f"try/finally or contextmanager in sight; wrap it (e.g. "
            f"`with _common.interpret_mode(...)`) or restore the saved "
            f"previous value in a finally")
