"""PTA008: SPMD collective / mesh consistency inside shard_map islands.

The bug class: the hand-written shard_map islands (collective_matmul
rings, moe a2a dispatch, the ragged ep ring, ulysses, ring attention,
pipeline) thread axis names and ring permutations as plain Python values.
A wrong axis name, a permutation that is not injective on the axis, or
``axis_index`` arithmetic modded by a *different* axis's size all trace
fine on one host and only explode (or silently mis-route) in the
multichip dryrun.

Three checks, all AST-only over the dataflow layer:

  * **axis membership** — at a ``shard_map(...)`` site whose mesh axis
    names are statically resolvable, every ``psum``/``ppermute``/
    ``all_to_all``/``axis_index``/... axis name used by the (resolved)
    body — one helper level deep, through ``functools.partial`` — must
    be one of the mesh axes;
  * **permutation audit** — a statically-known ``ppermute`` perm must be
    injective and in-range: literal pair lists need distinct sources and
    distinct destinations; comprehension perms
    ``[(i, f(i)) for i in range(B)]`` are checked symbolically —
    ``(i + h) % m`` must mod by the same symbol as the range bound
    (``m == B``), and un-modded ``i + d`` needs ``B <= axis - d`` (the
    pipeline's partial shift ``range(S - 1)`` with ``i + 1`` is valid;
    ``range(S)`` with ``i + 1`` overflows the last source);
  * **axis arithmetic** — ``(... axis_index(a) ...) % axis_size(b)``
    with ``a != b`` mixes two axes' coordinate systems.

Sites whose mesh/specs/perms are not statically resolvable are skipped,
never guessed. ``finalize`` enforces a coverage floor: each of the six
island families (collective_matmul, moe, ragged, ulysses, ring,
pipeline) must contribute at least one audited collective site, so the
rule cannot silently rot as modules move.
"""
from __future__ import annotations

import ast
from typing import Dict, Optional, Set

from .. import Rule, register
from .._astutil import (ConstEnv, FunctionIndex, affine_of, call_ident,
                        enclosing_function, iter_calls, keyword,
                        resolve_callable, resolve_local_call)

# collectives taking an axis name, with the positional index it rides at
_AXIS_ARG_POS = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "psum_scatter": 1,
    "ppermute": 1, "all_to_all": 1, "all_gather": 1,
    "axis_index": 0, "axis_size": 0, "_axis_size": 0,
}

# the six island families the coverage floor requires (substring of rel)
_FAMILIES = ("collective_matmul", "moe", "ragged", "ulysses", "ring",
             "pipeline")


def _axis_arg(call: ast.Call) -> Optional[ast.AST]:
    ident = call_ident(call)
    pos = _AXIS_ARG_POS.get(ident)
    if pos is None:
        return None
    kw = keyword(call, "axis_name")
    if kw is not None:
        return kw.value
    if len(call.args) > pos:
        return call.args[pos]
    return None


def _axis_sym(node: ast.AST, env: ConstEnv) -> str:
    s = env.resolve_str(node)
    if s is not None:
        return "str:" + s
    return "dump:" + ast.dump(env.resolve_node(node))


def _perm_arg(call: ast.Call) -> Optional[ast.AST]:
    kw = keyword(call, "perm")
    if kw is not None:
        return kw.value
    if len(call.args) > 2:
        return call.args[2]
    return None


def _mesh_axes(mesh_node: ast.AST, env: ConstEnv) -> Optional[Set[str]]:
    """Statically-known axis-name set of a Mesh(...) expression, chased
    through straight-line assignments; None when unresolvable."""
    mesh = env.resolve_node(mesh_node)
    if not (isinstance(mesh, ast.Call) and call_ident(mesh) == "Mesh"):
        return None
    names = keyword(mesh, "axis_names")
    ax = names.value if names is not None else (
        mesh.args[1] if len(mesh.args) > 1 else None)
    if ax is None:
        return None
    ax = env.resolve_node(ax)
    if not isinstance(ax, (ast.Tuple, ast.List)):
        return None
    out = set()
    for elt in ax.elts:
        s = env.resolve_str(elt)
        if s is None:
            return None
        out.add(s)
    return out


@register
class CollectiveMeshRule(Rule):
    code = "PTA008"
    title = "collective-mesh"
    rationale = ("wrong axis names, non-injective ppermute perms and "
                 "axis_index arithmetic modded by the wrong axis trace "
                 "fine single-host and only explode in the multichip "
                 "dryrun")
    scope = ("paddle_tpu/parallel/", "paddle_tpu/distributed/",
             "paddle_tpu/models/")

    def __init__(self, root):
        super().__init__(root)
        self._audited_rels: Set[str] = set()

    def check_module(self, module):
        index = FunctionIndex(module.tree)
        audited = False
        for call in module.calls:
            ident = call_ident(call)
            if ident == "shard_map":
                yield from self._check_island(module, call, index)
                audited = True
            elif ident == "ppermute":
                func = enclosing_function(call)
                env = ConstEnv(module.tree, func)
                yield from self._check_perm(module, call, env)
                audited = True
            elif ident in _AXIS_ARG_POS:
                audited = True
        yield from self._check_axis_arithmetic(module)
        if audited:
            self._audited_rels.add(module.rel)

    # --- axis membership at shard_map sites --------------------------------

    def _check_island(self, module, call, index):
        func = enclosing_function(call)
        env = ConstEnv(module.tree, func)
        mesh_kw = keyword(call, "mesh")
        mesh_node = mesh_kw.value if mesh_kw is not None else (
            call.args[1] if len(call.args) > 1 else None)
        if mesh_node is None:
            return
        axes = _mesh_axes(mesh_node, env)
        if axes is None:
            return  # mesh threaded from a caller: cannot audit statically
        if not call.args:
            return
        resolved = resolve_callable(call.args[0], index, env)
        if resolved is None:
            return
        body, binding = resolved
        yield from self._check_body_axes(module, body, binding, axes,
                                         index, depth=2)

    def _check_body_axes(self, module, body, binding, axes, index, depth):
        env = ConstEnv(module.tree, body if not isinstance(
            body, ast.Lambda) else None, bindings=binding)
        for call in iter_calls(body):
            ident = call_ident(call)
            if ident in _AXIS_ARG_POS:
                arg = _axis_arg(call)
                if arg is None:
                    continue
                name = env.resolve_str(arg)
                if name is not None and name not in axes:
                    yield self.finding(
                        module, call,
                        f"{ident}() over axis {name!r} inside a shard_map "
                        f"island whose mesh axes are "
                        f"{sorted(axes)}; the collective would fail (or "
                        f"bind an outer mesh) at run time")
            elif depth > 1:
                resolved = resolve_local_call(call, index, env)
                if resolved is not None:
                    helper, hbinding = resolved
                    yield from self._check_body_axes(
                        module, helper, hbinding, axes, index, depth - 1)

    # --- ppermute permutation audit -----------------------------------------

    def _check_perm(self, module, call, env):
        perm = _perm_arg(call)
        if perm is None:
            return
        perm = env.resolve_node(perm)
        if isinstance(perm, (ast.List, ast.Tuple)):
            yield from self._check_literal_perm(module, call, perm, env)
        elif isinstance(perm, ast.ListComp):
            yield from self._check_comp_perm(module, call, perm, env)
        # anything else (caller-threaded perm): skip, never guess

    def _check_literal_perm(self, module, call, perm, env):
        srcs, dsts = [], []
        for elt in perm.elts:
            if not (isinstance(elt, (ast.Tuple, ast.List))
                    and len(elt.elts) == 2):
                return
            s = env.resolve(elt.elts[0])
            d = env.resolve(elt.elts[1])
            if s is None or d is None:
                return
            srcs.append(s)
            dsts.append(d)
        if len(set(srcs)) != len(srcs):
            yield self.finding(
                module, call,
                f"ppermute perm has duplicate sources {sorted(srcs)}: a "
                f"device cannot send twice in one permute")
        if len(set(dsts)) != len(dsts):
            yield self.finding(
                module, call,
                f"ppermute perm has duplicate destinations {sorted(dsts)}: "
                f"two devices write the same receive buffer")
        bad = [v for v in srcs + dsts if v < 0]
        if bad:
            yield self.finding(
                module, call,
                f"ppermute perm contains negative device ids {bad}")

    def _check_comp_perm(self, module, call, perm, env):
        if len(perm.generators) != 1:
            return
        gen = perm.generators[0]
        if not isinstance(gen.target, ast.Name) or gen.ifs:
            return
        var = gen.target.id
        rng = env.resolve_node(gen.iter)
        if not (isinstance(rng, ast.Call) and call_ident(rng) == "range"
                and len(rng.args) == 1):
            return
        bound = affine_of(rng.args[0], env)
        elt = perm.elt
        if not (isinstance(elt, (ast.Tuple, ast.List))
                and len(elt.elts) == 2):
            return
        src, dst = elt.elts
        if not (isinstance(src, ast.Name) and src.id == var):
            return  # only the (i, f(i)) shape is audited
        dst = env.resolve_node(dst)
        if isinstance(dst, ast.BinOp) and isinstance(dst.op, ast.Mod):
            mod = affine_of(dst.right, env)
            if bound is not None and mod is not None and bound != mod:
                yield self.finding(
                    module, call,
                    f"ppermute perm ranges over "
                    f"{ast.unparse(rng.args[0])} but mods destinations by "
                    f"{ast.unparse(dst.right)} — a different axis size "
                    f"makes the perm non-injective (or wraps onto the "
                    f"wrong ring)")
            return
        shift = affine_of(dst, env)
        if shift is None or bound is None:
            return
        sym, d = shift
        # dst must still be an affine function of the loop var
        if sym is None or var not in {n.id for n in ast.walk(dst)
                                      if isinstance(n, ast.Name)}:
            return
        b_sym, b_off = bound
        if b_sym is None:
            return  # constant bound: literal-perm territory
        # i in [0, B-1], dst = i + d un-modded: max dst = B - 1 + d must
        # stay below the axis size; with B = sym + b_off that needs
        # b_off <= -d (range(n - d) with shift d), else the top sources
        # send out of range.
        if d > 0 and b_off > -d:
            yield self.finding(
                module, call,
                f"un-modded ppermute shift (i + {d}) over "
                f"range({ast.unparse(rng.args[0])}): the last "
                f"{d + b_off} source(s) send past the end of the axis; "
                f"mod by the axis size or shorten the range to "
                f"range(<axis> - {d})")
        if d < 0 and b_off >= 0:
            yield self.finding(
                module, call,
                f"un-modded negative ppermute shift (i - {-d}): source 0 "
                f"sends to a negative device id; mod by the axis size")

    # --- axis_index arithmetic mod the wrong axis ---------------------------

    def _check_axis_arithmetic(self, module):
        envs: Dict[int, ConstEnv] = {}
        for node in module.nodes:
            if not (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Mod)):
                continue
            func = enclosing_function(node)
            env = envs.get(id(func))
            if env is None:
                env = envs[id(func)] = ConstEnv(module.tree, func)
            idx_axis = self._axis_of_call(node.left, env, "axis_index")
            if idx_axis is None:
                continue
            size_axis = self._axis_of_call(node.right, env,
                                           "axis_size", "_axis_size")
            if size_axis is None:
                continue
            if idx_axis != size_axis:
                yield Rule.finding(
                    self, module, node,
                    f"axis_index over one axis is modded by the size of "
                    f"a DIFFERENT axis ({idx_axis.split(':', 1)[1]!r} vs "
                    f"{size_axis.split(':', 1)[1]!r}); the coordinate "
                    f"wraps onto the wrong ring")

    @staticmethod
    def _axis_of_call(node, env, *idents):
        """Axis symbol of the single axis_index/axis_size call reachable
        in ``node`` (directly or through one straight-line assignment);
        None when absent or ambiguous."""
        node = env.resolve_node(node)
        hits = []
        for call in iter_calls(node):
            if call_ident(call) in idents:
                arg = _axis_arg(call)
                if arg is not None:
                    hits.append(_axis_sym(arg, env))
        for n in ast.walk(node):
            if isinstance(n, ast.Name):
                r = env.resolve_node(n)
                if r is not n:
                    for call in iter_calls(r):
                        if call_ident(call) in idents:
                            arg = _axis_arg(call)
                            if arg is not None:
                                hits.append(_axis_sym(arg, env))
        hits = sorted(set(hits))
        return hits[0] if len(hits) == 1 else None

    # --- coverage floor -----------------------------------------------------

    def finalize(self):
        from .. import Finding
        for fam in sorted(_FAMILIES):
            if not any(fam in rel for rel in self._audited_rels):
                yield Finding(
                    self.code, "paddle_tpu/parallel/", 0, 0,
                    f"coverage floor: no audited collective site found "
                    f"for the {fam!r} island family — did the module "
                    f"move?")
