"""PTA009: Pallas grid / BlockSpec / scratch audit.

The bug class: a ``pallas_call`` whose ``index_map`` arity disagrees
with the grid rank, whose block shape does not divide the (unguarded)
operand shape, or whose accumulation scratch is bf16 traces fine in
interpret mode and only fails — or silently loses precision — when
Mosaic lowers it on hardware. With ~20 ``pallas_call`` sites across six
kernel files, eyeballing each edit stopped scaling around PR 7.

Checks per site (constant-folded through the dataflow layer; anything
unresolvable is skipped, never guessed):

  * **index_map arity** — every ``BlockSpec`` index_map must take
    ``grid_rank`` arguments, plus ``num_scalar_prefetch`` when the site
    rides a ``PrefetchScalarGridSpec`` (the scalar refs are appended to
    the index_map signature);
  * **divisibility** — when both an ``out_shape`` dim and the matching
    ``out_specs`` block dim are statically known, the block must divide
    the dim (a non-dividing tail needs an explicit guard/fitter, not
    silence);
  * **scratch dtype** — ``pltpu.VMEM`` scratch declared bf16/f16 is
    flagged: accumulators must be f32 (the kernels here all accumulate
    in f32 and cast on the way out; a half-precision accumulator loses
    the summation tail exactly when S gets long).

``finalize`` enforces a coverage floor: at least ``MIN_SITES`` audited
``pallas_call`` sites across ops/ — if kernels move out from under the
rule's scope, the floor trips instead of the audit silently shrinking.
"""
from __future__ import annotations

import ast
from typing import Optional

from .. import Rule, register
from .._astutil import (ConstEnv, FunctionIndex, call_ident,
                        enclosing_function, iter_calls, keyword,
                        resolve_callable, resolve_dtype_name)

# every ops/ kernel file carries multiple sites; the floor trips when the
# audit sees meaningfully fewer than the ~24 sites in tree today (the
# PR-18 speculative verify/commit family added four)
MIN_SITES = 24

_HALF_DTYPES = ("bfloat16", "float16")


def _grid_parts(call: ast.Call, env: ConstEnv):
    """(grid_node, n_prefetch, spec_containers) for a pallas_call: the
    grid expression, the scalar-prefetch count, and the calls whose
    in_specs/out_specs hold this site's BlockSpecs (the pallas_call
    itself and/or its grid_spec)."""
    containers = [call]
    grid_node = None
    n_prefetch = 0
    kw = keyword(call, "grid")
    if kw is not None:
        grid_node = kw.value
    gs = keyword(call, "grid_spec")
    if gs is not None and isinstance(gs.value, ast.Call):
        containers.append(gs.value)
        gkw = keyword(gs.value, "grid")
        if gkw is not None:
            grid_node = gkw.value
        pkw = keyword(gs.value, "num_scalar_prefetch")
        if pkw is not None:
            n_prefetch = env.resolve(pkw.value) or 0
    return grid_node, n_prefetch, containers


def _grid_rank(grid_node: Optional[ast.AST],
               env: ConstEnv) -> Optional[int]:
    if grid_node is None:
        return None
    node = env.resolve_node(grid_node)
    if isinstance(node, (ast.Tuple, ast.List)):
        return len(node.elts)
    if env.resolve(node) is not None:
        return 1  # scalar grid
    return None


def _index_map_arity(spec: ast.Call, index: FunctionIndex,
                     env: ConstEnv) -> Optional[int]:
    node = keyword(spec, "index_map")
    node = node.value if node is not None else (
        spec.args[1] if len(spec.args) > 1 else None)
    if node is None:
        return None
    resolved = resolve_callable(node, index, env)
    if resolved is None:
        return None
    fn, _ = resolved
    args = fn.args
    return len(args.args)


@register
class PallasGridRule(Rule):
    code = "PTA009"
    title = "pallas-grid"
    rationale = ("index_map arity / block divisibility / scratch dtype "
                 "mistakes trace fine in interpret mode and only fail "
                 "(or lose precision) when Mosaic lowers on hardware")
    scope = ("paddle_tpu/ops/", "paddle_tpu/parallel/")

    def __init__(self, root):
        super().__init__(root)
        self._sites = 0

    def check_module(self, module):
        index = FunctionIndex(module.tree)
        for call in module.calls:
            if call_ident(call) != "pallas_call":
                continue
            self._sites += 1
            func = enclosing_function(call)
            env = ConstEnv(module.tree, func)
            grid_node, n_prefetch, containers = _grid_parts(call, env)
            rank = _grid_rank(grid_node, env)

            for container in containers:
                for key in ("in_specs", "out_specs"):
                    kw = keyword(container, key)
                    if kw is None:
                        continue
                    for spec in iter_calls(kw.value):
                        if call_ident(spec) != "BlockSpec":
                            continue
                        yield from self._check_spec(
                            module, spec, rank, n_prefetch, index, env)
            yield from self._check_divisibility(module, call, containers,
                                                env)
            yield from self._check_scratch(module, call, env)

    def _check_spec(self, module, spec, rank, n_prefetch, index, env):
        if rank is None:
            return
        arity = _index_map_arity(spec, index, env)
        if arity is None:
            return
        want = rank + n_prefetch
        if arity != want:
            yield self.finding(
                module, spec,
                f"BlockSpec index_map takes {arity} argument(s) but the "
                f"grid has rank {rank}"
                + (f" plus {n_prefetch} scalar-prefetch ref(s)"
                   if n_prefetch else "")
                + f" — expected {want}; Mosaic rejects (or worse, "
                  f"misindexes) the mismatch on hardware")

    def _check_divisibility(self, module, call, containers, env):
        """Block dims must divide the out_shape dims when both resolve."""
        shape_kw = keyword(call, "out_shape")
        if shape_kw is None:
            return
        shapes = [c for c in iter_calls(shape_kw.value)
                  if call_ident(c) == "ShapeDtypeStruct"]
        if len(shapes) != 1 or not shapes[0].args:
            return  # multi-output or non-literal: skip
        dims_node = env.resolve_node(shapes[0].args[0])
        if not isinstance(dims_node, (ast.Tuple, ast.List)):
            return
        dims = [env.resolve(e) for e in dims_node.elts]
        for container in containers:
            kw = keyword(container, "out_specs")
            if kw is None:
                continue
            specs = [c for c in iter_calls(kw.value)
                     if call_ident(c) == "BlockSpec"]
            if len(specs) != 1 or not specs[0].args:
                continue
            block_node = env.resolve_node(specs[0].args[0])
            if not isinstance(block_node, (ast.Tuple, ast.List)):
                continue
            blocks = [env.resolve(e) for e in block_node.elts]
            if len(blocks) != len(dims):
                continue  # rank change via index_map: out of audit reach
            for axis, (dim, blk) in enumerate(zip(dims, blocks)):
                if dim is None or blk is None or not blk:
                    continue
                if int(dim) % int(blk):
                    yield self.finding(
                        module, specs[0],
                        f"out block dim {int(blk)} does not divide "
                        f"out_shape dim {int(dim)} (axis {axis}); the "
                        f"tail tile reads/writes out of bounds unless "
                        f"explicitly guarded — pad the shape or route "
                        f"sizing through a fitter")

    def _check_scratch(self, module, call, env):
        kw = keyword(call, "scratch_shapes")
        if kw is None:
            gs = keyword(call, "grid_spec")
            if gs is not None and isinstance(gs.value, ast.Call):
                kw = keyword(gs.value, "scratch_shapes")
        if kw is None:
            return
        for spec in iter_calls(kw.value):
            if call_ident(spec) != "VMEM" or len(spec.args) < 2:
                continue
            dtype = resolve_dtype_name(spec.args[1], env)
            if dtype in _HALF_DTYPES:
                yield self.finding(
                    module, spec,
                    f"VMEM scratch declared {dtype}: accumulation "
                    f"scratch must be f32 (accumulate in f32, cast on "
                    f"the way out) — a half-precision accumulator drops "
                    f"the summation tail at long S")

    def finalize(self):
        from .. import Finding
        if self._sites < MIN_SITES:
            yield Finding(
                self.code, "paddle_tpu/ops/", 0, 0,
                f"coverage floor: only {self._sites} pallas_call site(s) "
                f"audited (< {MIN_SITES}) — did kernels move out of the "
                f"rule's scope?")
