"""paddle.audio parity: spectral feature layers + window/mel functional
(ref: python/paddle/audio/). Built on paddle_tpu.signal's XLA-native STFT."""
from . import features
from . import functional
from .features import LogMelSpectrogram, MelSpectrogram, MFCC, Spectrogram

__all__ = ["features", "functional", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC"]
