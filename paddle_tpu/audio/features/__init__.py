from .layers import LogMelSpectrogram, MelSpectrogram, MFCC, Spectrogram

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]
