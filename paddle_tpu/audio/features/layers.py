"""Audio feature layers (ref: python/paddle/audio/features/layers.py).

All lower to the XLA-native STFT in paddle_tpu.signal (batched matmul against
the DFT basis -> MXU work), so feature extraction runs on-device.
"""
from __future__ import annotations

from ...nn.layer.layers import Layer
from ...signal import stft
from ...tensor import matmul
from ...tensor.tensor import Tensor, _run_op
from ..functional import (compute_fbank_matrix, create_dct, get_window,
                          power_to_db)


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.fft_window = get_window(window, self.win_length, dtype=dtype)

    def forward(self, x):
        spec = stft(x, self.n_fft, hop_length=self.hop_length,
                    win_length=self.win_length, window=self.fft_window,
                    center=self.center, pad_mode=self.pad_mode)
        import jax.numpy as jnp
        p = self.power
        return _run_op("spec_power",
                       lambda s: jnp.abs(s) ** p, (spec,), {})


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                        power, center, pad_mode, dtype)
        self.fbank_matrix = compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm, dtype=dtype)

    def forward(self, x):
        spec = self._spectrogram(x)  # (..., n_freq, n_frames)
        return matmul(self.fbank_matrix, spec)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return power_to_db(self._melspectrogram(x), ref_value=self.ref_value,
                           amin=self.amin, top_db=self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.dct_matrix = create_dct(n_mfcc, n_mels, dtype=dtype)

    def forward(self, x):
        mel = self._log_melspectrogram(x)  # (..., n_mels, n_frames)
        from ...tensor.manipulation import swapaxes
        return swapaxes(matmul(swapaxes(mel, -1, -2), self.dct_matrix),
                        -1, -2)
