from .functional import (compute_fbank_matrix, create_dct, fft_frequencies,
                         hz_to_mel, mel_frequencies, mel_to_hz, power_to_db)
from .window import get_window

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct", "get_window"]
