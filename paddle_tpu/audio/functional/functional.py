"""Mel/dB/DCT helpers (ref: python/paddle/audio/functional/functional.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ...tensor.tensor import Tensor


def hz_to_mel(freq, htk=False):
    scalar = not isinstance(freq, (Tensor, np.ndarray, list, tuple))
    f = np.asarray(freq.numpy() if isinstance(freq, Tensor) else freq,
                   dtype=np.float64)
    if htk:
        mel = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = np.log(6.4) / 27.0
        mel = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz)
                       / logstep, mel)
    return float(mel) if scalar else Tensor(jnp.asarray(mel, jnp.float32))


def mel_to_hz(mel, htk=False):
    scalar = not isinstance(mel, (Tensor, np.ndarray, list, tuple))
    m = np.asarray(mel.numpy() if isinstance(mel, Tensor) else mel,
                   dtype=np.float64)
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        hz = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = np.log(6.4) / 27.0
        hz = np.where(m >= min_log_mel,
                      min_log_hz * np.exp(logstep * (m - min_log_mel)), hz)
    return float(hz) if scalar else Tensor(jnp.asarray(hz, jnp.float32))


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    low = hz_to_mel(f_min, htk)
    high = hz_to_mel(f_max, htk)
    mels = np.linspace(low, high, n_mels)
    return Tensor(jnp.asarray(
        np.asarray(mel_to_hz(list(mels), htk).numpy()), dtype))


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor(jnp.asarray(np.linspace(0, sr / 2, 1 + n_fft // 2), dtype))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filterbank, (n_mels, 1 + n_fft//2)."""
    f_max = f_max if f_max is not None else sr / 2.0
    fftfreqs = np.asarray(fft_frequencies(sr, n_fft).numpy(), np.float64)
    melpts = np.asarray(
        mel_frequencies(n_mels + 2, f_min, f_max, htk).numpy(), np.float64)
    fdiff = np.diff(melpts)
    ramps = melpts[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / np.maximum(fdiff[:-1, None], 1e-10)
    upper = ramps[2:] / np.maximum(fdiff[1:, None], 1e-10)
    weights = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melpts[2:n_mels + 2] - melpts[:n_mels])
        weights *= enorm[:, None]
    return Tensor(jnp.asarray(weights, dtype))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    from ...tensor.tensor import _run_op
    def f(s):
        log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
        log_spec = log_spec - 10.0 * jnp.log10(max(amin, ref_value))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
        return log_spec
    return _run_op("power_to_db", f, (spect,), {})


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """(n_mels, n_mfcc) DCT-II matrix (ref: functional.create_dct)."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)[None, :]
    dct = np.cos(np.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct[:, 0] *= np.sqrt(1.0 / n_mels)
        dct[:, 1:] *= np.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct, dtype))
