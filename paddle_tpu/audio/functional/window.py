"""Window functions (ref: python/paddle/audio/functional/window.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import scipy.signal.windows as sw

from ...tensor.tensor import Tensor

_WINDOWS = {
    "hamming": sw.hamming, "hann": sw.hann, "blackman": sw.blackman,
    "bartlett": sw.bartlett, "bohman": sw.bohman, "nuttall": sw.nuttall,
    "cosine": sw.cosine, "triang": sw.triang,
}


def get_window(window, win_length, fftbins=True, dtype="float32"):
    if isinstance(window, tuple):
        name, *args = window
        if name in ("gaussian",):
            data = sw.gaussian(win_length, *args, sym=not fftbins)
        elif name in ("kaiser",):
            data = sw.kaiser(win_length, *args, sym=not fftbins)
        elif name in ("taylor",):
            data = sw.taylor(win_length, *args, sym=not fftbins)
        elif name in ("general_gaussian",):
            data = sw.general_gaussian(win_length, *args, sym=not fftbins)
        elif name in ("exponential",):
            data = sw.exponential(win_length, *args, sym=not fftbins)
        elif name in ("tukey",):
            data = sw.tukey(win_length, *args, sym=not fftbins)
        else:
            raise ValueError(f"unknown window {name}")
    else:
        fn = _WINDOWS.get(window)
        if fn is None:
            raise ValueError(f"unknown window {window}")
        data = fn(win_length, sym=not fftbins)
    return Tensor(jnp.asarray(np.asarray(data), dtype))
