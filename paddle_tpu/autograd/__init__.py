"""Autograd public API (ref: python/paddle/autograd/)."""
from .engine import (backward, grad, no_grad, enable_grad, is_grad_enabled,
                     set_grad_enabled, GradNode)
from .py_layer import PyLayer, PyLayerContext
from .functional import jacobian, hessian, vjp, jvp  # noqa: F401

__all__ = ["jacobian", "hessian", "vjp", "jvp", "backward", "grad", "no_grad", "enable_grad", "is_grad_enabled",
           "set_grad_enabled", "PyLayer", "PyLayerContext"]
