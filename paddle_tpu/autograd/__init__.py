"""Autograd public API (ref: python/paddle/autograd/)."""
from .engine import (backward, grad, no_grad, enable_grad, is_grad_enabled,
                     set_grad_enabled, GradNode)
from .py_layer import PyLayer, PyLayerContext

__all__ = ["backward", "grad", "no_grad", "enable_grad", "is_grad_enabled",
           "set_grad_enabled", "PyLayer", "PyLayerContext"]
