"""Define-by-run autograd engine (ref: paddle/fluid/eager/backward.cc, grad_node_info.h).

TPU-native design: instead of per-op hand-written GradNodes codegen'd from
backward.yaml, every eager op records ONE GradNode holding the ``jax.vjp``
closure of its traced forward. Backward is a reverse-topological sweep over
nodes, accumulating cotangents per producer output slot, exactly like the
reference's ``egr::Backward`` queue — but each node's grad kernel is the
XLA-compiled vjp instead of a CUDA kernel.
"""
from __future__ import annotations

import contextlib
import functools
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

_grad_enabled = True


def is_grad_enabled() -> bool:
    return _grad_enabled


def set_grad_enabled(mode: bool):
    global _grad_enabled
    _grad_enabled = bool(mode)


class no_grad(contextlib.ContextDecorator):
    """Context manager / decorator disabling autograd taping (paddle.no_grad parity)."""

    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = False
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False

    # allow use as plain decorator: @no_grad
    def __call__(self, func=None):
        if func is None:
            return self
        @functools.wraps(func)
        def wrapper(*a, **k):
            with no_grad():
                return func(*a, **k)
        return wrapper


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = True
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False


def _zero_cotangent(shape, dtype):
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


def _is_float0(g) -> bool:
    return getattr(g, "dtype", None) == jax.dtypes.float0


class GradNode:
    """One recorded op on the tape.

    Holds the vjp closure, strong refs to input Tensors (keeps the graph alive
    until backward, like the reference's GradNode input metas), the output tree
    structure, and accumulated pending cotangents per output slot.
    """

    __slots__ = ("name", "vjp_fn", "inputs", "out_treedef", "out_avals",
                 "pending", "out_hooks", "__weakref__")

    def __init__(self, name, vjp_fn, inputs, out_treedef, out_avals):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = inputs           # list[Tensor], positional wrt vjp primals
        self.out_treedef = out_treedef
        self.out_avals = out_avals     # list[(shape, dtype)] per flat output
        self.pending: Dict[int, Any] = {}
        self.out_hooks: Dict[int, List] = {}

    def producers(self):
        seen = []
        ids = set()
        for t in self.inputs:
            p = t._grad_node
            if p is not None and id(p) not in ids:
                ids.add(id(p))
                seen.append(p)
        return seen

    def accumulate(self, idx: int, g):
        cur = self.pending.get(idx)
        self.pending[idx] = g if cur is None else cur + g

    def run_vjp(self):
        cts = []
        for i, (shape, dtype) in enumerate(self.out_avals):
            g = self.pending.get(i)
            if g is None:
                g = _zero_cotangent(shape, dtype)
            else:
                for hook in self.out_hooks.get(i, ()):
                    res = hook_call(hook, g)
                    if res is not None:
                        g = res
            cts.append(g)
        self.pending.clear()  # consumed; a retained graph must start fresh
        ct_tree = jax.tree_util.tree_unflatten(self.out_treedef, cts)
        return self.vjp_fn(ct_tree)

    def release(self):
        self.vjp_fn = None
        self.inputs = ()
        self.pending.clear()


def hook_call(hook, g):
    from ..tensor.tensor import Tensor
    res = hook(Tensor._from_data(g, stop_gradient=True))
    if res is None:
        return None
    return res._data if isinstance(res, Tensor) else res


def _accumulate_leaf(tensor, g):
    from ..tensor.tensor import Tensor
    for hook in tensor._hooks:
        res = hook_call(hook, g)
        if res is not None:
            g = res
    if tensor.grad is None:
        tensor.grad = Tensor._from_data(g, stop_gradient=True)
    else:
        tensor.grad._data = tensor.grad._data + g


def backward(tensor, grad_tensor=None, retain_graph: bool = False):
    """Run backward from ``tensor``, accumulating into leaf ``.grad``s."""
    from ..tensor.tensor import Tensor

    data = tensor._data
    if grad_tensor is None:
        if data.size != 1:
            raise RuntimeError(
                "grad_tensor can only be None for scalar outputs "
                f"(got shape {tuple(data.shape)})")
        seed = jnp.ones_like(data)
    else:
        seed = grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)
        seed = jnp.broadcast_to(seed, data.shape).astype(data.dtype)

    root = tensor._grad_node
    if root is None:
        if not tensor.stop_gradient:
            _accumulate_leaf(tensor, seed)
        return

    # Count reachable consumer edges per node (Kahn over the reverse graph).
    indeg: Dict[int, int] = {id(root): 0}
    nodes: Dict[int, GradNode] = {id(root): root}
    stack = [root]
    while stack:
        n = stack.pop()
        for p in n.producers():
            pid = id(p)
            indeg[pid] = indeg.get(pid, 0) + 1
            if pid not in nodes:
                nodes[pid] = p
                stack.append(p)

    root.accumulate(tensor._out_index, seed)
    queue: List[GradNode] = [root]
    while queue:
        n = queue.pop()
        in_grads = n.run_vjp()
        consumed_inputs = n.inputs
        for t, g in zip(consumed_inputs, in_grads):
            if g is None or _is_float0(g):
                continue
            if t.stop_gradient:
                continue
            p = t._grad_node
            if p is None:
                _accumulate_leaf(t, g)
            else:
                p.accumulate(t._out_index, g)
        for p in n.producers():
            pid = id(p)
            indeg[pid] -= 1
            if indeg[pid] == 0:
                queue.append(p)
        if not retain_graph:
            n.release()


def grad(outputs, inputs, grad_outputs=None, retain_graph=False,
         create_graph=False, allow_unused=False):
    """paddle.grad parity: return grads of outputs w.r.t. inputs without
    touching ``.grad`` fields. Implemented via a scoped backward that records
    leaf grads into a side table."""
    from ..tensor.tensor import Tensor
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]

    saved = [(t.grad, t.stop_gradient) for t in inputs]
    for t in inputs:
        t.grad = None
        t.stop_gradient = False
    try:
        for o, go in zip(outputs, grad_outputs):
            backward(o, go, retain_graph=retain_graph or create_graph)
        results = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError("an input tensor received no gradient; "
                                       "pass allow_unused=True to permit this")
                results.append(None)
            else:
                results.append(t.grad)
        return results
    finally:
        for t, (g, sg) in zip(inputs, saved):
            t.grad = g
            t.stop_gradient = sg
