"""Define-by-run autograd engine (ref: paddle/fluid/eager/backward.cc, grad_node_info.h).

TPU-native design: instead of per-op hand-written GradNodes codegen'd from
backward.yaml, every eager op records ONE GradNode holding the ``jax.vjp``
closure of its traced forward. Backward is a reverse-topological sweep over
nodes, accumulating cotangents per producer output slot, exactly like the
reference's ``egr::Backward`` queue — but each node's grad kernel is the
XLA-compiled vjp instead of a CUDA kernel.
"""
from __future__ import annotations

import contextlib
import functools
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

_grad_enabled = True


def is_grad_enabled() -> bool:
    return _grad_enabled


def set_grad_enabled(mode: bool):
    global _grad_enabled
    _grad_enabled = bool(mode)


class no_grad(contextlib.ContextDecorator):
    """Context manager / decorator disabling autograd taping (paddle.no_grad parity)."""

    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = False
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False

    # allow use as plain decorator: @no_grad
    def __call__(self, func=None):
        if func is None:
            return self
        @functools.wraps(func)
        def wrapper(*a, **k):
            with no_grad():
                return func(*a, **k)
        return wrapper


class enable_grad(contextlib.ContextDecorator):
    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = True
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False


def _zero_cotangent(shape, dtype):
    if jnp.issubdtype(dtype, jnp.inexact):
        return jnp.zeros(shape, dtype)
    return np.zeros(shape, jax.dtypes.float0)


def _is_float0(g) -> bool:
    return getattr(g, "dtype", None) == jax.dtypes.float0


class GradNode:
    """One recorded op on the tape.

    Holds the vjp closure, strong refs to input Tensors (keeps the graph alive
    until backward, like the reference's GradNode input metas), the output tree
    structure, and accumulated pending cotangents per output slot.
    """

    __slots__ = ("name", "vjp_fn", "call_fn", "inputs", "out_treedef",
                 "out_avals", "pending", "out_hooks", "input_versions",
                 "__weakref__")

    def __init__(self, name, vjp_fn, inputs, out_treedef, out_avals,
                 call_fn=None):
        self.name = name
        self.vjp_fn = vjp_fn
        self.call_fn = call_fn         # raw forward (for create_graph re-vjp)
        self.inputs = inputs           # list[Tensor], positional wrt vjp primals
        self.out_treedef = out_treedef
        self.out_avals = out_avals     # list[(shape, dtype)] per flat output
        self.pending: Dict[int, Any] = {}
        self.out_hooks: Dict[int, List] = {}
        self.input_versions: Optional[List[int]] = None  # inplace_version @ record

    def check_versions(self):
        """Reference inplace_version check: raise if any input was modified
        in place after this op recorded it (its grads would otherwise be
        routed through the post-write graph silently)."""
        if self.input_versions is None:
            return
        for t, v in zip(self.inputs, self.input_versions):
            if t._inplace_version != v:
                raise RuntimeError(
                    f"tensor used by {self.name} (recorded inplace_version "
                    f"{v}) was modified by an in-place operation "
                    f"(current version {t._inplace_version}); gradient "
                    "computation through the old value is not possible")

    def producers(self):
        seen = []
        ids = set()
        for t in self.inputs:
            p = t._grad_node
            if p is not None and id(p) not in ids:
                ids.add(id(p))
                seen.append(p)
        return seen

    def accumulate(self, idx: int, g):
        cur = self.pending.get(idx)
        self.pending[idx] = g if cur is None else cur + g

    def collect_cts(self, slots, zero_fn, taped_hooks):
        """Shared cotangent collection: zero-fill missing output slots,
        apply output hooks (raw-array style or Tensor style), clear pending.
        Used by all four run_vjp variants (GradNode/_PyLayerNode x
        plain/taped) so the semantics can't diverge."""
        cts = []
        for i in slots:
            shape, dtype = self.out_avals[i]
            g = self.pending.get(i)
            if g is None:
                g = zero_fn(shape, dtype)
            else:
                for hook in self.out_hooks.get(i, ()):
                    res = hook(g) if taped_hooks else hook_call(hook, g)
                    if res is not None:
                        g = res
            cts.append(g)
        self.pending.clear()  # consumed; a retained graph must start fresh
        return cts

    def run_vjp(self):
        cts = self.collect_cts(range(len(self.out_avals)), _zero_cotangent,
                               taped_hooks=False)
        ct_tree = jax.tree_util.tree_unflatten(self.out_treedef, cts)
        return self.vjp_fn(ct_tree)

    def run_vjp_taped(self):
        """create_graph mode: the node's backward is itself RECORDED as a
        taped op (ref: the reference's codegen'd double-grad nodes,
        paddle/fluid/eager/backward.cc). The saved vjp closure can't be used
        — it bakes the primal residuals in as constants, so second
        derivatives w.r.t. the primals would silently be zero. Instead the
        op's forward is re-vjp'd INSIDE a taped grad op whose inputs are
        (primals, cotangents); grad-of-grad then flows through both."""
        from ..tensor.tensor import Tensor, apply_op
        if self.call_fn is None:
            raise RuntimeError(
                f"GradNode {self.name} has no retained forward; double "
                "backward requires the graph to have been built with grad "
                "enabled (and not released by a prior backward)")
        inexact_out = [i for i, (_, d) in enumerate(self.out_avals)
                       if jnp.issubdtype(d, jnp.inexact)]
        cts = self.collect_cts(
            inexact_out,
            lambda s, d: Tensor._from_data(jnp.zeros(s, d),
                                           stop_gradient=True),
            taped_hooks=True)
        n_in = len(self.inputs)
        diff_idx = [i for i, t in enumerate(self.inputs)
                    if jnp.issubdtype(t._data.dtype, jnp.inexact)]
        call_fn = self.call_fn
        out_treedef, out_avals = self.out_treedef, self.out_avals
        inexact_set = set(inexact_out)

        def grad_fn(*primals_and_cts):
            primals = primals_and_cts[:n_in]
            it = iter(primals_and_cts[n_in:])
            flat_cts = []
            for i, (shape, dtype) in enumerate(out_avals):
                if i in inexact_set:
                    flat_cts.append(next(it))
                else:
                    flat_cts.append(np.zeros(shape, jax.dtypes.float0))
            ct_tree = jax.tree_util.tree_unflatten(out_treedef, flat_cts)
            _, vjp_fn = jax.vjp(call_fn, *primals)
            gs = vjp_fn(ct_tree)
            return tuple(gs[i] for i in diff_idx)

        outs = apply_op(f"{self.name}_grad", grad_fn, *self.inputs, *cts)
        if not isinstance(outs, (list, tuple)):
            outs = (outs,)
        full = [None] * n_in
        for j, i in enumerate(diff_idx):
            full[i] = outs[j]
        return full

    def release(self):
        self.vjp_fn = None
        self.call_fn = None
        self.inputs = ()
        self.pending.clear()


def hook_call(hook, g):
    from ..tensor.tensor import Tensor
    res = hook(Tensor._from_data(g, stop_gradient=True))
    if res is None:
        return None
    return res._data if isinstance(res, Tensor) else res


def _accumulate_leaf(tensor, g):
    from ..tensor.tensor import Tensor
    for hook in tensor._hooks:
        res = hook_call(hook, g)
        if res is not None:
            g = res
    if tensor.grad is None:
        tensor.grad = Tensor._from_data(g, stop_gradient=True)
    else:
        tensor.grad._data = tensor.grad._data + g


def _accumulate_leaf_taped(tensor, g):
    """create_graph mode: g is a taped Tensor; .grad keeps its graph so
    paddle.grad(grad, x) can differentiate through it."""
    for hook in tensor._hooks:
        res = hook(g)
        if res is not None:
            g = res
    tensor.grad = g if tensor.grad is None else tensor.grad + g


def backward(tensor, grad_tensor=None, retain_graph: bool = False,
             create_graph: bool = False, _sink: Optional[Dict[int, Any]] = None):
    """Run backward from ``tensor``, accumulating into leaf ``.grad``s.

    With create_graph, every node's backward is recorded on the tape (see
    GradNode.run_vjp_taped) so the resulting grads are differentiable.
    With _sink (paddle.grad), leaf grads go into the side table keyed by
    id(tensor) instead of .grad — grad() must not touch ANY leaf's .grad,
    including leaves the caller didn't ask about."""
    backward_multi([(tensor, grad_tensor)], retain_graph=retain_graph,
                   create_graph=create_graph, _sink=_sink)


def backward_multi(pairs, retain_graph: bool = False,
                   create_graph: bool = False,
                   _sink: Optional[Dict[int, Any]] = None):
    """One reverse sweep over the union graph of several (output, grad)
    roots: every shared node's vjp runs exactly once with all cotangents
    seeded, instead of once per output."""
    from ..tensor.tensor import Tensor

    def leaf_accumulate(t, g):
        if _sink is not None:
            for hook in t._hooks:
                res = hook(g) if create_graph else hook_call(hook, g)
                if res is not None:
                    g = res
            cur = _sink.get(id(t))
            _sink[id(t)] = g if cur is None else cur + g
        elif create_graph:
            _accumulate_leaf_taped(t, g)
        else:
            _accumulate_leaf(t, g)

    roots: List[GradNode] = []
    root_ids = set()
    for tensor, grad_tensor in pairs:
        data = tensor._data
        if grad_tensor is None:
            if data.size != 1:
                raise RuntimeError(
                    "grad_tensor can only be None for scalar outputs "
                    f"(got shape {tuple(data.shape)})")
            seed = jnp.ones_like(data)
        else:
            seed = (grad_tensor._data if isinstance(grad_tensor, Tensor)
                    else jnp.asarray(grad_tensor))
            seed = jnp.broadcast_to(seed, data.shape).astype(data.dtype)
        if create_graph:
            # a graph-carrying grad_tensor seeds the tape directly (shape
            # must match); otherwise the seed is a constant
            if (isinstance(grad_tensor, Tensor)
                    and not grad_tensor.stop_gradient
                    and grad_tensor.shape == tuple(data.shape)):
                seed = grad_tensor
            else:
                seed = Tensor._from_data(seed, stop_gradient=True)

        root = tensor._grad_node
        if root is None:
            if not tensor.stop_gradient:
                leaf_accumulate(tensor, seed)
            continue
        root.accumulate(tensor._out_index, seed)
        if id(root) not in root_ids:
            root_ids.add(id(root))
            roots.append(root)
    if not roots:
        return

    # Count reachable consumer edges per node (Kahn over the reverse graph).
    indeg: Dict[int, int] = {id(r): 0 for r in roots}
    nodes: Dict[int, GradNode] = {id(r): r for r in roots}
    stack = list(roots)
    while stack:
        n = stack.pop()
        for p in n.producers():
            pid = id(p)
            indeg[pid] = indeg.get(pid, 0) + 1
            if pid not in nodes:
                nodes[pid] = p
                stack.append(p)

    queue: List[GradNode] = [r for r in roots if indeg[id(r)] == 0]
    while queue:
        n = queue.pop()
        n.check_versions()
        in_grads = n.run_vjp_taped() if create_graph else n.run_vjp()
        consumed_inputs = n.inputs
        for t, g in zip(consumed_inputs, in_grads):
            if g is None or _is_float0(g):
                continue
            if t.stop_gradient:
                continue
            p = t._grad_node
            if p is None:
                leaf_accumulate(t, g)
            else:
                p.accumulate(t._out_index, g)
        for p in n.producers():
            pid = id(p)
            indeg[pid] -= 1
            if indeg[pid] == 0:
                queue.append(p)
        if not (retain_graph or create_graph):
            n.release()


def grad(outputs, inputs, grad_outputs=None, retain_graph=False,
         create_graph=False, allow_unused=False):
    """paddle.grad parity: return grads of outputs w.r.t. inputs without
    touching ``.grad`` fields. Implemented via a scoped backward that records
    leaf grads into a side table."""
    from ..tensor.tensor import Tensor
    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]

    from ..tensor.tensor import Tensor as _T
    sink: Dict[int, Any] = {}
    saved_sg = [t.stop_gradient for t in inputs]
    for t in inputs:
        t.stop_gradient = False
    try:
        with enable_grad() if create_graph else contextlib.nullcontext():
            backward_multi(list(zip(outputs, grad_outputs)),
                           retain_graph=retain_graph or create_graph,
                           create_graph=create_graph, _sink=sink)
        results = []
        for t in inputs:
            g = sink.get(id(t))
            if g is None:
                if not allow_unused:
                    raise RuntimeError("an input tensor received no gradient; "
                                       "pass allow_unused=True to permit this")
                results.append(None)
            elif isinstance(g, _T):
                results.append(g)
            else:
                results.append(_T._from_data(g, stop_gradient=True))
        return results
    finally:
        for t, sg in zip(inputs, saved_sg):
            t.stop_gradient = sg
