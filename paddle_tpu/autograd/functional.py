"""Functional differentiation API (ref: python/paddle/autograd/
autograd.py — jacobian/hessian, and incubate.autograd vjp/jvp).

TPU-native: these are direct marshals onto jax's transforms — the tape
engine handles dygraph backward; jacobian/hessian/jvp/vjp are exactly the
functional transforms XLA was built around, so no graph surgery is
needed. Functions take and return paddle Tensors; multiple inputs pass as
a (tuple of) Tensors like the reference."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _tensor_cls():
    # lazy: tensor.tensor imports autograd.engine at module load, so a
    # top-level import here would be circular
    from ..tensor.tensor import Tensor
    return Tensor


def _unwrap(x):
    if isinstance(x, (list, tuple)):
        return tuple(_unwrap(v) for v in x)
    return x._data if isinstance(x, _tensor_cls()) else jnp.asarray(x)


def _wrap(x):
    if isinstance(x, (list, tuple)):
        return tuple(_wrap(v) for v in x)
    return _tensor_cls()._from_data(x)


def _fn_on_raw(func):
    def raw(*args):
        out = func(*_wrap(args))
        return _unwrap(out)
    return raw


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """d func / d xs. xs: Tensor or tuple of Tensors; returns the jacobian
    pytree mirroring (outputs x inputs) like the reference (single in/out
    -> a single Tensor)."""
    single = not isinstance(xs, (list, tuple))
    args = (xs,) if single else tuple(xs)
    jac = jax.jacobian(_fn_on_raw(func), argnums=tuple(range(len(args))))(
        *_unwrap(args))
    if single:
        jac = jac[0] if isinstance(jac, tuple) else jac
    return _wrap(jac)


def hessian(func, xs, create_graph=False, allow_unused=False):
    """d^2 func / d xs^2 for a SCALAR-output func (reference contract)."""
    single = not isinstance(xs, (list, tuple))
    args = (xs,) if single else tuple(xs)
    hes = jax.hessian(_fn_on_raw(func), argnums=tuple(range(len(args))))(
        *_unwrap(args))
    if single:
        hes = hes[0][0] if isinstance(hes, tuple) else hes
    return _wrap(hes)


def vjp(func, xs, v=None):
    """(outputs, vjp_result): pull v back through func at xs (ref:
    incubate.autograd.vjp). v defaults to ones like the output."""
    single = not isinstance(xs, (list, tuple))
    args = (xs,) if single else tuple(xs)
    out, pullback = jax.vjp(_fn_on_raw(func), *_unwrap(args))
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        cot = _unwrap(v)
    grads = pullback(cot)
    if single:
        grads = grads[0]
    return _wrap(out), _wrap(grads)


def jvp(func, xs, v=None):
    """(outputs, jvp_result): push v forward through func at xs."""
    single = not isinstance(xs, (list, tuple))
    args = (xs,) if single else tuple(xs)
    raw_args = _unwrap(args)
    if v is None:
        tangents = jax.tree_util.tree_map(jnp.ones_like, raw_args)
    else:
        tangents = _unwrap((v,) if single else v)
    out, tang = jax.jvp(_fn_on_raw(func), raw_args, tangents)
    return _wrap(out), _wrap(tang)
