"""Custom autograd functions (ref: python/paddle/autograd/py_layer.py).

PyLayer lets users define forward/backward in Python; the recorded GradNode
calls the user's ``backward`` instead of a jax.vjp closure. This is the
mechanism `recompute` (activation checkpointing) builds on, like the reference.
"""
from __future__ import annotations

import jax

from . import engine


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        return self._saved


class _PyLayerNode(engine.GradNode):
    """GradNode whose vjp is the user's backward()."""

    __slots__ = ("ctx", "layer_cls", "n_inputs")

    def __init__(self, layer_cls, ctx, inputs, out_treedef, out_avals):
        super().__init__(layer_cls.__name__, None, inputs, out_treedef, out_avals)
        self.ctx = ctx
        self.layer_cls = layer_cls

    def run_vjp(self):
        from ..tensor.tensor import Tensor
        cts = self.collect_cts(range(len(self.out_avals)),
                               engine._zero_cotangent, taped_hooks=False)
        cts = [Tensor._from_data(g, stop_gradient=True) for g in cts]
        with engine.no_grad():
            grads = self.layer_cls.backward(self.ctx, *cts)
        if not isinstance(grads, (tuple, list)):
            grads = (grads,)
        out = []
        for g in grads:
            out.append(None if g is None else (g._data if isinstance(g, Tensor) else g))
        return tuple(out)

    def run_vjp_taped(self):
        """create_graph mode: run the user's backward WITH grad enabled so
        its eager ops land on the tape and the returned grads are themselves
        differentiable (the reference's PyLayer double-grad contract)."""
        from ..tensor.tensor import Tensor
        cts = self.collect_cts(
            range(len(self.out_avals)),
            lambda s, d: Tensor._from_data(engine._zero_cotangent(s, d),
                                           stop_gradient=True),
            taped_hooks=True)
        with engine.enable_grad():
            grads = self.layer_cls.backward(self.ctx, *cts)
        if not isinstance(grads, (tuple, list)):
            grads = (grads,)
        return tuple(grads)

    def release(self):
        self.ctx = None
        self.inputs = ()
        self.pending.clear()


class PyLayer:
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..tensor.tensor import Tensor
        ctx = PyLayerContext()
        in_tensors = [a for a in args if isinstance(a, Tensor)]
        needs_grad = (engine.is_grad_enabled()
                      and any(not t.stop_gradient for t in in_tensors))
        with engine.no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        out_list = [outs] if single else list(outs)
        if not needs_grad:
            return outs
        out_leaves = [o._data for o in out_list]
        _, out_treedef = jax.tree_util.tree_flatten(out_leaves)
        avals = [(tuple(o.shape), o.dtype) for o in out_leaves]
        node = _PyLayerNode(cls, ctx, in_tensors, out_treedef, avals)
        wrapped = [Tensor._from_data(o, node=node, out_index=i, stop_gradient=False)
                   for i, o in enumerate(out_leaves)]
        return wrapped[0] if single else tuple(wrapped)
