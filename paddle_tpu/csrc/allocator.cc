// Best-fit caching host allocator
// (ref: paddle/fluid/memory/allocation/auto_growth_best_fit_allocator.cc).
//
// On TPU the device heap belongs to PJRT/XLA; what the framework still owns
// is host staging memory — the buffers DataLoader workers collate batches
// into before the host->HBM transfer (the pinned-pool analog).  Strategy
// mirrors the reference's AutoGrowthBestFit: grab OS chunks of at least
// `chunk_bytes`, carve blocks best-fit from a size-ordered free map, coalesce
// with neighbors on free, keep everything cached until release_free().
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <unordered_map>

#include "common.h"
#include "pd_runtime.h"

namespace pd {
namespace {

constexpr uint64_t kAlignment = 64;
constexpr uint64_t kSplitThreshold = 256;

inline uint64_t align_up(uint64_t n) {
  return (n + kAlignment - 1) & ~(kAlignment - 1);
}

struct Chunk;

struct Block {
  char* ptr;
  uint64_t size;
  bool free;
  Chunk* chunk;
  Block* prev;  // address-adjacent neighbors within the chunk
  Block* next;
};

struct Chunk {
  char* base;
  uint64_t size;
};

class Allocator {
 public:
  explicit Allocator(uint64_t chunk_bytes)
      : chunk_bytes_(chunk_bytes ? chunk_bytes : (64ull << 20)) {}

  ~Allocator() {
    for (auto& kv : chunks_) std::free(kv.first);
  }

  void* Alloc(uint64_t nbytes) {
    if (nbytes == 0) nbytes = kAlignment;
    nbytes = align_up(nbytes);
    std::lock_guard<std::mutex> lk(mu_);
    auto it = free_blocks_.lower_bound(nbytes);
    Block* b;
    if (it != free_blocks_.end()) {
      b = it->second;
      free_blocks_.erase(it);
    } else {
      b = NewChunkBlock(nbytes);
      if (!b) return nullptr;
    }
    b->free = false;
    if (b->size >= nbytes + kSplitThreshold) {
      Block* rest = new Block{b->ptr + nbytes, b->size - nbytes, true,
                              b->chunk, b, b->next};
      if (b->next) b->next->prev = rest;
      b->next = rest;
      b->size = nbytes;
      free_blocks_.emplace(rest->size, rest);
    }
    live_[b->ptr] = b;
    allocated_ += b->size;
    if (allocated_ > peak_) peak_ = allocated_;
    return b->ptr;
  }

  bool Free(void* p) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = live_.find(static_cast<char*>(p));
    if (it == live_.end()) return false;
    Block* b = it->second;
    live_.erase(it);
    allocated_ -= b->size;
    b->free = true;
    // Coalesce with address-adjacent free neighbors.
    if (b->next && b->next->free) {
      Block* n = b->next;
      EraseFree(n);
      b->size += n->size;
      b->next = n->next;
      if (n->next) n->next->prev = b;
      delete n;
    }
    if (b->prev && b->prev->free) {
      Block* p2 = b->prev;
      EraseFree(p2);
      p2->size += b->size;
      p2->next = b->next;
      if (b->next) b->next->prev = p2;
      delete b;
      b = p2;
    }
    free_blocks_.emplace(b->size, b);
    return true;
  }

  void Stats(uint64_t* allocated, uint64_t* reserved, uint64_t* peak) {
    std::lock_guard<std::mutex> lk(mu_);
    if (allocated) *allocated = allocated_;
    if (reserved) *reserved = reserved_;
    if (peak) *peak = peak_;
  }

  uint64_t ReleaseFree() {
    std::lock_guard<std::mutex> lk(mu_);
    uint64_t released = 0;
    for (auto it = chunks_.begin(); it != chunks_.end();) {
      Block* b = it->second;
      // A chunk is releasable iff it is one free block spanning the chunk.
      if (b->free && !b->prev && !b->next) {
        EraseFree(b);
        released += b->size;
        reserved_ -= b->size;
        std::free(it->first);
        delete b;
        it = chunks_.erase(it);
      } else {
        ++it;
      }
    }
    return released;
  }

 private:
  Block* NewChunkBlock(uint64_t nbytes) {
    uint64_t sz = nbytes > chunk_bytes_ ? nbytes : chunk_bytes_;
    char* mem = static_cast<char*>(std::malloc(sz));
    if (!mem) {
      set_last_error("allocator: malloc(%llu) failed",
                     static_cast<unsigned long long>(sz));
      return nullptr;
    }
    reserved_ += sz;
    Block* b = new Block{mem, sz, false, nullptr, nullptr, nullptr};
    chunks_.emplace(mem, b);
    return b;
  }

  void EraseFree(Block* b) {
    auto range = free_blocks_.equal_range(b->size);
    for (auto it = range.first; it != range.second; ++it) {
      if (it->second == b) {
        free_blocks_.erase(it);
        return;
      }
    }
  }

  std::mutex mu_;
  uint64_t chunk_bytes_;
  std::multimap<uint64_t, Block*> free_blocks_;
  std::unordered_map<char*, Block*> live_;
  // chunk base pointer -> first block in chunk (for release bookkeeping).
  std::unordered_map<char*, Block*> chunks_;
  uint64_t allocated_ = 0;
  uint64_t reserved_ = 0;
  uint64_t peak_ = 0;
};

}  // namespace
}  // namespace pd

extern "C" {

pd_allocator_t pd_allocator_create(uint64_t chunk_bytes) {
  return new pd::Allocator(chunk_bytes);
}

void pd_allocator_destroy(pd_allocator_t a) {
  delete static_cast<pd::Allocator*>(a);
}

void* pd_alloc(pd_allocator_t a, uint64_t nbytes) {
  return static_cast<pd::Allocator*>(a)->Alloc(nbytes);
}

void pd_free(pd_allocator_t a, void* ptr) {
  if (!ptr) return;
  if (!static_cast<pd::Allocator*>(a)->Free(ptr)) {
    pd::set_last_error("pd_free: pointer %p not owned by allocator", ptr);
  }
}

void pd_allocator_stats(pd_allocator_t a, uint64_t* allocated,
                        uint64_t* reserved, uint64_t* peak) {
  static_cast<pd::Allocator*>(a)->Stats(allocated, reserved, peak);
}

uint64_t pd_allocator_release_free(pd_allocator_t a) {
  return static_cast<pd::Allocator*>(a)->ReleaseFree();
}

}  // extern "C"
