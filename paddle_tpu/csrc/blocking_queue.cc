// Bounded MPMC blocking queue of opaque handles
// (ref: the reader BlockingQueue behind paddle/fluid/operators/reader/ that
// python/paddle/io's DataLoader feeds).  Handles are uint64 tokens the Python
// side maps to staged batches; capacity gives prefetch backpressure.
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>

#include "pd_runtime.h"

namespace pd {
namespace {

class BlockingQueue {
 public:
  explicit BlockingQueue(int capacity)
      : capacity_(capacity > 0 ? capacity : 1) {}

  int Push(uint64_t h, double timeout_s) {
    std::unique_lock<std::mutex> lk(mu_);
    auto pred = [&] { return closed_ || (int)q_.size() < capacity_; };
    if (!Wait(not_full_, lk, timeout_s, pred)) return -1;
    if (closed_) return -2;
    q_.push_back(h);
    not_empty_.notify_one();
    return 0;
  }

  int Pop(uint64_t* h, double timeout_s) {
    std::unique_lock<std::mutex> lk(mu_);
    auto pred = [&] { return closed_ || !q_.empty(); };
    if (!Wait(not_empty_, lk, timeout_s, pred)) return -1;
    if (q_.empty()) return -2;  // closed and drained
    *h = q_.front();
    q_.pop_front();
    not_full_.notify_one();
    return 0;
  }

  void Close() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

  int Size() {
    std::lock_guard<std::mutex> lk(mu_);
    return static_cast<int>(q_.size());
  }

  bool Closed() {
    std::lock_guard<std::mutex> lk(mu_);
    return closed_;
  }

 private:
  template <typename Pred>
  bool Wait(std::condition_variable& cv, std::unique_lock<std::mutex>& lk,
            double timeout_s, Pred pred) {
    if (timeout_s < 0) {
      cv.wait(lk, pred);
      return true;
    }
    return cv.wait_for(lk, std::chrono::duration<double>(timeout_s), pred);
  }

  std::mutex mu_;
  std::condition_variable not_empty_;
  std::condition_variable not_full_;
  int capacity_;
  std::deque<uint64_t> q_;
  bool closed_ = false;
};

}  // namespace
}  // namespace pd

extern "C" {

pd_queue_t pd_queue_create(int capacity) {
  return new pd::BlockingQueue(capacity);
}

void pd_queue_destroy(pd_queue_t q) {
  delete static_cast<pd::BlockingQueue*>(q);
}

int pd_queue_push(pd_queue_t q, uint64_t handle, double timeout_s) {
  return static_cast<pd::BlockingQueue*>(q)->Push(handle, timeout_s);
}

int pd_queue_pop(pd_queue_t q, uint64_t* handle, double timeout_s) {
  return static_cast<pd::BlockingQueue*>(q)->Pop(handle, timeout_s);
}

void pd_queue_close(pd_queue_t q) {
  static_cast<pd::BlockingQueue*>(q)->Close();
}

int pd_queue_size(pd_queue_t q) {
  return static_cast<pd::BlockingQueue*>(q)->Size();
}

int pd_queue_is_closed(pd_queue_t q) {
  return static_cast<pd::BlockingQueue*>(q)->Closed() ? 1 : 0;
}

}  // extern "C"
