// Shared internals for the native runtime (ref: paddle/common/enforce.h).
#ifndef PD_COMMON_H_
#define PD_COMMON_H_

#include <cstdarg>
#include <cstdio>
#include <string>

namespace pd {

// Per-thread last-error slot surfaced through pd_last_error().
std::string& last_error_slot();

inline void set_last_error(const char* fmt, ...) {
  char buf[1024];
  va_list ap;
  va_start(ap, fmt);
  vsnprintf(buf, sizeof(buf), fmt, ap);
  va_end(ap);
  last_error_slot() = buf;
}

}  // namespace pd

#endif  // PD_COMMON_H_
