// Typed flag registry with env override (ref: paddle/common/flags.cc,
// PHI_DEFINE_EXPORTED_*).  Values are stored as strings; typing lives in the
// Python layer, which mirrors the reference where FLAGS parse from env text.
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>

#include "common.h"
#include "pd_runtime.h"

namespace pd {

std::string& last_error_slot() {
  static thread_local std::string slot;
  return slot;
}

namespace {

struct FlagEntry {
  std::string def;
  std::string help;
  std::string value;  // runtime override; empty + !has_value means unset
  bool has_value = false;
};

std::mutex g_mu;
std::map<std::string, FlagEntry>& registry() {
  static std::map<std::string, FlagEntry> r;
  return r;
}

std::string json_escape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char tmp[8];
          snprintf(tmp, sizeof(tmp), "\\u%04x", c);
          out += tmp;
        } else {
          out += c;
        }
    }
  }
  return out;
}

}  // namespace
}  // namespace pd

extern "C" {

int pd_runtime_abi_version(void) { return PD_RUNTIME_ABI_VERSION; }

const char* pd_last_error(void) { return pd::last_error_slot().c_str(); }

int pd_flag_define(const char* name, const char* default_value,
                   const char* help) {
  if (!name || !default_value) {
    pd::set_last_error("pd_flag_define: null name/default");
    return -1;
  }
  std::lock_guard<std::mutex> lk(pd::g_mu);
  auto& e = pd::registry()[name];
  e.def = default_value;
  e.help = help ? help : "";
  return 0;
}

int pd_flag_set(const char* name, const char* value) {
  if (!name || !value) {
    pd::set_last_error("pd_flag_set: null name/value");
    return -1;
  }
  std::lock_guard<std::mutex> lk(pd::g_mu);
  auto it = pd::registry().find(name);
  if (it == pd::registry().end()) {
    pd::set_last_error("unknown flag: %s", name);
    return -2;
  }
  it->second.value = value;
  it->second.has_value = true;
  return 0;
}

const char* pd_flag_get(const char* name) {
  static thread_local std::string out;
  if (!name) return nullptr;
  std::lock_guard<std::mutex> lk(pd::g_mu);
  auto it = pd::registry().find(name);
  if (it == pd::registry().end()) return nullptr;
  if (it->second.has_value) {
    out = it->second.value;
    return out.c_str();
  }
  std::string env_name = std::string("FLAGS_") + name;
  if (const char* env = std::getenv(env_name.c_str())) {
    out = env;
    return out.c_str();
  }
  out = it->second.def;
  return out.c_str();
}

int pd_flags_list(char* buf, int cap) {
  std::string json = "{";
  {
    std::lock_guard<std::mutex> lk(pd::g_mu);
    bool first = true;
    for (auto& kv : pd::registry()) {
      if (!first) json += ",";
      first = false;
      const std::string cur =
          kv.second.has_value ? kv.second.value : kv.second.def;
      json += "\"" + pd::json_escape(kv.first) + "\":{\"value\":\"" +
              pd::json_escape(cur) + "\",\"default\":\"" +
              pd::json_escape(kv.second.def) + "\",\"help\":\"" +
              pd::json_escape(kv.second.help) + "\"}";
    }
  }
  json += "}";
  if (buf && cap > 0) {
    int n = static_cast<int>(json.size());
    int w = n < cap - 1 ? n : cap - 1;
    memcpy(buf, json.data(), w);
    buf[w] = '\0';
  }
  return static_cast<int>(json.size());
}

}  // extern "C"
