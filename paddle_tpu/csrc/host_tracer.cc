// Host-side span tracer with chrome-trace export
// (ref: paddle/fluid/platform/profiler/host_tracer.cc, RecordEvent,
//  chrometracing_logger.cc).  The device side on TPU comes from the XLA
// profiler (xplane -> TensorBoard/Perfetto); this covers the host: Python-op
// dispatch, DataLoader, checkpoint threads.  Export merges into one
// chrome://tracing JSON the Python profiler can also hand to perfetto.
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "pd_runtime.h"

namespace pd {
namespace {

enum class EventType : uint8_t { kSpan, kInstant, kCounter };

struct Event {
  EventType type;
  std::string name;
  uint64_t begin_ns;
  uint64_t end_ns;   // spans only
  double value;      // counters only
  uint32_t tid;
};

uint64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

std::atomic<bool> g_recording{false};
std::mutex g_mu;
std::vector<Event> g_events;
std::atomic<uint32_t> g_next_tid{0};

struct ThreadState {
  uint32_t tid;
  // Stack of open spans (name, begin) so begin/end nest per-thread.
  std::vector<std::pair<std::string, uint64_t>> open;
  ThreadState() : tid(g_next_tid.fetch_add(1)) {}
};

ThreadState& tls() {
  static thread_local ThreadState s;
  return s;
}

void emit(Event e) {
  std::lock_guard<std::mutex> lk(g_mu);
  if (g_events.size() < (1u << 22)) g_events.push_back(std::move(e));
}

std::string json_escape(const std::string& s) {
  std::string out;
  for (char c : s) {
    if (c == '"' || c == '\\') {
      out += '\\';
      out += c;
    } else if (static_cast<unsigned char>(c) >= 0x20) {
      out += c;
    }
  }
  return out;
}

}  // namespace
}  // namespace pd

extern "C" {

void pd_tracer_start(void) { pd::g_recording.store(true); }

void pd_tracer_stop(void) { pd::g_recording.store(false); }

int pd_tracer_is_recording(void) { return pd::g_recording.load() ? 1 : 0; }

void pd_tracer_clear(void) {
  std::lock_guard<std::mutex> lk(pd::g_mu);
  pd::g_events.clear();
}

void pd_trace_begin(const char* name) {
  // Push unconditionally so begin/end stay paired even when spans straddle a
  // tracer start/stop boundary; filtering happens at end time.
  pd::tls().open.emplace_back(name ? name : "", pd::now_ns());
}

void pd_trace_end(void) {
  auto& st = pd::tls();
  if (st.open.empty()) return;
  auto [name, begin] = st.open.back();
  st.open.pop_back();
  if (!pd::g_recording.load()) return;
  pd::emit({pd::EventType::kSpan, std::move(name), begin, pd::now_ns(), 0.0,
            st.tid});
}

void pd_trace_instant(const char* name) {
  if (!pd::g_recording.load()) return;
  pd::emit({pd::EventType::kInstant, name ? name : "", pd::now_ns(), 0, 0.0,
            pd::tls().tid});
}

void pd_trace_counter(const char* name, double value) {
  if (!pd::g_recording.load()) return;
  pd::emit({pd::EventType::kCounter, name ? name : "", pd::now_ns(), 0, value,
            pd::tls().tid});
}

int pd_tracer_export(char* buf, int cap) {
  std::string json = "{\"traceEvents\":[";
  {
    std::lock_guard<std::mutex> lk(pd::g_mu);
    bool first = true;
    char num[128];
    for (const auto& e : pd::g_events) {
      if (!first) json += ",";
      first = false;
      double ts_us = e.begin_ns / 1000.0;
      // Compose with std::string so arbitrarily long names can't truncate
      // the JSON mid-object.
      switch (e.type) {
        case pd::EventType::kSpan:
          snprintf(num, sizeof(num), "\"pid\":0,\"tid\":%u,\"ts\":%.3f,"
                   "\"dur\":%.3f}", e.tid, ts_us,
                   (e.end_ns - e.begin_ns) / 1000.0);
          json += "{\"ph\":\"X\",\"name\":\"" + pd::json_escape(e.name) +
                  "\"," + num;
          break;
        case pd::EventType::kInstant:
          snprintf(num, sizeof(num), "\"pid\":0,\"tid\":%u,\"ts\":%.3f,"
                   "\"s\":\"t\"}", e.tid, ts_us);
          json += "{\"ph\":\"i\",\"name\":\"" + pd::json_escape(e.name) +
                  "\"," + num;
          break;
        case pd::EventType::kCounter:
          snprintf(num, sizeof(num), "\"pid\":0,\"tid\":%u,\"ts\":%.3f,"
                   "\"args\":{\"value\":%g}}", e.tid, ts_us, e.value);
          json += "{\"ph\":\"C\",\"name\":\"" + pd::json_escape(e.name) +
                  "\"," + num;
          break;
      }
    }
  }
  json += "]}";
  if (buf && cap > 0) {
    int n = static_cast<int>(json.size());
    int w = n < cap - 1 ? n : cap - 1;
    memcpy(buf, json.data(), w);
    buf[w] = '\0';
  }
  return static_cast<int>(json.size());
}

}  // extern "C"
