/* C API for the paddle_tpu native runtime (libpd_runtime.so).
 *
 * TPU-native counterpart of the reference's native runtime surface
 * (ref: paddle/common/flags.cc, paddle/fluid/memory/allocation/,
 *  paddle/phi/core/distributed/store/tcp_store.cc,
 *  paddle/fluid/platform/profiler/).  Device memory itself is owned by
 * PJRT/XLA on TPU; this runtime owns everything around it: host staging
 * memory (the pinned-buffer-pool analog feeding host->HBM transfers),
 * prefetch queues, the multi-host rendezvous store, flags, and host tracing.
 *
 * Exposed over a plain C ABI so Python binds via ctypes (no pybind11 in the
 * image).  All functions are thread-safe unless noted.
 */
#ifndef PD_RUNTIME_H_
#define PD_RUNTIME_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define PD_RUNTIME_ABI_VERSION 1

int pd_runtime_abi_version(void);

/* ---------------- error reporting ----------------
 * Functions returning int use 0 = OK, negative = error.  The last error
 * message for the calling thread is retrievable here. */
const char* pd_last_error(void);

/* ---------------- flags (ref: paddle/common/flags.cc) ---------------- */
int pd_flag_define(const char* name, const char* default_value,
                   const char* help);
int pd_flag_set(const char* name, const char* value);
/* Returns value string (env FLAGS_<name> overrides default) or NULL if the
 * flag is unknown.  Pointer valid until the next pd_flag_get on this thread. */
const char* pd_flag_get(const char* name);
/* Writes a JSON object {name: {value, default, help}} into buf.  Returns the
 * number of bytes required (excluding NUL); if > cap, buf holds a truncated
 * string. */
int pd_flags_list(char* buf, int cap);

/* ------------- host allocator (ref: AutoGrowthBestFitAllocator) -------------
 * Best-fit caching allocator over malloc'd chunks; serves the host staging
 * arena for DataLoader batches so buffers are reused instead of churned. */
typedef void* pd_allocator_t;
pd_allocator_t pd_allocator_create(uint64_t chunk_bytes);
void pd_allocator_destroy(pd_allocator_t a);
void* pd_alloc(pd_allocator_t a, uint64_t nbytes);
void pd_free(pd_allocator_t a, void* ptr);
/* allocated = live bytes handed out, reserved = bytes malloc'd from the OS,
 * peak = high-water mark of allocated. */
void pd_allocator_stats(pd_allocator_t a, uint64_t* allocated,
                        uint64_t* reserved, uint64_t* peak);
/* Release fully-free chunks back to the OS; returns bytes released. */
uint64_t pd_allocator_release_free(pd_allocator_t a);

/* ------------- blocking queue (ref: the reader blocking queue used by
 * paddle/fluid/operators/reader + python/paddle/io prefetch) -------------
 * Bounded MPMC queue of opaque uint64 handles. */
typedef void* pd_queue_t;
pd_queue_t pd_queue_create(int capacity);
void pd_queue_destroy(pd_queue_t q);
/* 0 = ok, -1 = timeout, -2 = closed.  timeout_s < 0 means block forever. */
int pd_queue_push(pd_queue_t q, uint64_t handle, double timeout_s);
int pd_queue_pop(pd_queue_t q, uint64_t* handle, double timeout_s);
void pd_queue_close(pd_queue_t q);
int pd_queue_size(pd_queue_t q);
int pd_queue_is_closed(pd_queue_t q);

/* ------------- TCP store (ref: phi/core/distributed/store/tcp_store.cc) ----
 * Key/value rendezvous + barrier substrate for multi-host bootstrap, the
 * launch CLI, and elastic heartbeats. */
typedef void* pd_store_server_t;
typedef void* pd_store_client_t;
/* port 0 picks an ephemeral port (query with pd_store_server_port). */
pd_store_server_t pd_store_server_start(int port);
int pd_store_server_port(pd_store_server_t s);
void pd_store_server_stop(pd_store_server_t s);

pd_store_client_t pd_store_client_connect(const char* host, int port,
                                          double timeout_s);
void pd_store_client_close(pd_store_client_t c);
int pd_store_set(pd_store_client_t c, const char* key, const uint8_t* val,
                 int len);
/* Returns value length (may exceed cap; bytes up to cap are written), or
 * -1 on wait-timeout, -3 on connection error. timeout_s < 0 blocks forever
 * until the key exists. */
int pd_store_get(pd_store_client_t c, const char* key, uint8_t* buf, int cap,
                 double timeout_s);
/* Atomic add to an integer-valued key (created as 0); returns new value
 * (INT64_MIN on error). */
int64_t pd_store_add(pd_store_client_t c, const char* key, int64_t delta);
/* 0 once key exists, -1 on timeout. */
int pd_store_wait(pd_store_client_t c, const char* key, double timeout_s);
int pd_store_delete(pd_store_client_t c, const char* key);
int pd_store_num_keys(pd_store_client_t c);

/* ------------- host tracer (ref: paddle/fluid/platform/profiler) ------- */
void pd_tracer_start(void);
void pd_tracer_stop(void);
int pd_tracer_is_recording(void);
void pd_tracer_clear(void);
/* Begin/end nest per-thread; end closes the innermost open span. */
void pd_trace_begin(const char* name);
void pd_trace_end(void);
void pd_trace_instant(const char* name);
void pd_trace_counter(const char* name, double value);
/* Chrome-trace JSON. Returns bytes required (excluding NUL); truncates at
 * cap. Call with cap=0 to size the buffer. */
int pd_tracer_export(char* buf, int cap);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* PD_RUNTIME_H_ */
