// TCP key/value store for multi-host rendezvous
// (ref: paddle/phi/core/distributed/store/tcp_store.cc + tcp_utils.cc).
//
// The reference bootstraps ProcessGroupNCCL by exchanging NCCL uniqueIds
// through this store.  The TPU build has no NCCL, but the same substrate
// drives: launch-CLI rank rendezvous/barriers, elastic heartbeats (keys acting
// as TTL-free liveness counters), and user-level Store APIs.
//
// Wire protocol (all little-endian):
//   request : u8 cmd | u32 klen | key bytes | u32 vlen | value bytes
//   response: i64 status | u32 len | payload
// Commands: 1=SET 2=GET 3=ADD(value=i64 delta) 4=WAIT(value=f64 timeout)
//           5=DEL 6=NUMKEYS 7=GET_WAIT(value=f64 timeout)
// GET_WAIT blocks server-side until the key exists (or timeout -> status -1).
#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "common.h"
#include "pd_runtime.h"

namespace pd {
namespace {

enum Cmd : uint8_t {
  kSet = 1,
  kGet = 2,
  kAdd = 3,
  kWait = 4,
  kDel = 5,
  kNumKeys = 6,
  kGetWait = 7,
};

bool send_all(int fd, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= w;
  }
  return true;
}

bool recv_all(int fd, void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= r;
  }
  return true;
}

class StoreServer {
 public:
  explicit StoreServer(int port) : port_(port) {}

  bool Start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) {
      set_last_error("store server: socket() failed: %s", strerror(errno));
      return false;
    }
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
        0) {
      set_last_error("store server: bind(%d) failed: %s", port_,
                     strerror(errno));
      ::close(listen_fd_);
      return false;
    }
    socklen_t alen = sizeof(addr);
    getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen);
    port_ = ntohs(addr.sin_port);
    if (::listen(listen_fd_, 128) < 0) {
      set_last_error("store server: listen failed: %s", strerror(errno));
      ::close(listen_fd_);
      return false;
    }
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return true;
  }

  void Stop() {
    if (stopping_.exchange(true)) return;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    {
      std::lock_guard<std::mutex> lk(mu_);
      for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
      cv_.notify_all();
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    std::vector<std::thread> workers;
    {
      std::lock_guard<std::mutex> lk(mu_);
      workers.swap(workers_);
    }
    for (auto& t : workers)
      if (t.joinable()) t.join();
  }

  int port() const { return port_; }

 private:
  void AcceptLoop() {
    while (!stopping_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (stopping_.load()) return;
        continue;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(mu_);
      conn_fds_.push_back(fd);
      workers_.emplace_back([this, fd] { Serve(fd); });
    }
  }

  void Serve(int fd) {
    while (!stopping_.load()) {
      uint8_t cmd;
      uint32_t klen, vlen;
      if (!recv_all(fd, &cmd, 1) || !recv_all(fd, &klen, 4)) break;
      if (klen > (64u << 10)) break;
      std::string key(klen, '\0');
      if (klen && !recv_all(fd, &key[0], klen)) break;
      if (!recv_all(fd, &vlen, 4)) break;
      if (vlen > (256u << 20)) break;
      std::string val(vlen, '\0');
      if (vlen && !recv_all(fd, &val[0], vlen)) break;

      int64_t status = 0;
      std::string payload;
      switch (cmd) {
        case kSet: {
          std::lock_guard<std::mutex> lk(mu_);
          data_[key] = val;
          cv_.notify_all();
          break;
        }
        case kGet: {
          std::lock_guard<std::mutex> lk(mu_);
          auto it = data_.find(key);
          if (it == data_.end())
            status = -2;
          else
            payload = it->second;
          break;
        }
        case kGetWait:
        case kWait: {
          double timeout_s;
          memcpy(&timeout_s, val.data(), sizeof(double));
          std::unique_lock<std::mutex> lk(mu_);
          auto pred = [&] {
            return stopping_.load() || data_.count(key) > 0;
          };
          bool ok;
          if (timeout_s < 0) {
            cv_.wait(lk, pred);
            ok = data_.count(key) > 0;
          } else {
            ok = cv_.wait_for(lk, std::chrono::duration<double>(timeout_s),
                              pred) &&
                 data_.count(key) > 0;
          }
          if (!ok)
            status = -1;
          else if (cmd == kGetWait)
            payload = data_[key];
          break;
        }
        case kAdd: {
          int64_t delta;
          memcpy(&delta, val.data(), sizeof(int64_t));
          std::lock_guard<std::mutex> lk(mu_);
          int64_t cur = 0;
          auto it = data_.find(key);
          if (it != data_.end() && it->second.size() == sizeof(int64_t))
            memcpy(&cur, it->second.data(), sizeof(int64_t));
          cur += delta;
          std::string enc(sizeof(int64_t), '\0');
          memcpy(&enc[0], &cur, sizeof(int64_t));
          data_[key] = enc;
          cv_.notify_all();
          payload = enc;
          break;
        }
        case kDel: {
          std::lock_guard<std::mutex> lk(mu_);
          status = data_.erase(key) ? 0 : -2;
          break;
        }
        case kNumKeys: {
          std::lock_guard<std::mutex> lk(mu_);
          status = static_cast<int64_t>(data_.size());
          break;
        }
        default:
          status = -3;
      }
      uint32_t plen = static_cast<uint32_t>(payload.size());
      char hdr[12];
      memcpy(hdr, &status, 8);
      memcpy(hdr + 8, &plen, 4);
      if (!send_all(fd, hdr, 12)) break;
      if (plen && !send_all(fd, payload.data(), plen)) break;
    }
    ::close(fd);
  }

  int port_;
  int listen_fd_ = -1;
  std::atomic<bool> stopping_{false};
  std::thread accept_thread_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> data_;
  std::vector<int> conn_fds_;
  std::vector<std::thread> workers_;
};

class StoreClient {
 public:
  bool Connect(const std::string& host, int port, double timeout_s) {
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration_cast<std::chrono::nanoseconds>(
                        std::chrono::duration<double>(
                            timeout_s < 0 ? 3600.0 : timeout_s));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
      addrinfo hints{};
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      addrinfo* res = nullptr;
      if (getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 || !res) {
        set_last_error("store client: cannot resolve host %s", host.c_str());
        return false;
      }
      addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
      freeaddrinfo(res);
    }
    // Retry until the server is up (rendezvous races are normal).
    while (true) {
      fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
      if (fd_ >= 0 &&
          ::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) ==
              0) {
        int one = 1;
        setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        return true;
      }
      if (fd_ >= 0) ::close(fd_);
      fd_ = -1;
      if (std::chrono::steady_clock::now() > deadline) {
        set_last_error("store client: connect %s:%d timed out", host.c_str(),
                       port);
        return false;
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
  }

  ~StoreClient() {
    if (fd_ >= 0) ::close(fd_);
  }

  // Returns status; payload (if any) in *out.
  int64_t Request(uint8_t cmd, const std::string& key, const std::string& val,
                  std::string* out) {
    std::lock_guard<std::mutex> lk(mu_);
    uint32_t klen = static_cast<uint32_t>(key.size());
    uint32_t vlen = static_cast<uint32_t>(val.size());
    std::string msg;
    msg.reserve(9 + klen + vlen);
    msg.push_back(static_cast<char>(cmd));
    msg.append(reinterpret_cast<char*>(&klen), 4);
    msg.append(key);
    msg.append(reinterpret_cast<char*>(&vlen), 4);
    msg.append(val);
    if (!send_all(fd_, msg.data(), msg.size())) return -3;
    char hdr[12];
    if (!recv_all(fd_, hdr, 12)) return -3;
    int64_t status;
    uint32_t plen;
    memcpy(&status, hdr, 8);
    memcpy(&plen, hdr + 8, 4);
    std::string payload(plen, '\0');
    if (plen && !recv_all(fd_, &payload[0], plen)) return -3;
    if (out) *out = std::move(payload);
    return status;
  }

 private:
  std::mutex mu_;
  int fd_ = -1;
};

std::string encode_f64(double v) {
  std::string s(sizeof(double), '\0');
  memcpy(&s[0], &v, sizeof(double));
  return s;
}

}  // namespace
}  // namespace pd

extern "C" {

pd_store_server_t pd_store_server_start(int port) {
  auto* s = new pd::StoreServer(port);
  if (!s->Start()) {
    delete s;
    return nullptr;
  }
  return s;
}

int pd_store_server_port(pd_store_server_t s) {
  return static_cast<pd::StoreServer*>(s)->port();
}

void pd_store_server_stop(pd_store_server_t s) {
  auto* srv = static_cast<pd::StoreServer*>(s);
  srv->Stop();
  delete srv;
}

pd_store_client_t pd_store_client_connect(const char* host, int port,
                                          double timeout_s) {
  auto* c = new pd::StoreClient();
  if (!c->Connect(host ? host : "127.0.0.1", port, timeout_s)) {
    delete c;
    return nullptr;
  }
  return c;
}

void pd_store_client_close(pd_store_client_t c) {
  delete static_cast<pd::StoreClient*>(c);
}

int pd_store_set(pd_store_client_t c, const char* key, const uint8_t* val,
                 int len) {
  std::string v(reinterpret_cast<const char*>(val), len);
  return static_cast<int>(
      static_cast<pd::StoreClient*>(c)->Request(pd::kSet, key, v, nullptr));
}

int pd_store_get(pd_store_client_t c, const char* key, uint8_t* buf, int cap,
                 double timeout_s) {
  std::string payload;
  int64_t status = static_cast<pd::StoreClient*>(c)->Request(
      pd::kGetWait, key, pd::encode_f64(timeout_s), &payload);
  if (status < 0) return static_cast<int>(status);
  int n = static_cast<int>(payload.size());
  if (buf && cap > 0) memcpy(buf, payload.data(), n < cap ? n : cap);
  return n;
}

int64_t pd_store_add(pd_store_client_t c, const char* key, int64_t delta) {
  std::string enc(sizeof(int64_t), '\0');
  memcpy(&enc[0], &delta, sizeof(int64_t));
  std::string payload;
  int64_t status =
      static_cast<pd::StoreClient*>(c)->Request(pd::kAdd, key, enc, &payload);
  if (status < 0 || payload.size() != sizeof(int64_t)) return INT64_MIN;
  int64_t out;
  memcpy(&out, payload.data(), sizeof(int64_t));
  return out;
}

int pd_store_wait(pd_store_client_t c, const char* key, double timeout_s) {
  return static_cast<int>(static_cast<pd::StoreClient*>(c)->Request(
      pd::kWait, key, pd::encode_f64(timeout_s), nullptr));
}

int pd_store_delete(pd_store_client_t c, const char* key) {
  return static_cast<int>(
      static_cast<pd::StoreClient*>(c)->Request(pd::kDel, key, "", nullptr));
}

int pd_store_num_keys(pd_store_client_t c) {
  return static_cast<int>(static_cast<pd::StoreClient*>(c)->Request(
      pd::kNumKeys, "", "", nullptr));
}

}  // extern "C"
