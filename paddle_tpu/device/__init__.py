"""Device API (ref: python/paddle/device/).

Streams/events do not exist at the jax level on TPU — XLA orders execution by
data dependence. The stream API is kept for source compatibility as ordered
no-ops, with synchronize() mapping to blocking on all pending device work.
"""
from __future__ import annotations

import jax

from ..framework.place import (CPUPlace, CustomPlace, TPUPlace, get_device,
                               set_device)


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_custom_device(device_type: str = "tpu"):
    return any(d.platform != "cpu" for d in jax.devices())


def get_available_device():
    return [f"{'tpu' if d.platform != 'cpu' else 'cpu'}:{d.id}"
            for d in jax.devices()]


def get_available_custom_device():
    return [d for d in get_available_device() if not d.startswith("cpu")]


def device_count():
    return jax.device_count()


def synchronize(device=None):
    """Block until all dispatched device work completes."""
    (jax.device_put(0) + 0).block_until_ready()


class Stream:
    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        return None

    def wait_stream(self, stream):
        return None

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, device=None, enable_timing=False, blocking=False):
        pass

    def record(self, stream=None):
        return None

    def synchronize(self):
        synchronize()

    def query(self):
        return True


def current_stream(device=None):
    return Stream(device)


def set_stream(stream):
    return stream


def stream_guard(stream):
    import contextlib
    return contextlib.nullcontext()


class cuda:  # namespace shim: paddle.device.cuda
    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        return synchronize(device)

    @staticmethod
    def empty_cache():
        return None


def get_all_custom_device_type():
    """ref: paddle.device.get_all_custom_device_type — device types
    registered through the plugin (PJRT) mechanism."""
    kinds = []
    import jax
    for d in jax.devices():
        k = getattr(d, "platform", "")
        if k not in ("cpu", "gpu") and k not in kinds:
            kinds.append(k)
    return kinds
