"""Distributed API (ref: python/paddle/distributed/).

TPU-native stack: single-controller SPMD over a jax Mesh. See
fleet/topology.py for the axis layout and communication.py for collective
semantics.
"""
from . import fleet
from . import sharding_utils
from .communication import (Group, ReduceOp, all_gather, all_reduce,
                            all_to_all_single, alltoall, barrier, broadcast,
                            get_group, irecv, isend, new_group, ppermute,
                            recv, reduce, reduce_scatter, scatter, send)
from .env import (get_rank, get_world_size, init_parallel_env, is_initialized,
                  parallel_device_count)
from .parallel import DataParallel, spawn
from . import checkpoint
from . import rpc
from . import ps
from . import auto_parallel
from .auto_parallel.api import (shard_tensor, Shard, Replicate, Partial,
                                ProcessMesh)
