"""Distributed API (ref: python/paddle/distributed/).

TPU-native stack: single-controller SPMD over a jax Mesh. See
fleet/topology.py for the axis layout and communication.py for collective
semantics.
"""
from . import fleet
from . import launch
from . import sharding_utils
from . import communication
from .communication import stream
from .communication import (Group, P2POp, ReduceOp, all_gather, all_reduce,
                            batch_isend_irecv, gather,
                            all_gather_into_tensor, all_to_all_single,
                            alltoall, alltoall_single, barrier, broadcast,
                            destroy_process_group, get_backend,
                            monitored_barrier, reduce_scatter_tensor,
                            get_group, irecv, isend, new_group, ppermute,
                            ragged_alltoall_single, recv, reduce,
                            reduce_scatter, scatter, send)
from .communication import ragged
from .env import (get_rank, get_world_size, init_parallel_env, is_initialized,
                  parallel_device_count)
from .parallel import DataParallel, spawn
from . import checkpoint
from . import rpc
from . import ps
from . import utils
from . import auto_parallel
from .auto_parallel.api import (shard_tensor, Shard, Replicate, Partial,
                                ProcessMesh, reshard)


class ParallelEnv:
    """ref: paddle.distributed.ParallelEnv — process-level env view."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        import os
        return int(os.environ.get("PADDLE_LOCAL_RANK", "0"))

    @property
    def nranks(self):
        return get_world_size()

    @property
    def dev_id(self):
        return self.local_rank


def wait(tensor, group=None, use_calc_stream=True):
    """ref: paddle.distributed.wait — block until the tensor's pending
    work is done. Under the XLA model a device_get of one element is the
    only true barrier (see bench.py notes on block_until_ready)."""
    import jax
    data = getattr(tensor, "_data", tensor)
    jax.device_get(jax.numpy.ravel(data)[0])
    return tensor


def all_gather_object(object_list, obj, group=None):
    """ref: all_gather_object — pickle the object, gather the bytes.
    Single-controller SPMD note: in-process this is the world's view
    already; with multiple processes the coordination service would carry
    it. Implemented over the collective API's process-group when one is
    active, else the local world of size 1."""
    import pickle
    n = get_world_size(group)
    if n == 1:
        object_list.clear()
        object_list.append(pickle.loads(pickle.dumps(obj)))
        return
    raise NotImplementedError(
        "all_gather_object across processes requires the TCPStore path: "
        "use distributed.rpc or gather tensors via all_gather")


_SPLIT_LAYERS = {}


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """ref: paddle.distributed.split — run x through a megatron-parallel
    embedding or linear whose weight is split over the 'mp' mesh axis
    (the fleet parallel layers; GSPMD shards the weights and derives the
    collective). In the reference this builds ONE op in the static
    program; in eager code pass `name` so repeated calls REUSE the same
    layer (weights cached per name) — without it every call creates a
    fresh randomly-initialized layer, which is only meaningful under
    Program capture."""
    from .fleet.meta_parallel.parallel_layers.mp_layers import (
        ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)
    key = (name, tuple(size), operation, axis)
    layer = _SPLIT_LAYERS.get(key) if name is not None else None
    if layer is None:
        if operation == "embedding":
            layer = VocabParallelEmbedding(size[0], size[1])
        elif operation == "linear":
            if axis == 0:
                layer = RowParallelLinear(size[0], size[1],
                                          input_is_parallel=False)
            else:
                layer = ColumnParallelLinear(size[0], size[1],
                                             gather_output=gather_out)
        else:
            raise ValueError(f"split: unknown operation {operation!r}")
        if name is not None:
            _SPLIT_LAYERS[key] = layer
        else:
            import warnings
            warnings.warn(
                "distributed.split without `name` creates a fresh layer "
                "each call (static-graph semantics); pass name= for eager "
                "weight reuse", stacklevel=2)
    return layer(x)
