from .api import (Partial, ProcessMesh, Replicate, Shard, dtensor_from_fn,
                  get_mesh, reshard, shard_layer, shard_tensor)
from .engine import Engine, Strategy
