"""Semi-auto parallel API (ref: python/paddle/distributed/auto_parallel/api.py).

shard_tensor + placements map 1:1 onto jax NamedSharding: Shard(i) -> axis
name at dim i, Replicate -> None, Partial -> pending-psum (represented as
replicated data with a marker; XLA resolves partials inside compiled code).
ProcessMesh wraps jax.sharding.Mesh.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...tensor.tensor import Tensor


class Placement:
    pass


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)

    def __hash__(self):
        return hash("replicate")


class Partial(Placement):
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"


class ProcessMesh:
    def __init__(self, mesh, dim_names=None, shape=None, process_ids=None):
        if isinstance(mesh, Mesh):
            self._mesh = mesh
            self.dim_names = list(mesh.axis_names)
            return
        arr = np.asarray(mesh)
        self.dim_names = dim_names or [f"d{i}" for i in range(arr.ndim)]
        devs = jax.devices()
        if len(devs) < arr.size:
            devs = jax.devices("cpu")
        flat = arr.reshape(-1)
        dev_arr = np.array([devs[i] for i in flat]).reshape(arr.shape)
        self._mesh = Mesh(dev_arr, axis_names=tuple(self.dim_names))

    @property
    def mesh(self):
        return self._mesh

    @property
    def shape(self):
        return list(self._mesh.devices.shape)

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def _placements_to_spec(placements: List[Placement], ndim: int,
                        mesh: ProcessMesh):
    spec = [None] * ndim
    for axis_idx, p in enumerate(placements):
        if isinstance(p, Shard):
            spec[p.dim] = mesh.dim_names[axis_idx]
        elif isinstance(p, (Replicate, Partial)):
            continue
    return P(*spec)


def shard_tensor(data, mesh: ProcessMesh, placements, dtype=None,
                 place=None, stop_gradient=None):
    """Place a tensor on the mesh with the given placements; returns a Tensor
    whose underlying array is a sharded jax.Array (a true DistTensor)."""
    t = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    spec = _placements_to_spec(list(placements), t._data.ndim, mesh)
    sharded = jax.device_put(t._data, NamedSharding(mesh.mesh, spec))
    out = Tensor._from_data(sharded, stop_gradient=t.stop_gradient
                            if stop_gradient is None else stop_gradient)
    out.placements = list(placements)
    out.process_mesh = mesh
    return out


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(tensor, mesh: ProcessMesh, placements):
    spec = _placements_to_spec(list(placements), tensor._data.ndim, mesh)
    data = jax.device_put(tensor._data, NamedSharding(mesh.mesh, spec))
    out = Tensor._from_data(data, stop_gradient=tensor.stop_gradient)
    out.placements = list(placements)
    out.process_mesh = mesh
    return out


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None,
                output_fn=None):
    if shard_fn is not None:
        for name, sub in layer.named_sublayers(include_self=True):
            shard_fn(name, sub, process_mesh)
    return layer


def get_mesh():
    from ..fleet.topology import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    return ProcessMesh(hcg.mesh) if hcg else None
