"""Auto-parallel Engine — the user-facing semi-auto training orchestrator
(ref: python/paddle/distributed/auto_parallel/static/engine.py, used as
``from paddle.distributed.fleet import auto; auto.Engine(...)``).

The reference Engine runs completion -> partition -> reshard graph passes
plus a cost model to turn a single-card program into a distributed one. On
TPU those passes ARE the GSPMD partitioner: the user (or shard_layer rules)
annotates placements, the Engine builds one compiled SPMD train step over
the mesh, and XLA completes/partitions/reshards. What remains for the Engine
is exactly what users see: fit/evaluate/predict loops, dataloader plumbing,
metrics, and save/load."""
from __future__ import annotations

from typing import Optional

import numpy as np

from ...jit.train_step import TrainStep
from ...tensor.tensor import Tensor
from .api import ProcessMesh


class Strategy:
    """auto.Strategy (ref: auto_parallel/strategy.py) — knobs the TPU path
    honors; unknown reference fields accepted as attributes for parity."""

    def __init__(self):
        self.auto_mode = "semi"
        self.dp_degree = 1
        self.mp_degree = 1
        self.seed = None
        self.gradient_merge = _GradientMerge()
        self.recompute = _Toggle()
        self.amp = _Toggle()


class _Toggle:
    def __init__(self):
        self.enable = False


class _GradientMerge(_Toggle):
    def __init__(self):
        super().__init__()
        self.k_steps = 1  # accumulation count


class Engine:
    """engine = Engine(model, loss, optimizer, metrics, strategy)
    engine.fit(train_dataset, epochs=2, batch_size=32)
    engine.evaluate(valid_dataset); engine.predict(test_dataset)

    `mesh` (or a ProcessMesh via strategy degrees) activates SPMD: the train
    step compiles once over the mesh with the batch dp-sharded and any
    param placements (shard_tensor/shard_layer / group_sharded) honored."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None, mesh: Optional[ProcessMesh] = None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics if isinstance(metrics, (list, tuple)) else (
            [metrics] if metrics is not None else [])
        self.strategy = strategy or Strategy()
        self._mesh = mesh or self._mesh_from_strategy()
        self._train_step = None
        self.history = {"loss": []}

    def _mesh_from_strategy(self):
        dp = getattr(self.strategy, "dp_degree", 1) or 1
        mp = getattr(self.strategy, "mp_degree", 1) or 1
        if dp * mp <= 1:
            return None
        import jax
        devs = jax.devices()
        if len(devs) < dp * mp:
            devs = jax.devices("cpu")
        arr = np.array(devs[:dp * mp]).reshape(dp, mp)
        from jax.sharding import Mesh
        return ProcessMesh(Mesh(arr, ("dp", "mp")))

    # -- loops -------------------------------------------------------------

    def _grad_accum(self):
        gm = getattr(self.strategy, "gradient_merge", None)
        if gm is not None and getattr(gm, "enable", False):
            return int(getattr(gm, "k_steps", 1))
        return 1

    def _loader(self, data, batch_size, shuffle):
        from ...io import DataLoader, Dataset
        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            # a ragged final batch breaks SPMD batch sharding and
            # gradient-merge microbatch splitting: drop it when either is on
            drop = self._mesh is not None or self._grad_accum() > 1
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              drop_last=drop)
        raise TypeError(f"expected Dataset or DataLoader, got {type(data)}")

    def _ensure_train_step(self):
        if self._train_step is None:
            from jax.sharding import PartitionSpec as P
            mesh = self._mesh.mesh if self._mesh is not None else None
            bspec = None
            if mesh is not None:
                # batch shards over every data-like axis present
                axes = [a for a in ("dp", "sharding") if a in mesh.axis_names]
                bspec = P(tuple(axes)) if axes else None
            self._train_step = TrainStep(self.model, self.loss,
                                         self.optimizer, mesh=mesh,
                                         batch_spec=bspec,
                                         grad_accum=self._grad_accum())
        return self._train_step

    def _place_eval(self, t):
        """Eager eval with mesh-sharded params needs inputs on the same
        device set: replicate them over the mesh."""
        if self._mesh is None or not isinstance(t, Tensor):
            return t
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        return Tensor._from_data(
            jax.device_put(t._data, NamedSharding(self._mesh.mesh, P())),
            stop_gradient=t.stop_gradient)

    def fit(self, train_data=None, train_sample_split=None, batch_size=1,
            epochs=1, steps_per_epoch=None, log_freq=10, save_dir=None,
            save_freq=1, valid_data=None, valid_freq=1, shuffle=True,
            callbacks=None, verbose=1):
        loader = self._loader(train_data, batch_size, shuffle)
        step_fn = self._ensure_train_step()
        for epoch in range(epochs):
            self.model.train()
            for step, batch in enumerate(loader):
                if steps_per_epoch is not None and step >= steps_per_epoch:
                    break
                *xs, y = batch if isinstance(batch, (list, tuple)) else (batch,)
                loss = step_fn(*xs, labels=y)
                self.history["loss"].append(float(loss.numpy()))
                if verbose and step % log_freq == 0:
                    print(f"[auto.Engine] epoch {epoch} step {step} "
                          f"loss {float(loss.numpy()):.5f}")
            if save_dir is not None and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/epoch{epoch}")
            if valid_data is not None and (epoch + 1) % valid_freq == 0:
                self.evaluate(valid_data, batch_size=batch_size,
                              verbose=verbose)
        step_fn.sync_to_model()
        return self.history

    def evaluate(self, valid_data=None, valid_sample_split=None, batch_size=1,
                 steps=None, log_freq=10, callbacks=None, verbose=1):
        loader = self._loader(valid_data, batch_size, shuffle=False)
        self.model.eval()
        if self._train_step is not None:
            self._train_step.sync_to_model()
        from ...autograd import no_grad
        losses, n = [], 0
        for m in self.metrics:
            m.reset()
        with no_grad():
            for step, batch in enumerate(loader):
                if steps is not None and step >= steps:
                    break
                *xs, y = batch if isinstance(batch, (list, tuple)) else (batch,)
                xs = [self._place_eval(x) for x in xs]
                y = self._place_eval(y)
                out = self.model(*xs)
                if self.loss is not None:
                    losses.append(float(self.loss(out, y).numpy()))
                for m in self.metrics:
                    m.update(*_metric_args(m, out, y))
                n += 1
        result = {"eval_loss": float(np.mean(losses)) if losses else None}
        for m in self.metrics:
            result[m.name() if callable(getattr(m, "name", None)) else
                   getattr(m, "_name", "metric")] = m.accumulate()
        if verbose:
            print(f"[auto.Engine] eval: {result}")
        return result

    def predict(self, test_data=None, test_sample_split=None, batch_size=1,
                steps=None, callbacks=None, verbose=0):
        loader = self._loader(test_data, batch_size, shuffle=False)
        self.model.eval()
        if self._train_step is not None:
            self._train_step.sync_to_model()
        from ...autograd import no_grad
        outs = []
        with no_grad():
            for step, batch in enumerate(loader):
                if steps is not None and step >= steps:
                    break
                xs = batch if isinstance(batch, (list, tuple)) else (batch,)
                # sample_split: how many leading elements are model inputs
                # (default: all but a trailing label when the batch has one)
                n_in = test_sample_split or (len(xs) - 1 if len(xs) > 1
                                             else len(xs))
                out = self.model(*[self._place_eval(x) for x in xs[:n_in]])
                outs.append(out.numpy())
        return outs

    # -- persistence -------------------------------------------------------

    def save(self, path, training=True):
        from ...distributed.checkpoint import save_state_dict
        state = {"model": self.model.state_dict()}
        if training and self.optimizer is not None:
            if self._train_step is not None:
                self._train_step.sync_to_model()
            state["opt"] = self.optimizer.state_dict()
        save_state_dict(state, path)

    def load(self, path, strict=True, load_optimizer=True):
        from ...distributed.checkpoint import load_state_dict
        state = {"model": self.model.state_dict()}
        if load_optimizer and self.optimizer is not None:
            state["opt"] = self.optimizer.state_dict()
        load_state_dict(state, path)
        self.model.set_state_dict(state["model"])
        if load_optimizer and self.optimizer is not None and "opt" in state:
            self.optimizer.set_state_dict(state["opt"])
        self._train_step = None  # recompile with restored values


def _metric_args(metric, out, label):
    compute = getattr(metric, "compute", None)
    if compute is not None:
        try:
            res = compute(out, label)
            return res if isinstance(res, tuple) else (res,)
        except Exception:
            pass
    return (out, label)
