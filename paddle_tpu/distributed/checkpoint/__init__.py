"""Distributed checkpoint (ref: python/paddle/distributed/checkpoint/).

Sharded, metadata-carrying save/load with reshard-on-load, built on orbax
(TensorStore): each host writes its shards; load redistributes to the current
mesh/shardings — the TPU-native equivalent of the reference's per-rank shard
files + reshard logic.
"""
from .manager import CheckpointManager, Preempted
from .save_load import (AsyncSaveHandle, load_manifest, load_sharding_meta,
                        load_state_dict, save_state_dict,
                        wait_all_async_saves)
