"""Rolling checkpoint orchestration, preemption handling, verified resume.

Ref: SURVEY §5 (failure detection / elastic rows). ``save_load`` gives a
single crash-safe checkpoint *write*; production training needs the layer
above it:

- **rolling step dirs** — ``<root>/step_00000042`` per save, keep-N
  garbage collection of the oldest *complete* dirs (never a dir whose
  async write is still in flight);
- **completion marker + checksums** — a dir counts as a checkpoint only
  once its ``COMMIT.json`` marker is down, and the marker is written
  *after* the publish rename by the writer thread itself
  (``save_state_dict(on_complete=...)``), so a save killed at any stage
  of the write/publish protocol simply never produces a marker.
  ``manifest.json`` (written inside the tmp dir, before publish) carries
  per-leaf CRC32s; :meth:`restore` re-hashes the restored arrays against
  it and falls back to the next-older checkpoint on mismatch — bitrot or
  a torn shard write degrades to an older checkpoint instead of a
  corrupted resume;
- **save-interval pacing** — :meth:`on_step` issues async saves that
  overlap subsequent training steps (the device->host snapshot is the
  only blocking part); the next interval's save waits for the previous
  handle first, so at most one write is in flight per manager;
- **preemption** — SIGTERM (or :meth:`request_preemption`) sets a flag;
  at the next step boundary the manager finishes the in-flight async
  write (bounded by ``PADDLE_TPU_PREEMPT_GRACE`` seconds), takes one
  final *synchronous* save of the current state, dumps the flight
  recorder ring, and raises :class:`Preempted` so the driving loop
  unwinds cleanly.

The crash matrix (tests/test_checkpoint_manager.py) arms a fault at every
point in :data:`CRASH_POINTS` in turn, kills a save there, and asserts
:meth:`latest` still resolves a complete checksum-valid checkpoint whose
resumed training matches the uninterrupted loss bitwise.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import signal
import threading
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

from ... import envs
from ...testing import faults
from . import save_load as sl

__all__ = ["CheckpointManager", "Preempted", "CRASH_POINTS",
           "COMMIT_POINTS", "MARKER", "ENV_CKPT_KEEP", "ENV_CKPT_INTERVAL",
           "ENV_PREEMPT_GRACE"]

ENV_CKPT_KEEP = "PADDLE_TPU_CKPT_KEEP"
ENV_CKPT_INTERVAL = "PADDLE_TPU_CKPT_INTERVAL"
ENV_PREEMPT_GRACE = "PADDLE_TPU_PREEMPT_GRACE"

# ".json" so orbax restore surfaces it as a (popped) sidecar entry rather
# than tripping over an extensionless stray file.
MARKER = "COMMIT.json"
_STEP_RE = re.compile(r"^step_(\d{8})$")

# marker-side injection points (the write-side ones live in save_load)
COMMIT_POINTS = ("ckpt.commit.before_marker", "ckpt.commit.after_marker")
CRASH_POINTS = sl.CKPT_WRITE_POINTS + COMMIT_POINTS


class Preempted(RuntimeError):
    """Raised at a step boundary after a graceful preemption shutdown.

    ``step`` is the last completed step; ``checkpoint`` the final sync
    save's dir (None when that save itself failed — resume then falls
    back to the newest older checkpoint via ``latest()``)."""

    def __init__(self, step: int, checkpoint: Optional[str]):
        saved = checkpoint if checkpoint is not None else "no final save"
        super().__init__(f"preempted at step {step} ({saved})")
        self.step = step
        self.checkpoint = checkpoint


class CheckpointManager:
    """Rolling, preemption-aware checkpoints under one root directory."""

    def __init__(self, root: str, keep: Optional[int] = None,
                 interval: Optional[int] = None,
                 grace: Optional[float] = None):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.keep = int(keep if keep is not None
                        else envs.get(ENV_CKPT_KEEP))
        self.interval = (interval if interval is not None
                         else envs.get(ENV_CKPT_INTERVAL))
        self.grace = float(grace if grace is not None
                           else envs.get(ENV_PREEMPT_GRACE))
        self._lock = threading.Lock()
        self._inflight: Dict[str, sl.AsyncSaveHandle] = {}
        self._last_handle: Optional[sl.AsyncSaveHandle] = None
        self.save_errors: List[Tuple[str, BaseException]] = []
        self._preempt = threading.Event()
        self._signum: Optional[int] = None
        self._prev_handler: Any = None

    # -- layout ---------------------------------------------------------------

    def step_dir(self, step: int) -> str:
        return os.path.join(self.root, f"step_{int(step):08d}")

    def _complete(self, path: str) -> bool:
        """Marker down and manifest parseable — the `latest()` filter.
        (Checksum *verification* is restore-time: it needs the arrays.)"""
        marker = os.path.join(path, MARKER)
        if not os.path.isdir(path) or not os.path.isfile(marker):
            return False
        try:
            with open(marker) as f:
                json.load(f)
        except (OSError, ValueError):
            return False
        man = sl.load_manifest(path)
        return man is not None and "leaf_checksums" in man

    def steps(self) -> List[int]:
        """Complete checkpoint steps under root, ascending."""
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return []
        out = []
        for n in names:
            m = _STEP_RE.match(n)
            if m and self._complete(os.path.join(self.root, n)):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest(self) -> Optional[int]:
        """Newest complete checkpoint step (None when there is none).
        Incomplete dirs — killed saves, in-flight writes — are skipped."""
        steps = self.steps()
        return steps[-1] if steps else None

    def latest_path(self) -> Optional[str]:
        step = self.latest()
        return None if step is None else self.step_dir(step)

    # -- saving ---------------------------------------------------------------

    def _commit_marker(self, path: str, step: int) -> Callable[[], None]:
        def write_marker():
            faults.inject("ckpt.commit.before_marker", dir=path)
            tmp = os.path.join(path, MARKER + ".tmp")
            with open(tmp, "w") as f:
                json.dump({"step": int(step)}, f)
            os.replace(tmp, os.path.join(path, MARKER))
            faults.inject("ckpt.commit.after_marker", dir=path)
            try:
                # the dir just became complete — roll the window now, from
                # the writer thread, so retention never waits for the next
                # save() call
                self.gc()
            except Exception:
                pass  # GC failure must not poison a successful save
        return write_marker

    def _reap(self, h: Optional[sl.AsyncSaveHandle], path: str) -> None:
        """Collect a finished handle's error (a failed rolling save is
        survivable by design — warn, record, keep training)."""
        if h is None:
            return
        try:
            h.wait()
        except BaseException as e:
            self.save_errors.append((path, e))
            warnings.warn(
                f"async checkpoint save to {path!r} failed "
                f"({type(e).__name__}: {e}); continuing — latest() still "
                "resolves the newest complete checkpoint", RuntimeWarning)

    def save(self, state: Dict[str, Any], step: int,
             block: bool = False) -> sl.AsyncSaveHandle:
        """Snapshot `state` now and write ``step_<step>`` asynchronously
        (synchronously with block=True). Paces itself: waits out this
        manager's previous in-flight save first, so saves overlap training
        steps but never each other."""
        path = self.step_dir(step)
        with self._lock:
            prev = self._last_handle
            prev_path = next((p for p, h in self._inflight.items()
                              if h is prev), "")
        if prev is not None and not prev.done():
            self._reap(prev, prev_path)
        if os.path.isdir(path):
            # re-saving a step (e.g. resumed run re-reaches it): replace
            shutil.rmtree(path)
        h = sl.save_state_dict(state, path, async_save=True,
                               manifest={"step": int(step)},
                               on_complete=self._commit_marker(path, step))
        with self._lock:
            self._inflight[path] = h
            self._last_handle = h
        if block:
            try:
                h.wait()
            finally:
                with self._lock:
                    self._inflight.pop(path, None)
        self.gc()
        return h

    def wait(self, timeout: Optional[float] = None) -> List[Tuple[str, BaseException]]:
        """Drain every in-flight save this manager started. Returns the
        (path, error) list of failed saves instead of raising — a dead
        rolling save is the crash matrix's normal case, not a resume
        blocker. TimeoutError (still-running write past `timeout`) does
        propagate: the caller owns the grace budget."""
        with self._lock:
            items = list(self._inflight.items())
        errs = []
        for path, h in items:
            try:
                h.wait(timeout)
            except TimeoutError:
                raise
            except BaseException as e:
                errs.append((path, e))
            with self._lock:
                self._inflight.pop(path, None)
        self.save_errors.extend(errs)
        self.gc()
        return errs

    def gc(self) -> List[str]:
        """Delete the oldest complete checkpoints beyond keep-N. A dir
        whose write is still in flight in this manager is never touched
        (handle check), and incomplete dirs are left alone entirely —
        ``_write_checkpoint`` reclaims its own path's residue on the next
        save, and a second manager may be mid-write in one of them."""
        steps = self.steps()
        removed = []
        excess = steps[:-self.keep] if self.keep > 0 else steps
        for st in excess:
            path = self.step_dir(st)
            with self._lock:
                h = self._inflight.get(path)
            if h is not None and not h.done():
                continue
            shutil.rmtree(path, ignore_errors=True)
            removed.append(path)
        return removed

    # -- restore --------------------------------------------------------------

    def verify_step(self, step: int) -> bool:
        """Re-hash the checkpoint's arrays against its manifest CRCs."""
        path = self.step_dir(step)
        man = sl.load_manifest(path)
        if man is None:
            return False
        import orbax.checkpoint as ocp
        try:
            restored = ocp.PyTreeCheckpointer().restore(path)
        except Exception:
            return False
        if isinstance(restored, dict):
            for sidecar in ("sharding_meta.json", "manifest.json", MARKER):
                restored.pop(sidecar, None)
        return sl.leaf_checksums(restored) == list(
            man.get("leaf_checksums", []))

    def restore(self, state: Dict[str, Any], step: Optional[int] = None,
                verify: bool = True) -> int:
        """Fill `state` (Tensor or raw-jax.Array leaves, resharded onto
        each leaf's current sharding — the elastic-resume path) from
        `step`, or from the newest checkpoint that is complete AND
        checksum-valid, falling back older on corruption. Returns the
        restored step."""
        candidates = [int(step)] if step is not None else self.steps()
        tried = []
        for st in reversed(candidates):
            path = self.step_dir(st)
            if not self._complete(path):
                tried.append((st, "incomplete"))
                continue
            if verify and not self.verify_step(st):
                tried.append((st, "checksum mismatch"))
                warnings.warn(
                    f"checkpoint {path!r} failed checksum verification; "
                    "falling back to an older checkpoint", RuntimeWarning)
                continue
            sl.load_state_dict(state, path)
            return st
        detail = ", ".join(f"step {s}: {why}" for s, why in tried) or "empty"
        raise FileNotFoundError(
            f"no complete checksum-valid checkpoint under {self.root!r} "
            f"({detail})")

    # -- preemption -----------------------------------------------------------

    def install_preemption_handler(self, signum: int = signal.SIGTERM) -> None:
        """SIGTERM -> set the preemption flag; the actual shutdown happens
        at the next step boundary (signal handlers must not run device
        code). Keeps the previous handler for uninstall."""
        try:
            self._prev_handler = signal.signal(signum, self._on_signal)
            self._signum = signum
        except ValueError:
            # not the main thread: signals can't be hooked here — callers
            # still preempt via request_preemption()
            warnings.warn(
                "cannot install a signal handler off the main thread; "
                "use request_preemption()", RuntimeWarning)

    def uninstall_preemption_handler(self) -> None:
        if self._signum is not None:
            signal.signal(self._signum, self._prev_handler or signal.SIG_DFL)
            self._signum = None
            self._prev_handler = None

    def _on_signal(self, signum, frame) -> None:
        self._preempt.set()

    def request_preemption(self) -> None:
        """Programmatic preemption (tests, cluster agents without signals)."""
        self._preempt.set()

    @property
    def preempted(self) -> bool:
        return self._preempt.is_set()

    def on_step(self, step: int, state_fn: Callable[[], Dict[str, Any]],
                recorder=None) -> Optional[sl.AsyncSaveHandle]:
        """Per-step hook for TrainStep/driving loops: handles a pending
        preemption (raises :class:`Preempted`), else issues the interval-
        paced async save. `state_fn` is called only when a save actually
        happens."""
        if self._preempt.is_set():
            self._finalize_preemption(step, state_fn, recorder)
        if self.interval and step % self.interval == 0:
            return self.save(state_fn(), step)
        return None

    def _finalize_preemption(self, step: int, state_fn, recorder) -> None:
        # 1) let the in-flight async write land (bounded by the grace
        #    budget — a hung write must not eat the whole grace period)
        try:
            self.wait(timeout=self.grace)
        except TimeoutError:
            warnings.warn(
                f"in-flight checkpoint write still running after "
                f"{self.grace}s grace; abandoning it (its dir has no "
                "marker and will be skipped by latest())", RuntimeWarning)
        # 2) one final synchronous save of the current state
        final: Optional[str] = self.step_dir(step)
        try:
            self.save(state_fn(), step, block=True)
        except BaseException as e:
            final = None
            self.save_errors.append((self.step_dir(step), e))
            warnings.warn(
                f"final preemption save failed ({type(e).__name__}: {e}); "
                "resume will use the newest older checkpoint",
                RuntimeWarning)
        # 3) post-mortem ring (PR 12): one dump per preemption
        if recorder is not None:
            recorder.dump("preemption")
        raise Preempted(step, final)
