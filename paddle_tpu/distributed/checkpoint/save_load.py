"""Sharded checkpoint save/load with async (TensorStore-style) writes.

Ref: SURVEY §5.4 — the reference's distributed checkpoint saves per-rank
shards with metadata; the TPU equivalent is an async, sharded array
checkpoint keyed by mesh/sharding metadata. Here:

- save_state_dict(async_save=True) snapshots device arrays to host (the
  only part that must block the training loop) and hands the actual write
  to a background thread, returning an AsyncSaveHandle. Step time hides the
  file I/O entirely; callers (or the next save) wait on the handle.
- every leaf's sharding metadata (mesh axis names/shape + PartitionSpec)
  is written alongside the arrays, so a load onto a DIFFERENT topology can
  verify compatibility and reshard (load re-shards onto each target
  tensor's current layout — single-controller, the host sees every shard).
"""
from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional

import jax
import numpy as np

from ...tensor.tensor import Tensor


def _leaf_sharding_meta(v):
    """JSON-able sharding metadata for a jax.Array leaf (None for host)."""
    data = v._data if isinstance(v, Tensor) else v
    sh = getattr(data, "sharding", None)
    if sh is None or not hasattr(sh, "spec"):
        return None
    try:
        mesh = sh.mesh
        return {
            "mesh_axes": list(mesh.axis_names),
            "mesh_shape": [int(s) for s in mesh.devices.shape],
            "spec": [list(p) if isinstance(p, (tuple, list)) else p
                     for p in sh.spec],
        }
    except Exception:
        return None


def _to_arrays(state_dict):
    # host-gathered leaves: orbax then restores without needing concrete
    # shardings, and load_state_dict re-shards onto each target tensor's
    # layout (single-controller: the host sees every shard anyway). Nested
    # pytrees (optimizer states etc.) pass through with Tensor/array leaves
    # converted in place.
    return jax.tree_util.tree_map(
        lambda v: np.asarray(v._data if isinstance(v, Tensor) else v),
        state_dict, is_leaf=lambda v: isinstance(v, Tensor))


class _MetaLeaf:
    """Opaque wrapper: not a registered pytree node, so tree flattening
    treats each per-leaf meta dict (or None) as a single leaf instead of
    shredding the dict into scalars."""
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v


def _sharding_tree(state_dict):
    return jax.tree_util.tree_map(
        lambda v: _MetaLeaf(_leaf_sharding_meta(v)), state_dict,
        is_leaf=lambda v: isinstance(v, Tensor))


class AsyncSaveHandle:
    """Future-like handle for a background checkpoint write."""

    def __init__(self, thread: threading.Thread):
        self._thread = thread
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return not self._thread.is_alive()

    def wait(self, timeout: Optional[float] = None):
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("checkpoint write still in progress")
        if self._error is not None:
            raise self._error


_pending_lock = threading.Lock()
_pending: Dict[str, AsyncSaveHandle] = {}


def wait_all_async_saves():
    """Block until every in-flight async checkpoint write has finished."""
    with _pending_lock:
        handles = list(_pending.values())
    for h in handles:
        h.wait()


def _write_checkpoint(path: str, arrays, meta):
    import shutil

    import orbax.checkpoint as ocp
    tmp, old = path + ".tmp", path + ".old"
    for leftover in (tmp, old):  # residue of an earlier crashed save
        if os.path.exists(leftover):
            shutil.rmtree(leftover)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(tmp, arrays, force=True)
    with open(os.path.join(tmp, "sharding_meta.json"), "w") as f:
        json.dump(meta, f)
    # crash-safe publish: the previous complete checkpoint is moved aside
    # (rename, not delete) before the new one is renamed in, so a kill at
    # any instant leaves either `path` or `path + ".old"` complete —
    # load_state_dict falls back to ".old" if `path` is missing.
    if os.path.exists(path):
        os.replace(path, old)
    os.replace(tmp, path)
    if os.path.exists(old):
        shutil.rmtree(old)


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    async_save: bool = False):
    """Save `state_dict` to `path`. With async_save=True the device->host
    snapshot happens now (cheap) and the write runs in a background thread;
    returns an AsyncSaveHandle. A second save to the same path waits for
    the first (ordering is preserved per-path)."""
    arrays = _to_arrays(state_dict)  # snapshot: values at call time
    # per-leaf meta, aligned with the flatten order of `arrays`' leaves
    # (same structure, every leaf mapped — None kept for unsharded leaves)
    flat = [m.v for m in jax.tree_util.tree_leaves(_sharding_tree(state_dict))]
    meta = {"leaf_shardings": flat}
    path = os.path.abspath(path)

    # a save (sync or async) to a path with an in-flight write must wait:
    # both would otherwise race on the same tmp dir and publish rename.
    # Every save (sync too) registers a handle, and the free slot is
    # RESERVED under the same lock hold that found it free — a bare
    # check-then-register would let two concurrent saves both pass.
    handle_box = {}

    def run():
        try:
            _write_checkpoint(path, arrays, meta)
        except BaseException as e:  # surfaced on wait()
            handle_box["h"]._error = e
        finally:
            with _pending_lock:
                _pending.pop(path, None)

    thread = threading.Thread(target=run, name=f"ckpt-save:{path}",
                              daemon=True)
    handle = AsyncSaveHandle(thread)
    handle_box["h"] = handle
    while True:
        with _pending_lock:
            prev = _pending.get(path)
            if prev is None:
                # register AND start under one lock hold: a registered
                # handle must be joinable (started) before any concurrent
                # saver can observe it and wait() on it
                _pending[path] = handle
                thread.start()
                break
        try:
            prev.wait()
        except Exception:
            # the previous save's owner already receives its failure via
            # that save's own handle; a poisoned predecessor must not
            # abort THIS save (ADVICE r3) — its thread has exited, so the
            # registration slot is free and we proceed
            pass
        with _pending_lock:
            # normally run()'s finally pops the entry before the thread
            # exits; drop a dead handle that is somehow still registered
            # so this loop cannot spin on it
            if _pending.get(path) is prev and prev.done():
                _pending.pop(path, None)
    if not async_save:
        handle.wait()
        return None
    return handle


def load_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0):
    """Fills `state_dict`'s tensors in place, resharding saved arrays onto
    each tensor's current sharding. Waits for any in-flight async save to
    `path` first."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    with _pending_lock:
        prev = _pending.get(path)
    if prev is not None:
        prev.wait()
    if not os.path.exists(path) and os.path.isdir(path + ".old"):
        # a save crashed between moving the old checkpoint aside and
        # publishing the new one: the ".old" copy is the newest complete one
        path = path + ".old"
    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(path)
    if isinstance(restored, dict):
        restored.pop("sharding_meta.json", None)

    def fill(target, saved):
        """Recursively fill Tensor leaves in place; returns the new value for
        non-Tensor leaves so nested optimizer-state dicts restore too."""
        if isinstance(target, Tensor):
            data = jax.numpy.asarray(np.asarray(saved), dtype=target._data.dtype)
            try:
                data = jax.device_put(data, target._data.sharding)
            except Exception:
                pass
            target._data = data
            return target
        if isinstance(target, dict) and isinstance(saved, dict):
            for k in target:
                if k in saved:
                    target[k] = fill(target[k], saved[k])
            for k in saved:
                # structure the target hasn't materialized yet (e.g. an
                # optimizer's lazily-created moment dicts before step 1)
                # is adopted wholesale
                if k not in target:
                    target[k] = _adopt(saved[k])
            return target
        if isinstance(target, (list, tuple)) and isinstance(saved, (list, tuple)):
            if len(target) != len(saved):
                raise ValueError(
                    f"checkpoint sequence length mismatch: target has "
                    f"{len(target)} entries, saved has {len(saved)}")
            out = [fill(t, s) for t, s in zip(target, saved)]
            if hasattr(target, "_fields"):
                # namedtuples take positional fields, not an iterable
                return type(target)(*out)
            return type(target)(out)
        return saved

    fill(state_dict, restored)
    return state_dict


def _adopt(saved):
    """Convert restored host values to Tensor-leaved structures."""
    if isinstance(saved, dict):
        return {k: _adopt(v) for k, v in saved.items()}
    if isinstance(saved, (list, tuple)):
        return type(saved)(_adopt(v) for v in saved)
    if isinstance(saved, np.ndarray):
        return Tensor._from_data(jax.numpy.asarray(saved))
    return saved


def load_sharding_meta(path: str):
    """The per-leaf sharding metadata recorded at save time (or None).
    Entries align with the save-time tree_leaves order of the state dict."""
    path = os.path.abspath(path)
    if not os.path.exists(path) and os.path.isdir(path + ".old"):
        path = path + ".old"
    p = os.path.join(path, "sharding_meta.json")
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)
