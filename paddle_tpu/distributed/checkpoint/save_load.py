"""Sharded async checkpoint via orbax/TensorStore."""
from __future__ import annotations

import os
from typing import Any, Dict

import jax
import numpy as np

from ...tensor.tensor import Tensor


def _to_arrays(state_dict):
    # host-gathered leaves: orbax then restores without needing concrete
    # shardings, and load_state_dict re-shards onto each target tensor's
    # layout (single-controller: the host sees every shard anyway). Nested
    # pytrees (optimizer states etc.) pass through with Tensor/array leaves
    # converted in place.
    return jax.tree_util.tree_map(
        lambda v: np.asarray(v._data if isinstance(v, Tensor) else v),
        state_dict, is_leaf=lambda v: isinstance(v, Tensor))


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    async_save: bool = False):
    """Single-controller save: arrays are host-gathered and written once;
    load_state_dict reshards onto the target tensors' (possibly different)
    mesh layout. Multi-host owner-writes-its-shard saving would pass the
    jax.Arrays straight to orbax with per-leaf shardings instead — not
    needed in this single-controller deployment."""
    import orbax.checkpoint as ocp
    arrays = _to_arrays(state_dict)
    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, arrays, force=True)


def load_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0):
    """Fills `state_dict`'s tensors in place, resharding saved arrays onto
    each tensor's current sharding."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(path)

    def fill(target, saved):
        """Recursively fill Tensor leaves in place; returns the new value for
        non-Tensor leaves so nested optimizer-state dicts restore too."""
        if isinstance(target, Tensor):
            data = jax.numpy.asarray(np.asarray(saved), dtype=target._data.dtype)
            try:
                data = jax.device_put(data, target._data.sharding)
            except Exception:
                pass
            target._data = data
            return target
        if isinstance(target, dict) and isinstance(saved, dict):
            for k in target:
                if k in saved:
                    target[k] = fill(target[k], saved[k])
            return target
        if isinstance(target, (list, tuple)) and isinstance(saved, (list, tuple)):
            if len(target) != len(saved):
                raise ValueError(
                    f"checkpoint sequence length mismatch: target has "
                    f"{len(target)} entries, saved has {len(saved)}")
            out = [fill(t, s) for t, s in zip(target, saved)]
            if hasattr(target, "_fields"):
                # namedtuples take positional fields, not an iterable
                return type(target)(*out)
            return type(target)(out)
        return saved

    fill(state_dict, restored)
    return state_dict
