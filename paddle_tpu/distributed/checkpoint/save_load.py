"""Sharded checkpoint save/load with async (TensorStore-style) writes.

Ref: SURVEY §5.4 — the reference's distributed checkpoint saves per-rank
shards with metadata; the TPU equivalent is an async, sharded array
checkpoint keyed by mesh/sharding metadata. Here:

- save_state_dict(async_save=True) snapshots device arrays to host (the
  only part that must block the training loop) and hands the actual write
  to a background thread, returning an AsyncSaveHandle. Step time hides the
  file I/O entirely; callers (or the next save) wait on the handle.
- every leaf's sharding metadata (mesh axis names/shape + PartitionSpec)
  is written alongside the arrays, so a load onto a DIFFERENT topology can
  verify compatibility and reshard (load re-shards onto each target
  tensor's current layout — single-controller, the host sees every shard).
"""
from __future__ import annotations

import json
import os
import threading
import warnings
import zlib
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from ...tensor.tensor import Tensor
from ...testing import faults

# The named stages of the write/publish protocol, in order. The crash
# matrix (tests/test_checkpoint_manager.py) kills a save at every one of
# these and asserts CheckpointManager.latest() still resolves a complete
# checkpoint. "after_publish" is past the commit rename: a crash there
# loses nothing but the manager's COMMIT marker (see manager.COMMIT_POINTS
# for the marker-side points).
CKPT_WRITE_POINTS = (
    "ckpt.write.begin",          # before leftover cleanup / any I/O
    "ckpt.write.after_arrays",   # array shards written into the tmp dir
    "ckpt.write.after_meta",     # sharding_meta.json written
    "ckpt.write.after_manifest", # manifest.json (checksums) written
    "ckpt.write.before_publish", # one instant before the commit rename
    "ckpt.write.after_publish",  # tmp renamed to its final name
)


def _leaf_sharding_meta(v):
    """JSON-able sharding metadata for a jax.Array leaf (None for host)."""
    data = v._data if isinstance(v, Tensor) else v
    sh = getattr(data, "sharding", None)
    if sh is None or not hasattr(sh, "spec"):
        return None
    try:
        mesh = sh.mesh
        return {
            "mesh_axes": list(mesh.axis_names),
            "mesh_shape": [int(s) for s in mesh.devices.shape],
            "spec": [list(p) if isinstance(p, (tuple, list)) else p
                     for p in sh.spec],
        }
    except Exception:
        return None


def _to_arrays(state_dict):
    # host-gathered leaves: orbax then restores without needing concrete
    # shardings, and load_state_dict re-shards onto each target tensor's
    # layout (single-controller: the host sees every shard anyway). Nested
    # pytrees (optimizer states etc.) pass through with Tensor/array leaves
    # converted in place. copy=True is load-bearing: np.asarray of a CPU
    # jax.Array can alias the XLA buffer, and a donating jitted step reuses
    # that buffer — an aliased "snapshot" mutates under the async writer
    return jax.tree_util.tree_map(
        lambda v: np.array(v._data if isinstance(v, Tensor) else v,
                           copy=True),
        state_dict, is_leaf=lambda v: isinstance(v, Tensor))


class _MetaLeaf:
    """Opaque wrapper: not a registered pytree node, so tree flattening
    treats each per-leaf meta dict (or None) as a single leaf instead of
    shredding the dict into scalars."""
    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v


def _sharding_tree(state_dict):
    return jax.tree_util.tree_map(
        lambda v: _MetaLeaf(_leaf_sharding_meta(v)), state_dict,
        is_leaf=lambda v: isinstance(v, Tensor))


def leaf_checksums(arrays) -> list:
    """Per-leaf CRC32s over the host snapshot, in tree_leaves order.
    Each entry folds shape+dtype into the checksum so a truncated or
    re-typed shard can't collide with its original."""
    out = []
    for leaf in jax.tree_util.tree_leaves(arrays):
        a = np.asarray(leaf)
        crc = zlib.crc32(repr((a.shape, str(a.dtype))).encode())
        crc = zlib.crc32(np.ascontiguousarray(a).tobytes(), crc)
        out.append(int(crc))
    return out


class AsyncSaveHandle:
    """Future-like handle for a background checkpoint write."""

    def __init__(self, thread: threading.Thread):
        self._thread = thread
        self._error: Optional[BaseException] = None

    def started(self) -> bool:
        return self._thread.ident is not None

    def done(self) -> bool:
        # an unstarted thread is not alive, but its write hasn't happened
        # either — "done" must mean "the write finished", or a manager
        # would GC/commit over a save that never ran
        return self._thread.ident is not None and not self._thread.is_alive()

    def wait(self, timeout: Optional[float] = None):
        if self._thread.ident is None:
            raise RuntimeError(
                "checkpoint write thread was never started; the save that "
                "created this handle failed before launching its writer")
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("checkpoint write still in progress")
        if self._error is not None:
            raise self._error


_pending_lock = threading.Lock()
_pending: Dict[str, AsyncSaveHandle] = {}


def wait_all_async_saves():
    """Block until every in-flight async checkpoint write has finished."""
    with _pending_lock:
        handles = list(_pending.values())
    for h in handles:
        h.wait()


def _write_checkpoint(path: str, arrays, meta, manifest=None):
    import shutil

    import orbax.checkpoint as ocp
    tmp, old = path + ".tmp", path + ".old"
    faults.inject("ckpt.write.begin", dir=path)
    for leftover in (tmp, old):  # residue of an earlier crashed save
        if os.path.exists(leftover):
            shutil.rmtree(leftover)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(tmp, arrays, force=True)
    faults.inject("ckpt.write.after_arrays", dir=tmp)
    with open(os.path.join(tmp, "sharding_meta.json"), "w") as f:
        json.dump(meta, f)
    faults.inject("ckpt.write.after_meta", dir=tmp)
    if manifest is not None:
        manifest = dict(manifest)
        sums = leaf_checksums(arrays)
        manifest["leaf_checksums"] = sums
        manifest["n_leaves"] = len(sums)
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        faults.inject("ckpt.write.after_manifest", dir=tmp)
    # crash-safe publish: the previous complete checkpoint is moved aside
    # (rename, not delete) before the new one is renamed in, so a kill at
    # any instant leaves either `path` or `path + ".old"` complete —
    # load_state_dict falls back to ".old" if `path` is missing.
    faults.inject("ckpt.write.before_publish", dir=tmp)
    if os.path.exists(path):
        os.replace(path, old)
    os.replace(tmp, path)
    faults.inject("ckpt.write.after_publish", dir=path)
    if os.path.exists(old):
        shutil.rmtree(old)


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    async_save: bool = False,
                    manifest: Optional[Dict[str, Any]] = None,
                    on_complete: Optional[Callable[[], None]] = None):
    """Save `state_dict` to `path`. With async_save=True the device->host
    snapshot happens now (cheap) and the write runs in a background thread;
    returns an AsyncSaveHandle. A second save to the same path waits for
    the first (ordering is preserved per-path).

    `manifest` (extra fields, e.g. the step number) opts into writing a
    ``manifest.json`` with per-leaf checksums inside the checkpoint before
    publish. `on_complete` runs in the writer thread after a successful
    publish and before the handle resolves — CheckpointManager writes its
    COMMIT marker there, so "handle done without error" implies "marker
    down". An on_complete failure surfaces on wait() like a write failure.
    """
    arrays = _to_arrays(state_dict)  # snapshot: values at call time
    # per-leaf meta, aligned with the flatten order of `arrays`' leaves
    # (same structure, every leaf mapped — None kept for unsharded leaves)
    flat = [m.v for m in jax.tree_util.tree_leaves(_sharding_tree(state_dict))]
    meta = {"leaf_shardings": flat}
    path = os.path.abspath(path)

    # a save (sync or async) to a path with an in-flight write must wait:
    # both would otherwise race on the same tmp dir and publish rename.
    # Every save (sync too) registers a handle, and the free slot is
    # RESERVED under the same lock hold that found it free — a bare
    # check-then-register would let two concurrent saves both pass.
    handle_box = {}

    def run():
        try:
            _write_checkpoint(path, arrays, meta, manifest=manifest)
            if on_complete is not None:
                on_complete()
        except BaseException as e:  # surfaced on wait()
            handle_box["h"]._error = e
        finally:
            with _pending_lock:
                _pending.pop(path, None)

    thread = threading.Thread(target=run, name=f"ckpt-save:{path}",
                              daemon=True)
    handle = AsyncSaveHandle(thread)
    handle_box["h"] = handle
    while True:
        with _pending_lock:
            prev = _pending.get(path)
            if prev is None:
                # register AND start under one lock hold: a registered
                # handle must be joinable (started) before any concurrent
                # saver can observe it and wait() on it
                _pending[path] = handle
                thread.start()
                break
        try:
            prev.wait()
        except Exception:
            # the previous save's owner already receives its failure via
            # that save's own handle; a poisoned predecessor must not
            # abort THIS save (ADVICE r3) — its thread has exited, so the
            # registration slot is free and we proceed
            pass
        with _pending_lock:
            # normally run()'s finally pops the entry before the thread
            # exits; drop a dead handle that is somehow still registered
            # so this loop cannot spin on it
            if _pending.get(path) is prev and prev.done():
                _pending.pop(path, None)
    if not async_save:
        handle.wait()
        return None
    return handle


def load_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0):
    """Fills `state_dict`'s tensors in place, resharding saved arrays onto
    each tensor's current sharding. Waits for any in-flight async save to
    `path` first."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    with _pending_lock:
        prev = _pending.get(path)
    if prev is not None:
        prev.wait()
    if not os.path.exists(path) and os.path.isdir(path + ".old"):
        # a save crashed between moving the old checkpoint aside and
        # publishing the new one: the ".old" copy is the newest complete one
        path = path + ".old"
    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(path)
    if isinstance(restored, dict):
        # json sidecars written inside the checkpoint dir come back as
        # tree entries; they are not state
        for sidecar in ("sharding_meta.json", "manifest.json",
                        "COMMIT.json"):
            restored.pop(sidecar, None)

    def reshard(data, sharding, leaf_path):
        try:
            return jax.device_put(data, sharding)
        except Exception as e:
            # a failed device_put leaves the leaf host-resident/replicated:
            # correct values, silently slow (every step re-shards it). Warn
            # once per leaf so an elastic resume onto an incompatible
            # sharding is diagnosable.
            if leaf_path not in _reshard_warned:
                _reshard_warned.add(leaf_path)
                warnings.warn(
                    f"checkpoint leaf {leaf_path!r}: device_put onto "
                    f"{sharding} failed ({type(e).__name__}: {e}); keeping "
                    "the host copy un-resharded", RuntimeWarning)
            return data

    def fill(target, saved, leaf_path=""):
        """Recursively fill Tensor leaves in place; returns the new value for
        non-Tensor leaves so nested optimizer-state dicts restore too. Raw
        jax.Array leaves (TrainStep state dicts, functional train states)
        are replaced by the saved values resharded onto the leaf's current
        sharding — the elastic-resume path for non-Tensor trees."""
        if isinstance(target, Tensor):
            data = _from_host(saved, target._data.dtype)
            target._data = reshard(data, target._data.sharding, leaf_path)
            return target
        if isinstance(target, jax.Array):
            data = _from_host(saved, target.dtype)
            if not getattr(target, "_committed", True):
                # an UNCOMMITTED target (e.g. a functional optimizer's
                # scalar step counter, never device_put by its builder)
                # must stay uncommitted: committing it to the default
                # device makes jit refuse to co-place it with mesh-
                # sharded params on elastic resume
                return data
            return reshard(data, target.sharding, leaf_path)
        if isinstance(target, dict) and isinstance(saved, dict):
            for k in target:
                if k in saved:
                    target[k] = fill(target[k], saved[k],
                                     f"{leaf_path}.{k}" if leaf_path else str(k))
            for k in saved:
                # structure the target hasn't materialized yet (e.g. an
                # optimizer's lazily-created moment dicts before step 1)
                # is adopted wholesale
                if k not in target:
                    target[k] = _adopt(saved[k])
            return target
        if isinstance(target, (list, tuple)) and isinstance(saved, (list, tuple)):
            if len(target) != len(saved):
                raise ValueError(
                    f"checkpoint sequence length mismatch: target has "
                    f"{len(target)} entries, saved has {len(saved)}")
            out = [fill(t, s, f"{leaf_path}[{i}]")
                   for i, (t, s) in enumerate(zip(target, saved))]
            if hasattr(target, "_fields"):
                # namedtuples take positional fields, not an iterable
                return type(target)(*out)
            return type(target)(out)
        return saved

    fill(state_dict, restored)
    return state_dict


# leaf paths already warned about (once per process, not per load: an
# elastic resume loads the same tree repeatedly in retry loops)
_reshard_warned: set = set()


def _from_host(saved, dtype=None):
    """Host (orbax-restored) value -> device array that OWNS its buffer.
    jnp.array, NOT jnp.asarray: asarray of a 64-byte-aligned numpy array
    (orbax buffers, by allocation luck) is ZERO-COPY — jax borrows the
    numpy buffer, and a donating train step then writes into / frees
    memory jax doesn't own (flaky nan losses and heap corruption)."""
    return jax.numpy.array(np.asarray(saved), dtype=dtype)


def _adopt(saved):
    """Convert restored host values to Tensor-leaved structures."""
    if isinstance(saved, dict):
        return {k: _adopt(v) for k, v in saved.items()}
    if isinstance(saved, (list, tuple)):
        return type(saved)(_adopt(v) for v in saved)
    if isinstance(saved, np.ndarray):
        return Tensor._from_data(_from_host(saved))
    return saved


def load_manifest(path: str):
    """The checksum manifest written at save time (None when absent or
    unparseable — an unparseable manifest marks the checkpoint incomplete,
    it is never an error here)."""
    p = os.path.join(os.path.abspath(path), "manifest.json")
    if not os.path.exists(p):
        return None
    try:
        with open(p) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def load_sharding_meta(path: str):
    """The per-leaf sharding metadata recorded at save time (or None).
    Entries align with the save-time tree_leaves order of the state dict."""
    path = os.path.abspath(path)
    if not os.path.exists(path) and os.path.isdir(path + ".old"):
        path = path + ".old"
    p = os.path.join(path, "sharding_meta.json")
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)
