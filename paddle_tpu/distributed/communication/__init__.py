"""Collective communication API (ref: python/paddle/distributed/communication/).

TPU-native semantics: collectives are XLA HLO ops over named mesh axes. Inside
a compiled SPMD region (shard_map over the fleet mesh) each call lowers to
psum/all_gather/ppermute/all_to_all on ICI. Outside any compiled region a
collective over a size-1 group (or no group) is the identity, matching the
reference's single-rank behavior — there is no NCCL-style eager multi-process
collective because a single controller owns all devices.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ...tensor.tensor import Tensor, _run_op


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communication group = one named axis of the device mesh."""

    def __init__(self, axis_name: str, nranks: int, rank: int = 0, ranks=None):
        self.axis_name = axis_name
        self.nranks = nranks
        self.rank = rank
        self.ranks = ranks if ranks is not None else list(range(nranks))

    @property
    def world_size(self):
        return self.nranks

    def __repr__(self):
        return f"Group(axis={self.axis_name}, nranks={self.nranks})"


_default_group: Optional[Group] = None


def _axis_bound(axis_name) -> bool:
    try:
        lax.axis_index(axis_name)
        return True
    except (NameError, KeyError, Exception):
        return False


def _in_trace(x) -> bool:
    return hasattr(x, "aval") and not isinstance(x, jax.Array) or \
        (isinstance(x, jax.core.Tracer) if hasattr(jax.core, "Tracer") else False)


def new_group(ranks=None, backend=None, timeout=None):
    n = len(ranks) if ranks else 1
    return Group(axis_name=f"group_{id(ranks)}", nranks=n, ranks=ranks)


def get_group(gid=0):
    return _default_group


def _reduce_traced(data, op, axis):
    if op in (ReduceOp.SUM, "sum"):
        return lax.psum(data, axis)
    if op in (ReduceOp.MAX, "max"):
        return lax.pmax(data, axis)
    if op in (ReduceOp.MIN, "min"):
        return lax.pmin(data, axis)
    if op in (ReduceOp.AVG, "avg"):
        return lax.pmean(data, axis)
    if op in (ReduceOp.PROD, "prod"):
        return lax.psum(jnp.log(data), axis)  # pragma: no cover
    raise ValueError(f"unsupported reduce op {op}")


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = group.axis_name if group is not None else None
    if axis is not None and _axis_bound(axis):
        return _run_op("all_reduce", lambda a: _reduce_traced(a, op, axis),
                       (tensor,), {})
    # no bound axis: identity over a trivial group
    return tensor


def all_gather(tensor_list, tensor=None, group=None, sync_op=True, axis=0):
    """Two call forms like the reference: all_gather(list, t) fills the list;
    functional form all_gather(t, group=g) returns the gathered tensor."""
    if isinstance(tensor_list, Tensor) and tensor is None:
        t = tensor_list
        ax = group.axis_name if group is not None else None
        if ax is not None and _axis_bound(ax):
            return _run_op("all_gather",
                           lambda a: lax.all_gather(a, ax, axis=axis, tiled=True),
                           (t,), {})
        return t
    n = group.nranks if group is not None else 1
    ax = group.axis_name if group is not None else None
    if ax is not None and _axis_bound(ax):
        g = _run_op("all_gather",
                    lambda a: lax.all_gather(a, ax, axis=0), (tensor,), {})
        for i in range(n):
            tensor_list.append(g[i])
    else:
        for _ in range(max(n, 1)):
            tensor_list.append(tensor)
    return tensor_list


def reduce_scatter(tensor, tensor_or_tensor_list=None, op=ReduceOp.SUM,
                   group=None, sync_op=True):
    ax = group.axis_name if group is not None else None
    src = tensor_or_tensor_list if tensor_or_tensor_list is not None else tensor
    if ax is not None and _axis_bound(ax):
        def f(a):
            return lax.psum_scatter(a, ax, scatter_dimension=0, tiled=True)
        return _run_op("reduce_scatter", f, (src,), {})
    return src


def broadcast(tensor, src=0, group=None, sync_op=True):
    ax = group.axis_name if group is not None else None
    if ax is not None and _axis_bound(ax):
        def f(a):
            # select src's value on every member of the axis
            full = lax.all_gather(a, ax, axis=0)
            return full[src]
        return _run_op("broadcast", f, (tensor,), {})
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op=op, group=group)


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    if isinstance(in_tensor_list, Tensor):
        t = in_tensor_list
        ax = group.axis_name if group is not None else None
        if ax is not None and _axis_bound(ax):
            return _run_op(
                "alltoall",
                lambda a: lax.all_to_all(a, ax, split_axis=0, concat_axis=0,
                                         tiled=True),
                (t,), {})
        return t
    from ...tensor import concat, split
    n = group.nranks if group is not None else 1
    stacked = concat(in_tensor_list, axis=0)
    out = alltoall(stacked, group=group)
    parts = split(out, n, axis=0)
    if out_tensor_list is not None:
        out_tensor_list.extend(parts)
        return out_tensor_list
    return parts


def all_to_all_single(output, input, output_split_sizes=None,
                      input_split_sizes=None, group=None, sync_op=True):
    res = alltoall(input, group=group)
    if isinstance(output, Tensor):
        output._data = res._data
        return output
    return res


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """ref: paddle.distributed.alltoall_single (distributed/communication/
    all_to_all.py): scatter slices of one tensor to every rank and gather
    their slices back — the in-tensor's dim 0 splits across the group.
    Equal splits only (the XLA all_to_all form); ragged splits would need
    host-side repacking, which the MoE layer does at a higher level."""
    for name, sizes in (("in_split_sizes", in_split_sizes),
                        ("out_split_sizes", out_split_sizes)):
        if sizes is not None and len(set(sizes)) > 1:
            raise NotImplementedError(
                f"alltoall_single: ragged {name}={sizes} is not "
                "supported on a TPU mesh (XLA all_to_all splits evenly); "
                "use distributed.ragged_alltoall_single (per-hop ppermute "
                "ring with a count exchange) for uneven splits")
    res = alltoall(in_tensor, group=group)
    if isinstance(out_tensor, Tensor):
        out_tensor._data = res._data
        return out_tensor
    return res


def ragged_alltoall_single(in_tensor, send_counts, peer_rows, group=None,
                           impl=None, sync_op=True):
    """Uneven-splits alltoall_single (PR 10, VERDICT item 8): scatter ragged
    row slices of ``in_tensor`` to every rank of the group and gather theirs.

    ``in_tensor``'s dim 0 is sorted by destination rank; ``send_counts`` (an
    [nranks] int tensor/array) gives each peer's slice length. ``peer_rows``
    is the static per-peer chunk capacity every slice is padded to (SPMD
    shapes must be static; per-rank dynamic output splits cannot exist under
    a single controller). Returns ``(out, recv_counts)`` where ``out`` is
    [nranks * peer_rows, ...] with rank j's rows at
    ``out[j * peer_rows : j * peer_rows + recv_counts[j]]`` and zeros beyond
    each count. Transport follows ``PADDLE_TPU_MOE_A2A`` unless ``impl`` is
    given ('ring' = n-1 overlappable ppermute hops, 'dense' = one XLA
    all_to_all over the same chunk layout); both are bitwise-identical."""
    from . import ragged as _ragged
    from ... import envs as _envs
    if impl is None:
        impl = _envs.get("PADDLE_TPU_MOE_A2A")
    ax = group.axis_name if group is not None else None
    counts = send_counts._data if isinstance(send_counts, Tensor) \
        else send_counts
    if ax is None or not _axis_bound(ax):
        n = group.nranks if group is not None else 1
        if n != 1:
            raise RuntimeError(
                "ragged_alltoall_single outside a compiled mesh region is "
                "only defined for a trivial (size-1) group")
        # size-1 group: identity exchange, still pad to the chunk layout
        def pad1(a):
            pad = jnp.zeros((peer_rows - a.shape[0],) + a.shape[1:], a.dtype)
            return jnp.concatenate([a[:peer_rows], pad], axis=0) \
                if a.shape[0] < peer_rows else a[:peer_rows]
        out = _run_op("ragged_alltoall_single", pad1, (in_tensor,), {})
        return out, send_counts
    res = {}
    def f(a):
        out, rc = _ragged.ragged_all_to_all(a, jnp.asarray(counts), ax,
                                            peer_rows, impl=impl)
        res["recv_counts"] = rc
        return out
    out = _run_op("ragged_alltoall_single", f, (in_tensor,), {})
    return out, Tensor(res["recv_counts"])


def ppermute(tensor, perm, group=None):
    """collective_permute over the group axis (the TPU-native p2p primitive;
    PP microbatch rotation uses this instead of send/recv)."""
    ax = group.axis_name if group is not None else None
    if ax is not None and _axis_bound(ax):
        return _run_op("ppermute", lambda a: lax.ppermute(a, ax, perm),
                       (tensor,), {})
    return tensor


def send(tensor, dst=0, group=None, sync_op=True):
    raise RuntimeError(
        "point-to-point send/recv does not exist on a TPU mesh; use "
        "distributed.ppermute (collective_permute over ICI) inside a compiled "
        "region — fleet's pipeline engine does this for you")


def recv(tensor, src=0, group=None, sync_op=True):
    raise RuntimeError(
        "point-to-point send/recv does not exist on a TPU mesh; use "
        "distributed.ppermute (collective_permute over ICI) inside a compiled "
        "region — fleet's pipeline engine does this for you")


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group)


def barrier(group=None):
    # single-controller: device work is ordered by data dependence; a host
    # barrier only matters multi-host
    try:
        from jax.experimental import multihost_utils
        if jax.process_count() > 1:
            multihost_utils.sync_global_devices("paddle_tpu_barrier")
    except Exception:
        pass


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    ax = group.axis_name if group is not None else None
    if ax is not None and _axis_bound(ax) and tensor_list is not None:
        from ...tensor import stack
        stacked = stack(tensor_list, axis=0)
        def f(s):
            return s[lax.axis_index(ax)]
        return _run_op("scatter", f, (stacked,), {})
    return tensor


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """ref: paddle.distributed.gather — collect tensors onto rank dst.

    Single-controller SPMD note: under XLA there is no rank-private
    result; this lowers to an all_gather and every rank observes the
    gathered list (a superset of the reference's contract, same values
    on dst). Outside a bound axis (trivial group) it fills the list with
    the input."""
    ax = group.axis_name if group is not None else None
    n = group.nranks if group is not None else 1
    if gather_list is None:
        gather_list = []
    if ax is not None and _axis_bound(ax):
        g = _run_op("gather",
                    lambda a: lax.all_gather(a, ax, axis=0), (tensor,), {})
        for i in range(n):
            gather_list.append(g[i])
    else:
        for _ in range(max(n, 1)):
            gather_list.append(tensor)
    return gather_list


class P2POp:
    """ref: paddle.distributed.P2POp — one half of a batched point-to-point
    exchange. `op` is ``distributed.isend`` or ``distributed.irecv``; the
    batch executes as one collective_permute (see batch_isend_irecv)."""

    def __init__(self, op, tensor, peer, group=None):
        if op not in (isend, irecv):
            raise ValueError("P2POp op must be paddle.distributed.isend "
                             "or paddle.distributed.irecv")
        self.op = op
        self.tensor = tensor
        self.peer = int(peer)
        self.group = group


class _P2PTask:
    """Completed-task handle (XLA ordering makes the op synchronous with
    respect to its consumers)."""

    def wait(self):
        return None

    def is_completed(self):
        return True


def batch_isend_irecv(p2p_op_list):
    """ref: paddle.distributed.batch_isend_irecv.

    TPU-native mapping: raw p2p does not exist on a TPU mesh, but a batch
    of paired isend/irecv IS a permutation of the group axis — exactly
    ``lax.ppermute`` over ICI. Each isend(t, peer) contributes the
    uniform shift (peer - rank) mod n; the matching irecv's tensor is
    filled with the permuted value. Every rank must describe the same
    global permutation (true for the reference's canonical pipeline /
    ring uses); unpaired ops raise."""
    from .. import env as _env
    if not p2p_op_list:
        return []
    sends = [p for p in p2p_op_list if p.op is isend]
    recvs = [p for p in p2p_op_list if p.op is irecv]
    if len(sends) != len(recvs):
        raise ValueError(
            "batch_isend_irecv on a TPU mesh needs paired isend/irecv "
            f"(got {len(sends)} sends, {len(recvs)} recvs): the batch must "
            "form a permutation of the group axis")
    tasks = []
    for s in sends:
        group = s.group or (recvs[0].group if recvs else None)
        n = group.nranks if group is not None else 1
        rank = group.rank if group is not None else _env.get_rank()
        shift = (s.peer - rank) % max(n, 1)
        # the matching receive comes from rank - shift
        src = (rank - shift) % max(n, 1)
        match = next((r for r in recvs if r.peer == src), None)
        if match is None:
            raise ValueError(
                f"isend to peer {s.peer} (shift {shift}) has no matching "
                f"irecv from {src}; the batch must form a permutation")
        recvs.remove(match)
        perm = [(i, (i + shift) % n) for i in range(max(n, 1))]
        out = ppermute(s.tensor, perm, group=group)
        match.tensor._data = out._data
        tasks.append(_P2PTask())
    return tasks


from . import stream  # noqa: E402  (cyclic-safe: stream imports lazily)


def get_backend(group=None):
    """ref: paddle.distributed.get_backend — the collective backend name.
    XLA collectives over ICI/DCN stand in for the reference's NCCL/GLOO."""
    return "XLA"


def destroy_process_group(group=None):
    """ref: destroy_process_group. Groups are mesh-axis views with no
    owned OS resources; dropping the default group reference suffices."""
    global _default_group
    if group is None or group is _default_group:
        _default_group = None
    return True


def monitored_barrier(group=None, timeout=None, wait_all_ranks=False):
    """ref: monitored_barrier — barrier that surfaces straggler failures.
    Multi-host sync_global_devices raises on peer failure, which is the
    monitored property."""
    return barrier(group)


def all_gather_into_tensor(output, input, group=None, sync_op=True):
    """ref: all_gather_into_tensor (tensor form: output holds the
    concatenated result)."""
    res = all_gather(input, group=group)
    if isinstance(output, Tensor):
        output._data = res._data
        return output
    return res


def reduce_scatter_tensor(output, input, op=ReduceOp.SUM, group=None,
                          sync_op=True):
    """ref: reduce_scatter_tensor (tensor form)."""
    res = reduce_scatter(input, op=op, group=group)
    if isinstance(output, Tensor):
        output._data = res._data
        return output
    return res
