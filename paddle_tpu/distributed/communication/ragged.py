"""Ragged (uneven-splits) all-to-all over a mesh axis (PR 10, VERDICT item 8).

XLA's ``all_to_all`` splits its operand evenly across the axis, so a true
``alltoall_single`` with per-rank row counts has been an API gap: MoE dispatch
padded every peer slice to the worst-case capacity bucket and shipped the
padding over the wire. This module closes the gap with the TPU-native
building blocks:

- ``exchange_counts``: a tiny dense [n, ...] count all-to-all so every rank
  learns how many real rows each peer is about to send it.
- ``ring_hop``: one ``ppermute`` shift of the ep ring (hop ``h`` sends to
  rank ``(i + h) % n``); n-1 hops realize the full personalized exchange
  while carrying only each destination's actual rows (padded to a static
  per-peer chunk so shapes stay SPMD-static — the pad is *per peer*, not
  the global capacity bucket, and in the MoE path each hop's chunk overlaps
  the grouped-GEMM on rows that already arrived).
- ``ragged_all_to_all``: the generic dest-major exchange built from the two,
  with a dense single-``all_to_all`` fallback carrying the identical chunk
  layout (bitwise-equal results, no per-hop overlap).

All transports move the same row values into the same slots, so downstream
consumers are bitwise-independent of the transport choice.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..._compat import axis_size as _axis_size
from ...observability import trace as _obs


def exchange_counts(counts, axis_name, *, name="ragged_a2a.counts"):
    """All-to-all the per-destination count rows: ``counts[j]`` is what this
    rank is about to send rank ``j``; row ``j`` of the result is what rank
    ``j`` is about to send this rank. Shape [n, ...] -> [n, ...]."""
    counts = jnp.asarray(counts)
    n = _axis_size(axis_name)
    nbytes = int(counts.size * counts.dtype.itemsize)
    with _obs.comm_span(name, nbytes=nbytes, site="ragged_a2a.counts"):
        if n == 1:
            return counts
        return lax.all_to_all(counts, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)


def ring_hop(x, axis_name, hop, *, name="ragged_a2a.hop"):
    """One hop of the ragged ring: every rank ``i`` sends ``x`` to rank
    ``(i + hop) % n`` (negative ``hop`` walks the reverse/return ring)."""
    n = _axis_size(axis_name)
    h = hop % n
    if h == 0:
        return x
    perm = [(i, (i + h) % n) for i in range(n)]
    nbytes = int(x.size * x.dtype.itemsize)
    with _obs.comm_span(name, nbytes=nbytes, site="ragged_a2a.hop"):
        return lax.ppermute(x, axis_name, perm)


def _pack_dest_major(rows, send_counts, n, peer_rows):
    """[R, ...] dest-sorted rows -> [n, peer_rows, ...] zero-padded chunks."""
    R = rows.shape[0]
    padded = jnp.concatenate(
        [rows, jnp.zeros((1,) + rows.shape[1:], rows.dtype)], axis=0)
    off = jnp.concatenate(
        [jnp.zeros((1,), send_counts.dtype), jnp.cumsum(send_counts)[:-1]])
    r = jnp.arange(peer_rows, dtype=send_counts.dtype)
    idx = jnp.where(r[None, :] < send_counts[:, None],
                    off[:, None] + r[None, :], R)
    return jnp.take(padded, idx, axis=0)


def ragged_all_to_all(rows, send_counts, axis_name, peer_rows, *,
                      impl="ring", name="ragged_a2a"):
    """Personalized exchange with uneven per-peer splits over ``axis_name``.

    ``rows`` is [R, ...] sorted by destination rank: the first
    ``send_counts[0]`` rows go to rank 0, the next ``send_counts[1]`` to
    rank 1, and so on (trailing rows beyond ``send_counts.sum()`` are
    ignored). ``peer_rows`` is the static per-peer chunk capacity — the most
    rows any rank may address to any single peer; each peer slice is
    zero-padded to it so SPMD shapes stay static, but only ``peer_rows``
    per hop crosses the wire instead of the global capacity bucket.

    Returns ``(out, recv_counts)``: ``out`` is [n * peer_rows, ...] where
    ``out[j * peer_rows : j * peer_rows + recv_counts[j]]`` are the rows
    rank ``j`` addressed to this rank (zero rows beyond each count), and
    ``recv_counts`` is [n]. ``impl="ring"`` walks n-1 ppermute hops;
    ``impl="dense"`` ships the identical chunk layout through one XLA
    all_to_all — both land bitwise-identical ``out``.
    """
    if impl not in ("ring", "dense"):
        raise ValueError(f"ragged_all_to_all: unknown impl {impl!r}")
    n = _axis_size(axis_name)
    send_counts = jnp.asarray(send_counts)
    send = _pack_dest_major(rows, send_counts, n, peer_rows)
    recv_counts = exchange_counts(send_counts, axis_name,
                                  name=f"{name}.counts")
    if n == 1:
        return send.reshape((peer_rows,) + rows.shape[1:]), recv_counts
    if impl == "dense":
        nbytes = int(send.size * send.dtype.itemsize)
        with _obs.comm_span(f"{name}.dense", nbytes=nbytes,
                            site="ragged_a2a.dense"):
            out = lax.all_to_all(send, axis_name, split_axis=0,
                                 concat_axis=0, tiled=True)
    else:
        me = lax.axis_index(axis_name)
        out = jnp.zeros_like(send)
        out = lax.dynamic_update_index_in_dim(
            out, jnp.take(send, me, axis=0), me, 0)
        for h in range(1, n):
            got = ring_hop(jnp.take(send, (me + h) % n, axis=0), axis_name,
                           h, name=f"{name}.hop")
            out = lax.dynamic_update_index_in_dim(out, got, (me - h) % n, 0)
    return out.reshape((n * peer_rows,) + rows.shape[1:]), recv_counts
