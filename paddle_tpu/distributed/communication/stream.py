"""ref: python/paddle/distributed/communication/stream/ — the stream-
variant collective namespace. The reference schedules these on a chosen
CUDA stream (use_calc_stream); under XLA, op ordering and overlap are the
compiler's job, so each function delegates to the plain collective and
the stream arguments are accepted for API parity."""
from __future__ import annotations


def all_reduce(tensor, op=None, group=None, sync_op=True,
               use_calc_stream=False):
    from . import ReduceOp, all_reduce as _impl
    return _impl(tensor, op=op or ReduceOp.SUM, group=group, sync_op=sync_op)


def all_gather(tensor_or_tensor_list, tensor=None, group=None, sync_op=True,
               use_calc_stream=False):
    from . import all_gather as _impl
    return _impl(tensor_or_tensor_list, tensor, group=group, sync_op=sync_op)


def reduce(tensor, dst=0, op=None, group=None, sync_op=True,
           use_calc_stream=False):
    from . import ReduceOp, reduce as _impl
    return _impl(tensor, dst=dst, op=op or ReduceOp.SUM, group=group,
                 sync_op=sync_op)


def reduce_scatter(tensor, tensor_or_tensor_list=None, op=None, group=None,
                   sync_op=True, use_calc_stream=False):
    from . import ReduceOp, reduce_scatter as _impl
    return _impl(tensor, tensor_or_tensor_list, group=group)


def broadcast(tensor, src=0, group=None, sync_op=True, use_calc_stream=False):
    from . import broadcast as _impl
    return _impl(tensor, src=src, group=group, sync_op=sync_op)


def scatter(tensor, tensor_or_tensor_list=None, src=0, group=None,
            sync_op=True, use_calc_stream=False):
    from . import scatter as _impl
    return _impl(tensor, tensor_or_tensor_list, src=src, group=group,
                 sync_op=sync_op)


def alltoall(out_tensor_or_list, in_tensor_or_list=None, group=None,
             sync_op=True, use_calc_stream=False):
    from . import alltoall as _impl
    if in_tensor_or_list is None:
        return _impl(out_tensor_or_list, group=group, sync_op=sync_op)
    # reference contract: fill the caller's output container in place
    return _impl(in_tensor_or_list, out_tensor_or_list, group=group,
                 sync_op=sync_op)


def alltoall_single(output, input, output_split_sizes=None,
                    input_split_sizes=None, group=None, sync_op=True,
                    use_calc_stream=False):
    from . import all_to_all_single as _impl
    return _impl(output, input, output_split_sizes, input_split_sizes,
                 group=group, sync_op=sync_op)


def send(tensor, dst=0, group=None, sync_op=True, use_calc_stream=False):
    from . import send as _impl
    return _impl(tensor, dst=dst, group=group, sync_op=sync_op)


def recv(tensor, src=0, group=None, sync_op=True, use_calc_stream=False):
    from . import recv as _impl
    return _impl(tensor, src=src, group=group, sync_op=sync_op)
