"""Distributed environment (ref: python/paddle/distributed/parallel.py env vars).

TPU-native model: single-controller SPMD. One python process per HOST (not per
device); jax.distributed coordinates hosts, the mesh spans all devices.
``get_rank``/``get_world_size`` are therefore process-level (what you need for
data loading / logging); device-level parallelism lives in the mesh
(fleet/topology.py).
"""
from __future__ import annotations

import os

import jax

_initialized = False


def init_parallel_env():
    """Bootstrap multi-host jax (TCPStore-equivalent rendezvous is handled by
    jax.distributed's coordination service). Single-host: no-op."""
    global _initialized
    if _initialized:
        return
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    proc_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if coord and nprocs > 1:
        # the jax coordination service needs its OWN port: MASTER_PORT is
        # the launch controller's TCPStore (already bound on rank 0's
        # node). Default to store port + 1; override with
        # PADDLE_JAX_COORD_PORT.
        port = os.environ.get("PADDLE_JAX_COORD_PORT")
        if port is None:
            port = str(int(os.environ.get("MASTER_PORT", "8475")) + 1)
        jax.distributed.initialize(f"{coord}:{port}", num_processes=nprocs,
                                   process_id=proc_id)
    _initialized = True


def get_rank(group=None) -> int:
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    return jax.process_count()


def is_initialized() -> bool:
    return _initialized


def parallel_device_count() -> int:
    return jax.device_count()
