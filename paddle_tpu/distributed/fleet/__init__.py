"""Fleet: hybrid-parallel training (ref: python/paddle/distributed/fleet/)."""
from . import utils
from .distributed_strategy import DistributedStrategy
from .fleet import (Fleet, distributed_model, distributed_optimizer,
                    distributed_scaler, fleet, init, init_server,
                    init_worker, is_server, is_worker, run_server,
                    stop_server, stop_worker)
from .topology import (CommunicateTopology, HybridCommunicateGroup,
                       get_hybrid_communicate_group,
                       set_hybrid_communicate_group)
from . import meta_parallel
from .meta_parallel.parallel_layers.mp_layers import (ColumnParallelLinear,
                                                      ParallelCrossEntropy,
                                                      RowParallelLinear,
                                                      VocabParallelEmbedding)
from .meta_parallel.parallel_layers.pp_layers import (LayerDesc, PipelineLayer,
                                                      SharedLayerDesc)
from .recompute.recompute import (recompute, recompute_hybrid,
                                  recompute_sequential)


def get_hybrid_communicate_group_global():
    return get_hybrid_communicate_group()


# reference import path: `from paddle.distributed.fleet import auto`
from .. import auto_parallel as auto  # noqa: E402
