"""DistributedStrategy (ref: python/paddle/distributed/fleet/base/distributed_strategy.py).

The reference backs this with a protobuf; a typed python object with the same
field names is sufficient (and validates degrees against the device count at
fleet.init time via the topology)."""
from __future__ import annotations


class DistributedStrategy:
    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
            "ep_degree": 1,
        }
        self.pipeline_configs = {
            "micro_batch_size": 1,
            "accumulate_steps": 1,
        }
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0,
            "use_pure_fp16": False,
            "use_bf16": True,
        }
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.sharding = False
        self.sharding_configs = {"stage": 1, "degree": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1}
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1}
        # PS async / geo-SGD mode (ref: a_sync + a_sync_configs["k_steps"]:
        # 0 = fully async PS pushes; k > 0 = geo-SGD with per-k-step delta
        # sync, served by PSClient.init_geo/geo_step)
        self.a_sync = False
        self.a_sync_configs = {"k_steps": 0}
        self.find_unused_parameters = False
        self.fuse_grad_size_in_MB = 32
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}

    @property
    def sharding_degree(self):
        return self.sharding_configs.get("degree", 1)

    def __repr__(self):
        return (f"DistributedStrategy(hybrid={self.hybrid_configs}, "
                f"pipeline={self.pipeline_configs})")
