"""Elastic / fault-tolerant training (ref: python/paddle/distributed/fleet/elastic/).

The reference resizes jobs via etcd membership within [min_np, max_np].  A TPU
slice cannot resize in place, so elasticity here means **failure detection +
checkpoint-restart**: heartbeats through the rendezvous TCP store detect dead
ranks; the launch controller (distributed/launch/) relaunches the node with
``PADDLE_RESTART_ROUND`` bumped; training code resumes from the latest
checkpoint (see distributed/checkpoint/).
"""
from .manager import ElasticManager, current_restart_round  # noqa: F401
