"""Heartbeat-based failure detection over the rendezvous store
(ref: python/paddle/distributed/fleet/elastic/manager.py — etcd TTL leases
there; TCP-store timestamps here).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from .... import runtime as rt


def current_restart_round() -> int:
    """Which elastic restart round this process is running in (0 = first
    launch). Training scripts use this to decide whether to resume."""
    return int(os.environ.get("PADDLE_RESTART_ROUND", "0"))


class ElasticManager:
    """Per-process heartbeat writer + peer watchdog.

    Every ``interval`` seconds, writes ``{job}/hb/{rank}`` = monotonic-ish
    wall time into the store.  The watchdog scans peers' heartbeats; a peer
    stale by more than ``miss_threshold * interval`` triggers ``on_fault``
    (default: ``os._exit(1)`` so the launch controller's restart loop takes
    over — the whole-job restart is the TPU analog of an elastic scale event).
    """

    def __init__(self, rank: Optional[int] = None,
                 world_size: Optional[int] = None,
                 host: Optional[str] = None, port: Optional[int] = None,
                 job_id: Optional[str] = None,
                 interval: Optional[float] = None,
                 miss_threshold: float = 3.0,
                 on_fault: Optional[Callable[[int], None]] = None):
        self.rank = rank if rank is not None else int(
            os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.world_size = world_size if world_size is not None else int(
            os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        host = host or os.environ.get("PADDLE_MASTER", "127.0.0.1")
        port = port if port is not None else int(
            os.environ.get("MASTER_PORT", "0"))
        self.job_id = job_id or os.environ.get("PADDLE_JOB_ID", "default")
        self.interval = interval if interval is not None else float(
            os.environ.get("PADDLE_HEARTBEAT_INTERVAL", "5.0"))
        self.miss_threshold = miss_threshold
        self.on_fault = on_fault or self._default_fault
        self._store = rt.TCPStore(host, port) if port else None
        self._stop = threading.Event()
        self._threads = []
        self.dead_ranks: list[int] = []

    # -- lifecycle --------------------------------------------------------
    def start(self):
        if self._store is None:
            return
        self._beat()  # register immediately so peers see us
        t1 = threading.Thread(target=self._beat_loop, daemon=True)
        t2 = threading.Thread(target=self._watch_loop, daemon=True)
        self._threads = [t1, t2]
        t1.start()
        t2.start()

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2.0)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- internals --------------------------------------------------------
    def _key(self, rank: int) -> str:
        return f"{self.job_id}/hb/{rank}"

    def _beat(self):
        try:
            self._store.set(self._key(self.rank), repr(time.time()).encode())
        except (ConnectionError, OSError):
            pass  # store down: the controller is already tearing down

    def _beat_loop(self):
        while not self._stop.wait(self.interval):
            self._beat()

    def _watch_loop(self):
        # Give peers one full interval to register before judging them.
        if self._stop.wait(self.interval * 2):
            return
        while not self._stop.wait(self.interval):
            now = time.time()
            stale = []
            for r in range(self.world_size):
                if r == self.rank:
                    continue
                try:
                    raw = self._store.get(self._key(r), timeout=1.0)
                    last = float(raw.decode())
                except TimeoutError:
                    continue  # never registered yet
                except (ConnectionError, OSError, ValueError):
                    return
                if now - last > self.miss_threshold * self.interval:
                    stale.append(r)
            if stale:
                self.dead_ranks = stale
                self.on_fault(stale[0])
                return

    def _default_fault(self, dead_rank: int):
        import sys
        print(f"[elastic] rank {self.rank}: peer rank {dead_rank} missed "
              f"heartbeats; exiting for checkpoint-restart", file=sys.stderr)
        # drain in-flight async checkpoint writes so the restart resumes
        # from the newest complete save (writes are atomic tmp+rename, so
        # even a hard kill can't corrupt — this just avoids losing the
        # latest round)
        try:
            from ...checkpoint.save_load import wait_all_async_saves
            wait_all_async_saves()
        except Exception:
            pass
        os._exit(1)
