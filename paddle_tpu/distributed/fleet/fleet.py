"""Fleet facade (ref: python/paddle/distributed/fleet/fleet.py).

fleet.init builds the hybrid mesh topology; distributed_model wraps the model
per the strategy's degrees (TensorParallel / PipelineParallel); and
distributed_optimizer wraps with HybridParallelOptimizer — the same three
calls as the reference, now producing mesh-aware objects whose compiled steps
run SPMD over ICI.
"""
from __future__ import annotations

from typing import Optional

from ...nn.layer.layers import Layer
from ..env import init_parallel_env
from .distributed_strategy import DistributedStrategy
from .meta_optimizers.dygraph_optimizer.hybrid_parallel_optimizer import (
    HybridParallelOptimizer)
from .meta_parallel.parallel_layers.pp_layers import PipelineLayer
from .meta_parallel.pipeline_parallel import PipelineParallel
from .meta_parallel.tensor_parallel import TensorParallel
from .topology import (HybridCommunicateGroup, set_hybrid_communicate_group,
                       get_hybrid_communicate_group)


class Fleet:
    def __init__(self):
        import threading
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._is_initialized = False
        self._ps_server = None
        self._ps_client = None
        self._ps_stop = threading.Event()

    def init(self, role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
        self._strategy = strategy or DistributedStrategy()
        init_parallel_env()
        cfg = self._strategy.hybrid_configs
        self._hcg = HybridCommunicateGroup(
            dp_degree=cfg.get("dp_degree", 1),
            mp_degree=cfg.get("mp_degree", 1),
            pp_degree=cfg.get("pp_degree", 1),
            sharding_degree=cfg.get("sharding_degree", 1),
            sep_degree=cfg.get("sep_degree", 1),
            ep_degree=cfg.get("ep_degree", 1))
        set_hybrid_communicate_group(self._hcg)
        self._is_initialized = True
        return self

    def get_hybrid_communicate_group(self):
        return self._hcg

    def distributed_model(self, model: Layer):
        assert self._is_initialized, "call fleet.init first"
        hcg = self._hcg
        strategy = self._strategy
        # strategy.recompute (ref: fleet/meta_optimizers/recompute — a
        # graph rewrite in the static reference; here sublayer forwards
        # are wrapped so remat lands in the compiled HLO / eager tape)
        if strategy is not None and getattr(strategy, "recompute", False):
            from .recompute.recompute import attach_recompute
            attach_recompute(
                model,
                strategy.recompute_configs.get("checkpoints") or None)
        # strategy.amp wraps the INNER model's forward (before the
        # parallel wrappers): PipelineParallel.train_batch calls
        # self._layers(...) directly, so an outer-wrapper-only autocast
        # would be a silent no-op on the pp path (review r5)
        if strategy is not None and getattr(strategy, "amp", False):
            cfg = getattr(strategy, "amp_configs", {}) or {}
            # use_pure_fp16=True means FLOAT16 as in the reference;
            # bfloat16 only on an explicit use_bf16=True (the
            # DistributedStrategy default dict carries one, keeping the
            # TPU-friendly bf16 default). The previous mapping defaulted
            # use_bf16 to True in the lookup, silently remapping every
            # pure-fp16 request to bf16 (ADVICE r5 inversion).
            use_bf16 = bool(cfg.get("use_bf16", False))
            if cfg.get("use_pure_fp16") and use_bf16:
                import warnings
                warnings.warn(
                    "amp_configs sets use_pure_fp16=True together with "
                    "use_bf16=True: running pure BFLOAT16; set "
                    "use_bf16=False for the reference's float16 behavior",
                    UserWarning, stacklevel=2)
            dtype = "bfloat16" if use_bf16 else "float16"
            level = "O2" if cfg.get("use_pure_fp16") else "O1"
            from ...amp import decorate as amp_decorate
            if level == "O2":
                amp_decorate(model, level="O2", dtype=dtype)
            _wrap_forward_with_autocast(model, level, dtype)
        if hcg.get_pipe_parallel_world_size() > 1:
            if not isinstance(model, PipelineLayer):
                raise TypeError("pp_degree > 1 requires a PipelineLayer model")
            wrapped = PipelineParallel(model, hcg, strategy)
        elif hcg.get_model_parallel_world_size() > 1 or \
                hcg.get_sep_parallel_world_size() > 1:
            wrapped = TensorParallel(model, hcg, strategy)
        else:
            # pure dp/sharding: model unchanged (mesh handles it in
            # compiled steps)
            wrapped = model
        return wrapped

    def distributed_optimizer(self, optimizer, strategy=None):
        """Compose the strategy's meta-optimizer toggles around the user
        optimizer (ref: the static-graph meta-optimizer stack applies
        graph rewrites; here each toggle wraps or re-attaches state on
        the dygraph optimizer): sharding stage 1 -> DygraphSharding,
        localsgd/dgc -> their wrappers, then the hybrid wrapper with the
        mesh-aware grad clip."""
        assert self._is_initialized, "call fleet.init first"
        strategy = strategy or self._strategy
        if strategy is not None:
            if getattr(strategy, "sharding", False) and \
                    int(strategy.sharding_configs.get("stage", 1)) == 1:
                from .meta_optimizers.dygraph_optimizer \
                    .hybrid_parallel_optimizer import DygraphShardingOptimizer
                optimizer = DygraphShardingOptimizer(optimizer, self._hcg)
            if getattr(strategy, "localsgd", False):
                from .meta_optimizers.localsgd_dgc import LocalSGDOptimizer
                k = getattr(strategy, "localsgd_configs",
                            {}).get("k_steps", 1)
                optimizer = LocalSGDOptimizer(optimizer, k_steps=k)
            if getattr(strategy, "amp", False):
                # O2 (pure low-precision params) keeps fp32 master
                # weights in the optimizer (ref: amp meta-optimizer's
                # master-weight path)
                cfg = getattr(strategy, "amp_configs", {}) or {}
                if cfg.get("use_pure_fp16"):
                    optimizer._multi_precision = True
        return HybridParallelOptimizer(optimizer, self._hcg, strategy)

    def distributed_scaler(self, scaler):
        """Hybrid-parallel GradScaler (ref: fleet.distributed_scaler):
        under SPMD the found-inf check is computed on replicated loss/
        grads inside the compiled step, so the scaler itself needs no
        per-group allreduce — returned as-is for API parity."""
        return scaler

    # -- parameter-server mode (ref: fleet PS role flow:
    # fleet.init(is_collective=False) -> init_server/run_server on PSERVER
    # ranks, init_worker + pull/push on TRAINER ranks; roles/endpoints come
    # from the PADDLE_* env the launcher sets) ----------------------------

    def _ps_env(self):
        import os
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        servers = [e for e in eps.split(",") if e]
        return {
            "role": os.environ.get("TRAINING_ROLE", "TRAINER").upper(),
            "server_endpoints": servers,
            "num_servers": max(len(servers), 1),
            "server_index": int(os.environ.get("PADDLE_PSERVER_ID", "0")),
            "trainer_index": int(os.environ.get("PADDLE_TRAINER_ID", "0")),
            "master": os.environ.get("PADDLE_MASTER",
                                     servers[0] if servers else None),
            "world_size": int(os.environ.get("PADDLE_WORLD_SIZE", "1")),
            "rank": int(os.environ.get("PADDLE_RANK", "0")),
        }

    def is_server(self):
        return self._ps_env()["role"] == "PSERVER"

    def is_worker(self):
        return self._ps_env()["role"] == "TRAINER"

    def init_server(self, *args, **kwargs):
        from ..ps import PSServer
        env = self._ps_env()
        self._ps_server = PSServer(server_index=env["server_index"],
                                   rank=env["rank"],
                                   world_size=env["world_size"],
                                   master_endpoint=env["master"])
        return self._ps_server

    def run_server(self):
        """Serve table requests until stop_server() (ref: blocking
        fleet.run_server)."""
        assert self._ps_server is not None, "call fleet.init_server first"
        self._ps_stop.wait()
        self._ps_server.stop()

    def stop_server(self):
        self._ps_stop.set()

    def init_worker(self, *args, **kwargs):
        from ..ps import PSClient
        env = self._ps_env()
        self._ps_client = PSClient(f"trainer:{env['trainer_index']}",
                                   num_servers=env["num_servers"],
                                   rank=env["rank"],
                                   world_size=env["world_size"],
                                   master_endpoint=env["master"])
        return self._ps_client

    def stop_worker(self):
        if self._ps_client is not None:
            self._ps_client.stop()
            self._ps_client = None

    # -- worker info (reference API surface) ------------------------------
    def worker_index(self):
        import jax
        return jax.process_index()

    def worker_num(self):
        import jax
        return jax.process_count()

    def is_first_worker(self):
        return self.worker_index() == 0

    def barrier_worker(self):
        from ..communication import barrier
        barrier()


def _wrap_forward_with_autocast(wrapped, level, dtype):
    """Make the model's forward run under paddle.amp.auto_cast — the
    observable effect of strategy.amp (matmuls/convs compute in the amp
    dtype when the step is traced or run eagerly)."""
    import functools

    from ...amp import auto_cast
    if getattr(wrapped, "_amp_wrapped", None) is not None:
        return
    orig = wrapped.forward

    @functools.wraps(orig)
    def fwd(*args, **kwargs):
        with auto_cast(enable=True, level=level, dtype=dtype):
            return orig(*args, **kwargs)

    wrapped.forward = fwd
    wrapped._amp_wrapped = (level, dtype)


fleet = Fleet()


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    return fleet.init(role_maker, is_collective, strategy, log_level)


def distributed_model(model):
    return fleet.distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return fleet.distributed_optimizer(optimizer, strategy)


def distributed_scaler(scaler):
    return fleet.distributed_scaler(scaler)


def get_hybrid_communicate_group_():
    return fleet.get_hybrid_communicate_group()


def init_server(*args, **kwargs):
    return fleet.init_server(*args, **kwargs)


def run_server():
    return fleet.run_server()


def stop_server():
    return fleet.stop_server()


def init_worker(*args, **kwargs):
    return fleet.init_worker(*args, **kwargs)


def stop_worker():
    return fleet.stop_worker()


def is_server():
    return fleet.is_server()


def is_worker():
    return fleet.is_worker()
