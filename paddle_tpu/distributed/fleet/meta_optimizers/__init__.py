from .dygraph_optimizer.hybrid_parallel_optimizer import (
    DygraphShardingOptimizer, HybridParallelClipGrad, HybridParallelOptimizer)
from .localsgd_dgc import DGCMomentumOptimizer, LocalSGDOptimizer  # noqa: F401,E501
