from .dygraph_optimizer.hybrid_parallel_optimizer import (
    DygraphShardingOptimizer, HybridParallelClipGrad, HybridParallelOptimizer)
