from .hybrid_parallel_optimizer import (DygraphShardingOptimizer,
                                        HybridParallelClipGrad,
                                        HybridParallelOptimizer)
