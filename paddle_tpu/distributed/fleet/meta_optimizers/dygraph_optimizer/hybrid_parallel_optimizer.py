"""HybridParallelOptimizer (ref: python/paddle/distributed/fleet/
meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py).

Wraps the user optimizer; grad clipping uses HybridParallelClipGrad, whose
global norm must span ALL shards. Single-controller note: every parameter
(incl. mp/sharding-sharded ones) is one logical array here, so the local
sq-norm sum IS the global norm — the reference's cross-group allreduce chain
(mp+pp+sharding) is implicit. Inside compiled steps with sharded params, XLA
reduces the norm across shards for the same reason.
"""
from __future__ import annotations

import jax.numpy as jnp

from .....nn.clip import ClipGradByGlobalNorm
from .....tensor.tensor import Tensor


class HybridParallelClipGrad(ClipGradByGlobalNorm):
    def __init__(self, clip, hcg):
        super().__init__(getattr(clip, "clip_norm", 1.0))
        self._hcg = hcg


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        if isinstance(optimizer._grad_clip, ClipGradByGlobalNorm):
            optimizer._grad_clip = HybridParallelClipGrad(
                optimizer._grad_clip, hcg)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, *a, **k):
        loss.backward()
        self._inner_opt.step()
        return None, None

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)


class DygraphShardingOptimizer:
    """ZeRO stage-1 (ref: dygraph_sharding_optimizer.py): optimizer states
    sharded over the 'sharding' axis. Sharding-rule form: attach
    opt_state_pspec to each param; the compiled TrainStep places states
    sharded and XLA reduce-scatters grads into the owning shard."""

    def __init__(self, optimizer, hcg=None):
        from ...meta_parallel.sharding.group_sharded import _shard_spec_for
        self._inner_opt = optimizer
        self._hcg = hcg
        degree = None
        if hcg is not None:
            try:
                degree = hcg.get_sharding_parallel_world_size()
            except Exception:
                degree = None
        for p in optimizer._parameter_list:
            if not p.stop_gradient:
                base = getattr(p, "pspec", None)
                p._pre_gs_pspec = base
                p.opt_state_pspec = _shard_spec_for(
                    tuple(p._data.shape), base, degree=degree)
                p.sharding_level = "os"
        optimizer._sharding_level = "os"

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)
