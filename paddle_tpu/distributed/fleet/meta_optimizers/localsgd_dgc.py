"""LocalSGD + DGC meta-optimizers (ref: fleet/meta_optimizers/
localsgd_optimizer.py, dgc_optimizer.py — the reference implements these as
static-graph rewrites; here they wrap the eager optimizer directly).

TPU note: DGC's win on GPU clusters is PCIe/IB bandwidth; over ICI the
all-reduce is rarely the bottleneck, but the semantics (top-k sparsified
gradient exchange with local accumulation + momentum correction) are kept
for parity and for DCN-connected multi-slice runs.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax


def _effective_world(group):
    """Ranks actually participating in a reduction: all_reduce is the
    identity unless the group's mesh axis is bound (communication.py), so
    dividing by a bigger world would silently shrink the values."""
    from ... import communication as comm
    if group is None or group.axis_name is None:
        return 1
    if not comm._axis_bound(group.axis_name):
        return 1
    return group.nranks


class LocalSGDOptimizer:
    """Run k local steps, then average parameters across the data-parallel
    group (ref: LocalSGDOptimizer)."""

    def __init__(self, inner_optimizer, k_steps=1, group=None):
        self.inner_optimizer = inner_optimizer
        if int(k_steps) < 1:
            raise ValueError(f"k_steps must be >= 1, got {k_steps}")
        self.k_steps = int(k_steps)
        self.group = group
        self._step_num = 0

    def step(self):
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k_steps == 0:
            self._sync_params()

    def _sync_params(self):
        from ... import communication as comm
        world = _effective_world(self.group)
        if world <= 1:
            return
        for p in self.inner_optimizer._parameter_list:
            # all_reduce is functional: capture the summed result
            reduced = comm.all_reduce(p, group=self.group)
            p._data = reduced._data / world

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def __getattr__(self, item):
        # delegate everything else (e.g. _grad_clip, _parameter_list) so
        # the fleet HybridParallelOptimizer can wrap a LocalSGD-wrapped
        # optimizer transparently
        return getattr(self.inner_optimizer, item)


class DGCMomentumOptimizer:
    """Deep Gradient Compression (Lin et al. 2018; ref: DGCMomentumOptimizer):
    exchange only the top ``rampup`` fraction of gradient magnitudes, locally
    accumulating the rest (with momentum correction) until they grow large
    enough to send."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 sparsity=0.999, group=None, name=None):
        if parameters is None:
            raise ValueError("DGCMomentumOptimizer needs parameters")
        self._params = list(parameters)
        self.lr = learning_rate
        self.momentum = momentum
        self.sparsity = float(sparsity)
        self.group = group
        self._u = {id(p): jnp.zeros_like(p._data.astype(jnp.float32))
                   for p in self._params}   # momentum-corrected residual
        self._v = {id(p): jnp.zeros_like(p._data.astype(jnp.float32))
                   for p in self._params}   # accumulated unsent gradient

    def _sparsify(self, g):
        """Top-(1-sparsity) by |value|: returns (sent, residual)."""
        flat = g.reshape(-1)
        k = max(1, int(round(flat.size * (1.0 - self.sparsity))))
        # k-th largest via top_k: O(n) vs a full sort
        thresh = lax.top_k(jnp.abs(flat), k)[0][-1]
        mask = (jnp.abs(g) >= thresh).astype(g.dtype)
        return g * mask, g * (1 - mask)

    def step(self):
        from ... import communication as comm
        world = _effective_world(self.group)
        for p in self._params:
            if p.grad is None:
                continue
            g = p.grad._data.astype(jnp.float32)
            # momentum correction: accumulate velocity locally
            self._u[id(p)] = self.momentum * self._u[id(p)] + g
            self._v[id(p)] = self._v[id(p)] + self._u[id(p)]
            sent, residual = self._sparsify(self._v[id(p)])
            self._v[id(p)] = residual
            # clear velocity where gradient was sent (DGC masking)
            self._u[id(p)] = self._u[id(p)] * (sent == 0)
            if world > 1:
                from ....tensor.tensor import Tensor
                reduced = comm.all_reduce(Tensor(sent), group=self.group)
                sent = reduced._data / world
            p._data = (p._data.astype(jnp.float32)
                       - self.lr * sent).astype(p._data.dtype)

    def clear_grad(self):
        for p in self._params:
            p.grad = None

    @property
    def _parameter_list(self):
        return self._params
