from .meta_parallel_base import MetaParallelBase
from .parallel_layers.mp_layers import (ColumnParallelLinear,
                                        ParallelCrossEntropy,
                                        RowParallelLinear,
                                        VocabParallelEmbedding)
from .parallel_layers.pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc
from .pipeline_parallel import PipelineParallel, PipelineParallelWithInterleave
from .tensor_parallel import TensorParallel
from .sharding.group_sharded import (GroupShardedOptimizerStage2,
                                     GroupShardedStage2, GroupShardedStage3,
                                     group_sharded_parallel)
