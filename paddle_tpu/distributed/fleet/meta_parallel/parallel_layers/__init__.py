from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy,
                        RowParallelLinear, VocabParallelEmbedding)
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc
from .random_ctrl import RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed
