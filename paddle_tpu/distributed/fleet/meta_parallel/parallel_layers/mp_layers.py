"""Megatron-style tensor-parallel layers
(ref: python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py).

TPU-native: instead of per-rank weight shards + hand-issued NCCL collectives
(_c_identity/_mp_allreduce), each layer holds the FULL logical parameter with a
PartitionSpec over the 'mp' mesh axis; GSPMD partitions the matmuls and emits
the identical collective pattern (allreduce after row-parallel, none after
column-parallel) over ICI. Eager single-device execution is dense, matching
the reference's mp_degree=1 path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from jax.sharding import PartitionSpec as P

from .....nn import functional as F
from .....nn import initializer as I
from .....nn.layer.layers import Layer
from .....tensor.tensor import _run_op
from ....sharding_utils import active_mesh, hint, hint_tensor
from ...topology import get_hybrid_communicate_group


def _mp_degree():
    hcg = get_hybrid_communicate_group()
    return hcg.get_model_parallel_world_size() if hcg else 1


def _overlap_cm():
    """The collective_matmul module when the overlap applies to this trace
    (switch on AND a mesh is active), else None."""
    from .....parallel import collective_matmul as cm
    if not cm.overlap_enabled():
        return None
    if active_mesh() is None:
        return None
    return cm


def _overlap_plan(kind, x, weight):
    """Collective-matmul plan for this call, or None for the fused GSPMD
    path (overlap off / eager / mp==1 / sub-MXU chunks — see
    parallel/collective_matmul.py gates)."""
    from .....amp import state as amp_state
    cm = _overlap_cm()
    if cm is None:
        return None
    plan_fn = (cm.plan_row_parallel if kind == "row"
               else cm.plan_column_parallel)
    plan = plan_fn(tuple(x.shape), tuple(weight.shape), active_mesh())
    if plan is None:
        return None

    def apply(a, w):
        # same O1 autocast F.linear applies — the ring kernels (and their
        # custom VJPs) need uniform operand dtypes
        a, w = amp_state.maybe_autocast_pair(a, w)
        return plan(a, w)

    return apply


def fused_ffn_plan(x, w_cols, w_row, activation, col_bias=False,
                   batch_axis="dp"):
    """Single-island column->activation->row plan that never gathers the
    intermediate activation (see collective_matmul.plan_fused_ffn), with the
    same O1 autocast F.linear applies, or None for the fused GSPMD path.
    Returned apply takes (x, w_cols tuple, w_row, b_cols tuple)."""
    from .....amp import state as amp_state
    cm = _overlap_cm()
    if cm is None:
        return None
    plan = cm.plan_fused_ffn(tuple(x.shape), tuple(w_cols[0].shape),
                             tuple(w_row.shape), active_mesh(),
                             n_cols=len(w_cols), activation=activation,
                             col_bias=col_bias, batch_axis=batch_axis)
    if plan is None:
        return None

    def apply(a, cols, row, b_cols=()):
        a, row = amp_state.maybe_autocast_pair(a, row)
        cols = tuple(amp_state.maybe_autocast(w) for w in cols)
        if amp_state.autocast_enabled():
            b_cols = tuple(b.astype(a.dtype) for b in b_cols)
        return plan(a, cols, row, b_cols)

    return apply


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over 'mp'."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        self.weight.pspec = P("mp", None)
        self.weight.is_distributed = _mp_degree() > 1
        self.weight.split_axis = 0

    def forward(self, x):
        cm = _overlap_cm()
        if cm is not None:
            # masked local lookup + chunked reduce ring (exact: each token's
            # row is non-zero on exactly one vocab shard)
            plan = cm.plan_vocab_parallel_embedding(
                tuple(x.shape), tuple(self.weight.shape), active_mesh())
            if plan is not None:
                return _run_op("vocab_embed_overlap", plan,
                               (x, self.weight), {})
        out = F.embedding(x, self.weight)
        return hint_tensor(out, None, None, None)  # replicated activations


class ColumnParallelLinear(Layer):
    """Linear with out_features sharded over 'mp' (ref: fused QKV / MLP-up)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.pspec = P(None, "mp")
        self.weight.is_distributed = _mp_degree() > 1
        self.weight.split_axis = 1
        if has_bias is None:
            has_bias = True
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.pspec = P("mp")
            self.bias.is_distributed = self.weight.is_distributed

    def forward(self, x):
        if self.gather_output:
            # decomposed matmul + all-gather: the weight shards ride a
            # ppermute ring, each hop's transfer hidden behind the previous
            # column block's matmul
            plan = _overlap_plan("column", x, self.weight)
            if plan is not None:
                out = _run_op("column_parallel_overlap", plan,
                              (x, self.weight), {})
                return out + self.bias if self.bias is not None else out
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return hint_tensor(out, *([None] * out.ndim))
        # keep last dim sharded over mp
        spec = [None] * (out.ndim - 1) + ["mp"]
        return hint_tensor(out, *spec)


class RowParallelLinear(Layer):
    """Linear with in_features sharded over 'mp'; output is allreduced
    (GSPMD inserts the psum from the contraction)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.pspec = P("mp", None)
        self.weight.is_distributed = _mp_degree() > 1
        self.weight.split_axis = 0
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.pspec = P()

    def forward(self, x):
        # decomposed matmul + all-reduce: partial matmuls ride a
        # reduce-scatter ppermute ring, then a ring all-gather — every hop
        # overlaps the next row chunk's compute
        plan = _overlap_plan("row", x, self.weight)
        if plan is not None:
            out = _run_op("row_parallel_overlap", plan, (x, self.weight), {})
            return out + self.bias if self.bias is not None else out
        if self.input_is_parallel:
            spec = [None] * (x.ndim - 1) + ["mp"]
            x = hint_tensor(x, *spec)
        out = F.linear(x, self.weight, self.bias)
        # replicate output -> forces the partial-sum allreduce over mp
        return hint_tensor(out, *([None] * out.ndim))


class ParallelCrossEntropy(Layer):
    """Softmax CE over mp-sharded logits
    (ref: mp_ops._c_softmax_with_cross_entropy). The fp32 logsumexp reduction
    over the sharded vocab axis becomes an ICI psum under GSPMD."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        cm = _overlap_cm()
        ring = (cm.plan_parallel_cross_entropy(tuple(input.shape),
                                               active_mesh())
                if cm is not None else None)
        if ring is not None:
            # per-rank (max, sumexp, picked) stats ride a chunked gather
            # ring — [n, t, 3] on the wire instead of replicated logits
            def f(logits, lbl):
                idx = lbl.astype(jnp.int32)
                if idx.ndim == logits.ndim:
                    idx = jnp.squeeze(idx, -1)
                loss = ring(logits, idx)[..., None]
                if self.ignore_index >= 0:
                    loss = jnp.where((idx == self.ignore_index)[..., None],
                                     0.0, loss)
                return loss
            return _run_op("parallel_cross_entropy_overlap", f,
                           (input, label), {})

        def f(logits, lbl):
            spec = [None] * (logits.ndim - 1) + ["mp"]
            logits = hint(logits, *spec)
            l32 = logits.astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(l32, axis=-1, keepdims=True)
            idx = lbl.astype(jnp.int32)
            if idx.ndim == logits.ndim:
                idx = jnp.squeeze(idx, -1)
            picked = jnp.take_along_axis(l32, idx[..., None], axis=-1)
            loss = (lse - picked).squeeze(-1)[..., None]
            if self.ignore_index >= 0:
                loss = jnp.where((idx == self.ignore_index)[..., None], 0.0, loss)
            return loss
        return _run_op("parallel_cross_entropy", f, (input, label), {})


# reference's low-level mp_ops surface, as sharding-constraint equivalents
def _c_identity(tensor, group=None):
    return tensor


def _mp_allreduce(tensor, group=None, use_calc_stream=True, use_model_parallel=True):
    return hint_tensor(tensor, *([None] * tensor.ndim))


def _c_split(tensor, group=None):
    spec = [None] * (tensor.ndim - 1) + ["mp"]
    return hint_tensor(tensor, *spec)


def _c_concat(tensor, group=None):
    return hint_tensor(tensor, *([None] * tensor.ndim))
