"""Pipeline layer description (ref:
python/paddle/distributed/fleet/meta_parallel/parallel_layers/pp_layers.py).

Same API: a PipelineLayer is a list of LayerDescs segmented into stages.
TPU-native difference: a single controller owns ALL stages (no per-rank
construction), so forward() works dense, and the compiled pipeline engine
(paddle_tpu.parallel.pipeline) consumes the per-stage segmentation to build
the shard_map/ppermute schedule with stage params stacked over the 'pp' axis.
"""
from __future__ import annotations

import math
import re
from typing import Callable, List

from .....nn.layer.layers import Layer
from .....nn.layer.container import LayerList
from ...topology import get_hybrid_communicate_group


class LayerDesc:
    def __init__(self, layer_class, *inputs, **kwargs):
        self.layer_class = layer_class
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_class, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_class(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_class.__name__})"


class SharedLayerDesc(LayerDesc):
    """Tied layers (e.g. embedding shared with the LM head across first/last
    stage). The single-controller design makes weight tying literal object
    sharing — no cross-stage grad allreduce needed (the tape accumulates both
    uses), unlike the reference's _broadcast_shared_weights."""

    def __init__(self, key, layer_class, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_class, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 recompute_ctx=None, num_virtual_pipeline_stages=None):
        super().__init__()
        self._layer_descs = list(layers)
        hcg = get_hybrid_communicate_group()
        if num_stages is None:
            num_stages = hcg.get_pipe_parallel_world_size() if hcg else 1
        self._num_stages = num_stages
        self._num_virtual_stages = num_virtual_pipeline_stages or 1
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        self._shared_layers = {}

        built = []
        for desc in self._layer_descs:
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name in self._shared_layers:
                    layer = self._shared_layers[desc.layer_name]
                else:
                    layer = desc.build_layer()
                    self._shared_layers[desc.layer_name] = layer
                built.append((layer, desc.forward_func))
            elif isinstance(desc, LayerDesc):
                built.append((desc.build_layer(), None))
            elif isinstance(desc, Layer):
                built.append((desc, None))
            elif callable(desc):
                built.append((desc, None))
            else:
                raise TypeError(f"invalid pipeline layer desc: {desc!r}")
        self._built = built
        self.run_function = LayerList(
            [l for l, _ in built if isinstance(l, Layer)])
        self._segment(seg_method)

    def _segment(self, seg_method):
        n = len(self._built)
        # with interleaving the layer list splits into S*V chunks; chunk c is
        # hosted by stage c % S (Megatron round-robin layout, ref:
        # pp_layers.py _segment_network_for_interleave)
        s = self._num_stages * self._num_virtual_stages
        if seg_method.startswith("layer:"):
            # segment at layers whose class name matches
            pat = seg_method.split(":", 1)[1]
            marks = [0] + [i for i, (l, _) in enumerate(self._built)
                           if type(l).__name__ == pat]
            # choose s boundaries as evenly as possible among marks
            if len(marks) >= s:
                chosen = [marks[int(i * len(marks) / s)] for i in range(s)]
            else:
                chosen = marks + [n] * (s - len(marks))
            bounds = sorted(set(chosen)) + [n]
            while len(bounds) < s + 1:
                bounds.insert(-1, bounds[-2])
        else:  # uniform
            per = n / s
            bounds = [int(round(i * per)) for i in range(s + 1)]
        self.segment_parts = bounds
        self._chunks = [
            self._built[bounds[i]:bounds[i + 1]] for i in range(s)]
        if self._num_virtual_stages == 1:
            self._stage_layers = self._chunks
        else:
            # stage_layers[s] = its V chunks in pipeline order
            self._stage_layers = [self.get_model_chunks(st)
                                  for st in range(self._num_stages)]

    def get_model_chunks(self, stage_id=None):
        """Chunk list (interleave): all chunks, or this stage's V chunks."""
        if stage_id is None:
            return self._chunks
        return [self._chunks[c] for c in range(len(self._chunks))
                if c % self._num_stages == stage_id]

    # -- dense (non-pipelined) execution: numerically the ground truth ------
    def forward(self, x):
        for layer, fwd in self._built:
            if fwd is not None:
                x = fwd(layer, x)
            elif isinstance(layer, Layer):
                x = layer(x)
            else:
                x = layer(x)
        return x

    def get_stage_layers(self, stage_id):
        return self._stage_layers[stage_id]

    def get_num_stages(self):
        return self._num_stages

    def loss_fn(self, *args):
        return self._loss_fn(*args)

    def allreduce_shared_weight_gradients(self):
        # literal weight sharing on a single controller: tape already
        # accumulated both contributions; kept for API parity
        return None
