"""TP RNG control (ref: fleet/meta_parallel/parallel_layers/random.py):
re-exported from the framework generator, which implements the tracker."""
from .....framework.random import (RNGStatesTracker, get_rng_state_tracker,
                                   model_parallel_random_seed)
