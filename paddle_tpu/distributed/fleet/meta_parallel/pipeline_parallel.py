"""Pipeline-parallel engine (ref:
python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py).

``train_batch`` keeps the reference's contract: split the batch into
micro-batches, run forward/backward per micro-batch, accumulate grads, step.

Scheduling note (TPU-native): the reference interleaves micro-batches across
stage PROCESSES (1F1B) to hide p2p latency. Here all stages live in one SPMD
program; when the model is jit-compiled over a mesh with pp>1 the collective
pipeline schedule (paddle_tpu/parallel/pipeline.py: ppermute rotation +
bubble masking, grads via autodiff through the scan = 1F1B-equivalent
utilization M/(M+S-1)) is used. The eager path below is the numerically
identical micro-batch accumulation loop.
"""
from __future__ import annotations

from typing import Optional

from ....autograd import no_grad
from ....tensor.tensor import Tensor
from .meta_parallel_base import MetaParallelBase
from .parallel_layers.pp_layers import PipelineLayer


class PipelineParallel(MetaParallelBase):
    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        cfg = strategy.pipeline_configs if strategy is not None else {}
        self.micro_batch_size = cfg.get("micro_batch_size", 1)
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.total_loss = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _split_micro(self, data, n):
        if data is None:
            return [None] * n
        if isinstance(data, (list, tuple)):
            parts = [self._split_micro(d, n) for d in data]
            return [type(data)(p[i] for p in parts) for i in range(n)]
        b = data.shape[0]
        assert b % n == 0, f"batch {b} not divisible by accumulate_steps {n}"
        mb = b // n
        return [data[i * mb:(i + 1) * mb] for i in range(n)]

    _overlap_warned = False

    def forward_backward_pipeline(self, data, scaler=None):
        if not PipelineParallel._overlap_warned and \
                self._hcg is not None and \
                self._hcg.get_pipe_parallel_world_size() > 1:
            import warnings
            warnings.warn(
                "PipelineParallel.train_batch is running the EAGER "
                "micro-batch loop: numerically identical to 1F1B but with "
                "no stage overlap. For the pipelined schedule compile the "
                "step over the pp mesh (paddle_tpu.parallel.pipeline / "
                "models.llama.build_train_step with pp>1).",
                stacklevel=3)
            PipelineParallel._overlap_warned = True
        x, y = data
        n = self.accumulate_steps
        xs = self._split_micro(x, n)
        ys = self._split_micro(y, n)
        total = None
        for xi, yi in zip(xs, ys):
            out = self._layers(xi)
            loss = self._layers.loss_fn(out, yi) / n
            if scaler is not None:
                scaler.scale(loss).backward()
            else:
                loss.backward()
            total = loss.detach() if total is None else total + loss.detach()
        return total

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        self._layers.allreduce_shared_weight_gradients()
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    @no_grad()
    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        x, y = data
        out = self._layers(x)
        if compute_loss:
            return self._layers.loss_fn(out, y)
        return out


class PipelineParallelWithInterleave(PipelineParallel):
    """Virtual-stage interleaving (ref: same file). Under the SPMD collective
    schedule, interleaving corresponds to segmenting the layer list into
    v*pp chunks and cycling them through the mesh; the eager loop is
    numerically identical so this class shares train_batch."""

    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
