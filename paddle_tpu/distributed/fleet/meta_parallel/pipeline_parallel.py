"""Pipeline-parallel engine (ref:
python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py).

``train_batch`` keeps the reference's contract: split the batch into
micro-batches, run forward/backward per micro-batch, accumulate grads, step.

Scheduling note (TPU-native): the reference interleaves micro-batches across
stage PROCESSES (1F1B) to hide p2p latency. Here all stages live in one SPMD
program; when the model is jit-compiled over a mesh with pp>1 the collective
pipeline schedule (paddle_tpu/parallel/pipeline.py: ppermute rotation +
bubble masking, grads via autodiff through the scan = 1F1B-equivalent
utilization M/(M+S-1)) is used. The eager path below is the numerically
identical micro-batch accumulation loop.
"""
from __future__ import annotations

from typing import Optional

from ....autograd import no_grad
from ....tensor.tensor import Tensor
from .meta_parallel_base import MetaParallelBase
from .parallel_layers.pp_layers import PipelineLayer


class PipelineParallel(MetaParallelBase):
    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        cfg = strategy.pipeline_configs if strategy is not None else {}
        self.micro_batch_size = cfg.get("micro_batch_size", 1)
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.total_loss = None
        # compiled micro-batch step, built lazily on first train_batch
        # (None = untried, False = fell back to eager permanently)
        self._compiled_step = None
        self._compiled_opt = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _split_micro(self, data, n):
        if data is None:
            return [None] * n
        if isinstance(data, (list, tuple)):
            parts = [self._split_micro(d, n) for d in data]
            return [type(data)(p[i] for p in parts) for i in range(n)]
        b = data.shape[0]
        assert b % n == 0, f"batch {b} not divisible by accumulate_steps {n}"
        mb = b // n
        return [data[i * mb:(i + 1) * mb] for i in range(n)]

    _overlap_warned = False

    def forward_backward_pipeline(self, data, scaler=None):
        if not PipelineParallel._overlap_warned and \
                self._hcg is not None and \
                self._hcg.get_pipe_parallel_world_size() > 1:
            import warnings
            warnings.warn(
                "PipelineParallel.train_batch is running the EAGER "
                "micro-batch loop: numerically identical to 1F1B but with "
                "no stage overlap. For the pipelined schedule compile the "
                "step over the pp mesh (paddle_tpu.parallel.pipeline / "
                "models.llama.build_train_step with pp>1).",
                stacklevel=3)
            PipelineParallel._overlap_warned = True
        x, y = data
        n = self.accumulate_steps
        xs = self._split_micro(x, n)
        ys = self._split_micro(y, n)
        total = None
        for xi, yi in zip(xs, ys):
            out = self._layers(xi)
            loss = self._layers.loss_fn(out, yi) / n
            if scaler is not None:
                scaler.scale(loss).backward()
            else:
                loss.backward()
            total = loss.detach() if total is None else total + loss.detach()
        return total

    def _build_compiled_step(self, optimizer):
        """COMPILED micro-batch schedule (r5, VERDICT #8): one jitted
        program per train_batch — the micro-batches run as a lax.scan
        with grad accumulation (jit/train_step.py), params/opt state
        live sharded over hcg.mesh (TP from param pspecs), and XLA
        schedules/overlaps the whole step. The reference's 1F1B exists
        to overlap p2p between stage PROCESSES; under single-controller
        SPMD the compiled step is the equivalent — the eager per-micro-
        batch python loop below is only the fallback for untraceable
        models or scaler-driven loss scaling."""
        from jax.sharding import PartitionSpec as P

        from ....jit.train_step import TrainStep
        mesh = getattr(self._hcg, "mesh", None) if self._hcg else None
        batch_spec = None
        if mesh is not None and \
                self._hcg.get_data_parallel_world_size() > 1:
            batch_spec = P("dp")
        return TrainStep(self._layers, self._layers.loss_fn, optimizer,
                         mesh=mesh, batch_spec=batch_spec,
                         grad_accum=self.accumulate_steps)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        self._layers.train()
        ran_compiled = getattr(self, "_compiled_ran", False)
        if scaler is not None and ran_compiled:
            # the compiled step's optimizer state lives in TrainStep; a
            # mid-training switch to the eager scaler path would step
            # stale moments — refuse loudly rather than diverge
            raise RuntimeError(
                "PipelineParallel.train_batch: a GradScaler was passed "
                "after compiled steps already ran; pass the scaler from "
                "the FIRST call (the scaler path uses the eager loop)")
        if scaler is None and self._compiled_step is not False:
            if ran_compiled and self._compiled_opt is not optimizer:
                # rebuilding TrainStep would seed FRESH (zero) Adam
                # moments — a silent mid-training reset
                raise RuntimeError(
                    "PipelineParallel.train_batch: a different optimizer "
                    "object was passed after compiled steps already ran; "
                    "keep passing the same optimizer (its state lives in "
                    "the compiled step)")
            if getattr(self, "_eager_ran", False):
                # moments accumulated in the eager optimizer would be
                # silently dropped by a fresh compiled step
                raise RuntimeError(
                    "PipelineParallel.train_batch: earlier steps ran the "
                    "eager (scaler) path; mixing in the compiled path "
                    "would discard the optimizer moments accumulated "
                    "there — keep passing the scaler for the whole run")
            # the try covers ONLY build + the compiled update: failures
            # after the update applied (sync, lr step) must propagate,
            # not double-apply the batch through the eager path
            step_ok = False
            try:
                if self._compiled_step is None or \
                        self._compiled_opt is not optimizer:
                    self._compiled_step = self._build_compiled_step(
                        optimizer)
                    self._compiled_opt = optimizer
                x, y = data
                loss = self._compiled_step(x, labels=y)
                step_ok = True
            except Exception as e:
                if ran_compiled:
                    # moments live in TrainStep — a silent eager
                    # fallback mid-training would train on stale state
                    raise
                import warnings
                warnings.warn(
                    "PipelineParallel.train_batch could not compile the "
                    f"micro-batch schedule ({type(e).__name__}: {e}); "
                    "falling back to the eager per-micro-batch loop "
                    "(numerically identical, no stage overlap)",
                    stacklevel=2)
                self._compiled_step = False
            if step_ok:
                self._compiled_ran = True
                # keep the Layer objects coherent for state_dict/eager
                # reads (device-array rebinds, no host transfer)
                self._compiled_step.sync_to_model()
                if lr_scheduler is not None:
                    lr_scheduler.step()
                return loss
        self._eager_ran = True
        loss = self.forward_backward_pipeline(data, scaler)
        self._layers.allreduce_shared_weight_gradients()
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    @no_grad()
    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        x, y = data
        out = self._layers(x)
        if compute_loss:
            return self._layers.loss_fn(out, y)
        return out


class PipelineParallelWithInterleave(PipelineParallel):
    """Virtual-stage interleaving (ref: same file). Under the SPMD collective
    schedule, interleaving corresponds to segmenting the layer list into
    v*pp chunks and cycling them through the mesh; the eager loop is
    numerically identical so this class shares train_batch."""

    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
