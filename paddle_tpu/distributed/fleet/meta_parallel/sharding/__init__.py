from .group_sharded import (GroupShardedOptimizerStage2, GroupShardedStage2,
                            GroupShardedStage3, group_sharded_parallel,
                            save_group_sharded_model)
