"""GroupSharded / ZeRO stages 1-3 (ref:
python/paddle/distributed/fleet/meta_parallel/sharding/group_sharded_stage{2,3}.py
and python/paddle/distributed/sharding/group_sharded.py).

TPU-native design: ZeRO is a SHARDING RULE, not a runtime protocol. The
reference manually allgathers param shards before each layer (stage 3) and
reduce-scatters grads (stage 2/3) on NCCL streams. Under GSPMD the same
communication pattern falls out of annotating:

  stage 1 (os):     optimizer states sharded over 'sharding'
  stage 2 (os_g):   + gradients reduce-scattered (XLA does this automatically
                    when the update is computed on sharded states)
  stage 3 (p_g_os): + parameters themselves sharded over 'sharding'; XLA
                    inserts the per-layer allgather before use and frees the
                    gathered buffer after (the same gather/free the reference
                    hand-schedules), overlapped by the scheduler.

``group_sharded_parallel`` attaches the PartitionSpecs; the compiled TrainStep
(jit/train_step.py) places arrays accordingly.
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from .....nn.layer.layers import Layer


def _largest_dim(shape):
    if not shape:
        return None
    return max(range(len(shape)), key=lambda i: shape[i])


def _shard_spec_for(shape, base_spec, axis="sharding", degree=None):
    """Shard the largest eligible dim over the sharding axis, composing
    with an existing (e.g. mp) spec. With a known degree only dims whose
    size divides evenly are eligible, falling through to the next largest
    — an uneven shard is silently padded by GSPMD, wasting memory exactly
    where ZeRO exists to save it. No divisible dim → left unsharded."""
    shape = tuple(shape)
    existing = list(base_spec or [None] * len(shape))
    while len(existing) < len(shape):
        existing.append(None)
    candidates = sorted(range(len(shape)), key=lambda i: -shape[i])
    for i in candidates:
        if existing[i] is not None:
            continue
        if degree is not None and shape[i] % degree != 0:
            continue
        existing[i] = axis
        return P(*existing)
    return P(*existing)


def mesh_resolved_spec(param, mesh, axis="sharding"):
    """Placement-time re-derivation of a param's ZeRO spec with the TRUE
    degree (the mesh is usually unknown at group_sharded_parallel time).
    Recomputes from the pre-ZeRO base spec so divisibility is enforced
    against mesh.shape[axis]."""
    spec = getattr(param, "opt_state_pspec", None)
    if spec is None or mesh is None or axis not in dict(mesh.shape):
        return spec
    if not hasattr(param, "_pre_gs_pspec"):
        # opt_state_pspec set directly by the user, not by the ZeRO
        # attach path: honor it verbatim
        return spec
    return _shard_spec_for(tuple(param._data.shape),
                           getattr(param, "_pre_gs_pspec", None),
                           axis=axis, degree=int(mesh.shape[axis]))


def group_sharded_parallel(model: Layer, optimizer, level: str,
                           scaler=None, group=None, offload=False,
                           sync_buffers=False, buffer_max_size=2**23,
                           segment_size=2**20, sync_comm=False,
                           dp_group=None, exclude_layer=None):
    """Attach ZeRO sharding specs (ref: python/paddle/distributed/sharding/).

    level: 'os' (stage 1) | 'os_g' (stage 2) | 'p_g_os' (stage 3)
    """
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(f"invalid group_sharded level: {level}")
    degree = group.nranks if group is not None else None
    for p in model.parameters():
        if p.stop_gradient:
            continue
        base = getattr(p, "pspec", None)
        p._pre_gs_pspec = base  # lets TrainStep re-derive with the mesh degree
        spec = _shard_spec_for(tuple(p._data.shape), base, degree=degree)
        # stage 1/2: only optimizer state (and grads) shard; stage 3: params too
        p.opt_state_pspec = spec
        if level == "p_g_os":
            p.pspec = spec
        p.sharding_level = level
    optimizer._sharding_level = level
    model._group_sharded_level = level
    # stage-3 prefetch bucket cap (jit/train_step param_gather buckets):
    # reuse the reference's comm buffer knob — buffer_max_size caps how many
    # param bytes one prefetched all-gather bucket carries
    model._gs_buffer_bytes = int(buffer_max_size)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    import os
    from .....framework.io import save
    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))


class GroupShardedStage2:
    """API-parity alias: stage-2 wrapping is sharding-rule attachment."""

    def __new__(cls, model, optimizer=None, group=None, **kw):
        model, _, _ = group_sharded_parallel(model, optimizer, "os_g",
                                             group=group)
        return model


class GroupShardedStage3:
    def __new__(cls, model, optimizer=None, group=None, **kw):
        model, _, _ = group_sharded_parallel(model, optimizer, "p_g_os",
                                             group=group)
        return model


class GroupShardedOptimizerStage2:
    def __new__(cls, params, optim, group=None, **kw):
        optim._sharding_level = "os_g"
        return optim
