"""TensorParallel model wrapper (ref:
python/paddle/distributed/fleet/meta_parallel/tensor_parallel.py).

The reference broadcasts non-distributed params across mp ranks and seeds the
TP RNG tracker. Single-controller: params are shared by construction; this
wrapper seeds the tracker and records which params carry mp shardings."""
from __future__ import annotations

from ....framework import random as random_mod
from .meta_parallel_base import MetaParallelBase


class TensorParallel(MetaParallelBase):
    def _prepare_for_model(self):
        mp_rank = self._hcg.get_model_parallel_rank() if self._hcg else 0
        random_mod.model_parallel_random_seed(
            seed_=random_mod._GLOBAL.seed, mp_rank=mp_rank)
