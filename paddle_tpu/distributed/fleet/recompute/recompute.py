"""Activation recomputation (ref: python/paddle/distributed/fleet/recompute/recompute.py).

Eager path: a PyLayer that drops intermediate activations and re-runs the
forward during backward, with RNG state capture for deterministic dropout
(the reference's RNGStatesTracker dance). Compiled path: layers wrapped with
``jax.checkpoint`` — XLA's native remat, strictly better on TPU.
"""
from __future__ import annotations

from ....autograd import engine
from ....autograd.py_layer import PyLayer
from ....framework import random as random_mod
from ....tensor.tensor import Tensor


class _RecomputeFunction(PyLayer):
    @staticmethod
    def forward(ctx, run_function, preserve_rng_state, *args):
        ctx.run_function = run_function
        ctx.preserve_rng = preserve_rng_state
        ctx.rng_state = random_mod.get_rng_state()
        ctx.inputs = args
        ctx.save_for_backward(*[a for a in args if isinstance(a, Tensor)])
        with engine.no_grad():
            out = run_function(*args)
        return out

    @staticmethod
    def backward(ctx, *grads):
        # re-run forward WITH the tape, under the saved RNG state
        saved_state = random_mod.get_rng_state()
        if ctx.preserve_rng:
            random_mod.set_rng_state(ctx.rng_state)
        detached = []
        tensor_inputs = []
        for a in ctx.inputs:
            if isinstance(a, Tensor):
                d = a.detach()
                d.stop_gradient = a.stop_gradient
                detached.append(d)
                tensor_inputs.append((a, d))
            else:
                detached.append(a)
        with engine.enable_grad():
            out = ctx.run_function(*detached)
        if ctx.preserve_rng:
            random_mod.set_rng_state(saved_state)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        diff_outs = [o for o in outs if isinstance(o, Tensor) and not o.stop_gradient]
        diff_grads = [g for o, g in zip(outs, grads)
                      if isinstance(o, Tensor) and not o.stop_gradient]
        # reentrant backward (torch-checkpoint style): engine.backward
        # accumulates into every reachable leaf — the module's PARAMETERS
        # (captured inside run_function, not passed as args) get their .grad
        # here, while the detached inputs collect the grads this PyLayer
        # must return. engine.grad would be wrong: it routes grads to a side
        # table and must not touch param .grad.
        for _, d in tensor_inputs:
            d.grad = None
        if engine.is_grad_enabled():
            # run_vjp_taped invoked us (create_graph double backward). The
            # reentrant scheme detaches its inputs, which severs the
            # second-order path to the caller's graph — same limitation as
            # the reference's (and torch's use_reentrant=True) checkpoint.
            raise RuntimeError(
                "recompute does not support double backward "
                "(create_graph=True): the recomputed forward runs on "
                "detached inputs. Compute gradient-penalty terms on a "
                "non-recomputed block instead.")
        engine.backward_multi(list(zip(diff_outs, diff_grads)),
                              retain_graph=True)
        return tuple(d.grad if not d.stop_gradient else None
                     for _, d in tensor_inputs)


def recompute(function, *args, **kwargs):
    preserve = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)
    if kwargs:
        raise ValueError(f"unsupported recompute kwargs: {list(kwargs)}")
    return _RecomputeFunction.apply(function, preserve, *args)


def recompute_sequential(ctx, functions, *args):
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    if isinstance(functions, (list, tuple)):
        funcs = list(functions)
    else:
        funcs = list(functions)
    n = len(funcs)
    per = max(n // max(segments, 1), 1)

    out = args
    for i in range(0, n, per):
        chunk = funcs[i:i + per]

        def run_chunk(*xs, _chunk=chunk):
            y = xs
            for f in _chunk:
                y = f(*y) if isinstance(y, tuple) else f(y)
                if not isinstance(y, tuple):
                    y = (y,)
            return y if len(y) > 1 else y[0]

        out = recompute(run_chunk, *out) if isinstance(out, tuple) \
            else recompute(run_chunk, out)
        if not isinstance(out, tuple):
            out = (out,)
    return out if len(out) > 1 else out[0]


def recompute_hybrid(ctx, function, *args, **kwargs):
    """mp-aware recompute (ref: recompute_hybrid.py): the RNG tracker keeps
    global/local dropout seeds consistent across the recomputation."""
    return recompute(function, *args, **kwargs)
