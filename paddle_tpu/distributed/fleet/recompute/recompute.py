"""Activation recomputation (ref: python/paddle/distributed/fleet/recompute/recompute.py).

Eager path: a PyLayer that drops intermediate activations and re-runs the
forward during backward, with RNG state capture for deterministic dropout
(the reference's RNGStatesTracker dance). Compiled path: layers wrapped with
``jax.checkpoint`` — XLA's native remat, strictly better on TPU.
"""
from __future__ import annotations

from ....autograd import engine
from ....autograd.py_layer import PyLayer
from ....framework import random as random_mod
from ....tensor.tensor import Tensor


class _RecomputeFunction(PyLayer):
    @staticmethod
    def forward(ctx, run_function, preserve_rng_state, *args):
        from ....amp import state as amp_state
        ctx.run_function = run_function
        ctx.preserve_rng = preserve_rng_state
        ctx.rng_state = random_mod.get_rng_state()
        # amp autocast is consulted at op-dispatch time; backward re-runs
        # the forward AFTER the auto_cast context has exited, so the
        # state must be captured here and re-applied during the re-run
        # (reference recompute does the same amp-state dance)
        ctx.amp_state = (amp_state._enabled, amp_state._dtype,
                         amp_state._level)
        ctx.inputs = args
        ctx.save_for_backward(*[a for a in args if isinstance(a, Tensor)])
        with engine.no_grad():
            out = run_function(*args)
        return out

    @staticmethod
    def backward(ctx, *grads):
        from ....amp import state as amp_state
        # re-run forward WITH the tape, under the saved RNG + AMP state
        saved_state = random_mod.get_rng_state()
        saved_amp = (amp_state._enabled, amp_state._dtype, amp_state._level)
        amp_state._enabled, amp_state._dtype, amp_state._level = ctx.amp_state
        if ctx.preserve_rng:
            random_mod.set_rng_state(ctx.rng_state)
        detached = []
        tensor_inputs = []
        for a in ctx.inputs:
            if isinstance(a, Tensor):
                d = a.detach()
                d.stop_gradient = a.stop_gradient
                detached.append(d)
                tensor_inputs.append((a, d))
            else:
                detached.append(a)
        try:
            with engine.enable_grad():
                out = ctx.run_function(*detached)
        finally:
            (amp_state._enabled, amp_state._dtype,
             amp_state._level) = saved_amp
        if ctx.preserve_rng:
            random_mod.set_rng_state(saved_state)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        diff_outs = [o for o in outs if isinstance(o, Tensor) and not o.stop_gradient]
        diff_grads = [g for o, g in zip(outs, grads)
                      if isinstance(o, Tensor) and not o.stop_gradient]
        # reentrant backward (torch-checkpoint style): engine.backward
        # accumulates into every reachable leaf — the module's PARAMETERS
        # (captured inside run_function, not passed as args) get their .grad
        # here, while the detached inputs collect the grads this PyLayer
        # must return. engine.grad would be wrong: it routes grads to a side
        # table and must not touch param .grad.
        for _, d in tensor_inputs:
            d.grad = None
        if engine.is_grad_enabled():
            # run_vjp_taped invoked us (create_graph double backward). The
            # reentrant scheme detaches its inputs, which severs the
            # second-order path to the caller's graph — same limitation as
            # the reference's (and torch's use_reentrant=True) checkpoint.
            raise RuntimeError(
                "recompute does not support double backward "
                "(create_graph=True): the recomputed forward runs on "
                "detached inputs. Compute gradient-penalty terms on a "
                "non-recomputed block instead.")
        engine.backward_multi(list(zip(diff_outs, diff_grads)),
                              retain_graph=True)
        return tuple(d.grad if not d.stop_gradient else None
                     for _, d in tensor_inputs)


def recompute(function, *args, **kwargs):
    preserve = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)
    if kwargs:
        raise ValueError(f"unsupported recompute kwargs: {list(kwargs)}")
    return _RecomputeFunction.apply(function, preserve, *args)


def recompute_sequential(ctx, functions, *args):
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    if isinstance(functions, (list, tuple)):
        funcs = list(functions)
    else:
        funcs = list(functions)
    n = len(funcs)
    per = max(n // max(segments, 1), 1)

    out = args
    for i in range(0, n, per):
        chunk = funcs[i:i + per]

        def run_chunk(*xs, _chunk=chunk):
            y = xs
            for f in _chunk:
                y = f(*y) if isinstance(y, tuple) else f(y)
                if not isinstance(y, tuple):
                    y = (y,)
            return y if len(y) > 1 else y[0]

        out = recompute(run_chunk, *out) if isinstance(out, tuple) \
            else recompute(run_chunk, out)
        if not isinstance(out, tuple):
            out = (out,)
    return out if len(out) > 1 else out[0]


def recompute_hybrid(ctx, function, *args, **kwargs):
    """mp-aware recompute (ref: recompute_hybrid.py): the RNG tracker keeps
    global/local dropout seeds consistent across the recomputation."""
    return recompute(function, *args, **kwargs)


def _tensor_leaf(x):
    return isinstance(x, Tensor)


def _recompute_dispatch(layer, orig, args, kwargs):
    """Run one checkpointed sublayer forward: the eager path uses the
    PyLayer tape recompute above; under a jax trace (functional_call /
    TrainStep, where params and activations wrap tracers) it instead
    wraps a PURE function of (arg arrays, param/buffer arrays) in
    ``jax.checkpoint`` so XLA's native remat lands in the compiled HLO
    — the strategy.recompute meta-optimizer's observable effect."""
    import jax
    import jax.core as jc

    def _is_tracer(x):
        return isinstance(getattr(x, "_data", x), jc.Tracer)

    flat, treedef = jax.tree_util.tree_flatten((args, kwargs),
                                               is_leaf=_tensor_leaf)
    traced = any(_is_tracer(x) for x in flat if isinstance(x, Tensor)) or \
        any(isinstance(p._data, jc.Tracer) for p in layer.parameters())
    if not traced:
        import functools as _ft
        fn = _ft.partial(orig, **kwargs) if kwargs else orig
        return recompute(fn, *args)

    is_t = [isinstance(x, Tensor) for x in flat]
    arg_arrs = [x._data for x, t in zip(flat, is_t) if t]
    params = list(layer.parameters())
    bufs = [b for _, b in layer.named_buffers() if b is not None]
    state = params + bufs
    s_arrs = [s._data for s in state]

    def pure(arg_arrs, s_arrs):
        saved = [s._data for s in state]
        it = iter(arg_arrs)
        re_flat = [Tensor._from_data(next(it)) if t else x
                   for x, t in zip(flat, is_t)]
        a2, k2 = jax.tree_util.tree_unflatten(treedef, re_flat)
        for s, a in zip(state, s_arrs):
            s._data = a
        try:
            out = orig(*a2, **k2)
            new_buf = [b._data for b in bufs]
        finally:
            for s, sv in zip(state, saved):
                s._data = sv
        out_arrs = jax.tree_util.tree_map(
            lambda x: x._data if isinstance(x, Tensor) else x, out,
            is_leaf=_tensor_leaf)
        return out_arrs, new_buf

    out_arrs, new_buf = jax.checkpoint(pure)(arg_arrs, s_arrs)
    for b, a in zip(bufs, new_buf):
        b._data = a

    def _wrap_out(x):
        import jax as _j
        if isinstance(x, _j.Array) or hasattr(x, "aval"):
            return Tensor._from_data(x)
        return x

    return jax.tree_util.tree_map(_wrap_out, out_arrs)


def attach_recompute(root, checkpoints=None):
    """Wrap sublayers of ``root`` so their forwards recompute in backward
    (the strategy.recompute meta-optimizer; ref: fleet/meta_optimizers/
    recompute_optimizer.py applies the static-graph rewrite — here the
    wrapper recomputes via PyLayer eagerly and via jax.checkpoint under
    the compiled trace).

    checkpoints: sublayer names from ``root.named_sublayers()`` (exact,
    or a trailing component like "block1"); EMPTY means every direct
    child holding parameters — the whole-layer default a dygraph user
    gets from wrapping each block manually. Returns the wrapped layer
    names (so callers/tests can see what was attached)."""
    import functools as _ft
    subs = dict(root.named_sublayers())
    chosen = {}
    if checkpoints:
        for want in checkpoints:
            hits = {n: l for n, l in subs.items()
                    if n == want or n.split(".")[-1] == want}
            if not hits:
                raise ValueError(
                    f"strategy.recompute checkpoint '{want}' names no "
                    f"sublayer; known: {sorted(subs)[:20]}")
            chosen.update(hits)
    else:
        # direct parameterized children — but containers (LayerList, or
        # any layer without its own forward) are transparent: wrapping
        # their never-called forward would be a silent no-op, so descend
        # into THEIR children instead (a GPT block list checkpoints each
        # block, not the list)
        from ....nn.layer.layers import Layer as _BaseLayer

        def collect(layer, prefix, out):
            for n, l in getattr(layer, "_sub_layers", {}).items():
                name = f"{prefix}.{n}" if prefix else n
                if type(l).forward is _BaseLayer.forward:
                    collect(l, name, out)
                elif any(True for _ in l.parameters()):
                    out[name] = l

        chosen = {}
        collect(root, "", chosen)
        if not chosen:
            raise ValueError(
                "strategy.recompute is on but the model has no "
                "parameterized direct children to checkpoint; set "
                "recompute_configs['checkpoints'] to sublayer names")
    for name, sub in chosen.items():
        if getattr(sub, "_recompute_wrapped", False):
            continue
        orig = sub.forward

        def fwd(*args, _layer=sub, _orig=orig, **kwargs):
            return _recompute_dispatch(_layer, _orig, args, kwargs)

        sub.forward = _ft.wraps(orig)(fwd)
        sub._recompute_wrapped = True
    return sorted(chosen)
