"""Hybrid-parallel topology (ref: python/paddle/distributed/fleet/base/topology.py).

The reference's ``HybridCommunicateGroup`` builds an N-D rank mesh with axis
order [dp, pp, sharding, sep, mp] and one NCCL communicator per axis. The
TPU-native equivalent builds ONE ``jax.sharding.Mesh`` over the physical
devices with the same named axes; "communicators" are just the axis names —
XLA emits the ICI collectives when sharded computations reference them.
Axis order matters for locality exactly like NCCL ring order did: mp (heaviest
traffic) is innermost so it maps to adjacent ICI neighbors, dp outermost.
An optional ep degree (expert parallel) reuses the sharding×sep×mp submesh.

Multi-host (DCN vs ICI): ``jax.devices()`` enumerates process-major, so the
OUTERMOST axes of the [dp, pp, sharding, sep, mp] order land across hosts —
dp's once-per-step gradient all-reduce rides the slow DCN link, while mp/sep
(per-layer collectives) stay on intra-host ICI. This is the same
dp-outer-over-nodes placement the reference's HybridCommunicateGroup
produces with its rank-ordered NCCL subgroups. Proven end-to-end by
tests/test_multihost.py (two jax.distributed processes, dp over hosts,
mp within, loss equal to serial).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from ..communication import Group

_AXIS_ORDER = ["dp", "pp", "sharding", "sep", "mp"]


def _pick_devices(n: int):
    """Choose n devices: accelerators if enough, else host CPU devices."""
    devs = jax.devices()
    accel = [d for d in devs if d.platform != "cpu"]
    if len(accel) >= n:
        return accel[:n]
    cpus = jax.devices("cpu")
    if len(cpus) >= n:
        return cpus[:n]
    if n == 1:
        return devs[:1]
    raise ValueError(
        f"need {n} devices for the hybrid topology but only "
        f"{len(accel)} accelerator / {len(cpus)} cpu devices exist "
        "(set XLA_FLAGS=--xla_force_host_platform_device_count=N for testing)")


class CommunicateTopology:
    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = hybrid_group_names or _AXIS_ORDER
        self._dims = dims or [1] * len(self._parallel_names)
        self._world_size = int(np.prod(self._dims))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    def world_size(self):
        return self._world_size


class HybridCommunicateGroup:
    def __init__(self, topology: CommunicateTopology = None, *,
                 dp_degree=1, mp_degree=1, pp_degree=1, sharding_degree=1,
                 sep_degree=1, ep_degree=1, devices=None):
        if topology is not None:
            dims = {n: topology.get_dim(n) for n in topology.get_hybrid_group_names()}
            dp_degree = dims.get("dp", 1)
            pp_degree = dims.get("pp", 1)
            sharding_degree = dims.get("sharding", 1)
            sep_degree = dims.get("sep", 1)
            mp_degree = dims.get("mp", 1)
        self._dp_degree = dp_degree
        self._mp_degree = mp_degree
        self._pp_degree = pp_degree
        self._sharding_degree = sharding_degree
        self._sep_degree = sep_degree
        self._ep_degree = ep_degree
        total = dp_degree * mp_degree * pp_degree * sharding_degree * sep_degree
        if ep_degree > 1 and ep_degree > sharding_degree * sep_degree * mp_degree:
            raise ValueError(
                f"ep_degree {ep_degree} must divide into the non-dp/pp submesh "
                f"(sharding*sep*mp = {sharding_degree * sep_degree * mp_degree})")
        self.nranks = total
        devs = list(devices) if devices is not None else _pick_devices(total)
        dev_array = np.array(devs[:total]).reshape(
            dp_degree, pp_degree, sharding_degree, sep_degree, mp_degree)
        self.mesh = Mesh(dev_array, axis_names=tuple(_AXIS_ORDER))
        self.global_rank = 0  # single controller

    # -- degree / rank queries (reference API surface) ---------------------
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_expert_parallel_world_size(self):
        return self._ep_degree

    # In SPMD there is no per-process rank; ranks are symbolic (axis_index
    # inside compiled code). These return 0 for host-side logic, like rank 0.
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    # -- groups ------------------------------------------------------------
    def get_data_parallel_group(self) -> Group:
        return Group("dp", self._dp_degree)

    def get_model_parallel_group(self) -> Group:
        return Group("mp", self._mp_degree)

    def get_pipe_parallel_group(self) -> Group:
        return Group("pp", self._pp_degree)

    def get_sharding_parallel_group(self) -> Group:
        return Group("sharding", self._sharding_degree)

    def get_sep_parallel_group(self) -> Group:
        return Group("sep", self._sep_degree)

    def get_expert_parallel_group(self) -> Group:
        return Group("ep", self._ep_degree)

    def get_check_parallel_group(self, *a, **k) -> Group:
        return Group("mp", self._mp_degree)

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_group_src_rank(self):
        return 0

    # -- pipeline helpers --------------------------------------------------
    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    def get_rank_from_stage(self, stage_id, **kwargs):
        return stage_id

    def topology(self):
        return CommunicateTopology(_AXIS_ORDER,
                                   [self._dp_degree, self._pp_degree,
                                    self._sharding_degree, self._sep_degree,
                                    self._mp_degree])


_hcg: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    global _hcg
    _hcg = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg
