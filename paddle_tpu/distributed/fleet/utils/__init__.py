from . import hybrid_parallel_util, sequence_parallel_utils
