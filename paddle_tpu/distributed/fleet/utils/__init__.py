from . import hybrid_parallel_util, sequence_parallel_utils
from ..recompute.recompute import recompute  # noqa: F401
