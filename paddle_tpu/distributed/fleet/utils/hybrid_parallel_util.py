"""Hybrid-parallel grad utilities (ref:
python/paddle/distributed/fleet/utils/hybrid_parallel_util.py).

The reference fuses DP/sharding grad allreduces into buckets overlapping
backward. Single-controller SPMD: grads of replicated params are already
globally correct inside a compiled step (XLA inserts the psum); these helpers
keep the eager API surface working (identity on one controller, with the mp
partial-grad allreduce expressed as a sharding hint)."""
from __future__ import annotations

from ....tensor.tensor import Tensor
from ...communication import all_reduce


def fused_allreduce_gradients(parameter_list, hcg):
    group = hcg.get_data_parallel_group() if hcg else None
    if group is None or group.nranks <= 1:
        return
    for p in parameter_list:
        if p.grad is not None:
            p.grad = all_reduce(p.grad, group=group)


def broadcast_input_data(hcg, *inputs, **kwargs):
    return inputs, kwargs


def broadcast_mp_parameters(model, hcg):
    return None  # single controller: one copy of every parameter


def broadcast_dp_parameters(model, hcg):
    return None


def broadcast_sharding_parameters(model, hcg):
    return None


def sharding_reduce_gradients(parameter_list, hcg):
    return None
