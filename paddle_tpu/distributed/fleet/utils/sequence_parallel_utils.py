"""Megatron-style sequence parallelism
(ref: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py).

In the TP region, activations are sharded along the sequence dim over 'mp'
(saving activation memory ∝ mp_degree): allgather before attention/MLP
matmuls, reduce-scatter after. Under GSPMD these are sharding constraints —
ScatterOp/GatherOp below pin the seq dim sharding and XLA emits the
all-gather / reduce-scatter pair over ICI.
"""
from __future__ import annotations

from ....tensor.tensor import Tensor
from ...sharding_utils import hint_tensor
from ..topology import get_hybrid_communicate_group


def mark_as_sequence_parallel_parameter(parameter):
    """Params of seq-parallel layers (LayerNorm in the SP region): their grads
    are partial over mp and need an allreduce. Under GSPMD the replicated
    param spec forces that psum automatically; the marker is kept so
    register_sequence_parallel_allreduce_hooks remains API-compatible."""
    parameter.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_allreduce=True):
    return None  # GSPMD emits the allreduce from the sharding specs


class ScatterOp:
    """Scatter activation along seq dim over 'mp' (enter the SP region)."""

    @staticmethod
    def apply(x):
        # layout [B, S, H]: shard S over mp
        spec = [None, "mp"] + [None] * (x.ndim - 2)
        return hint_tensor(x, *spec)


class GatherOp:
    """Gather activation along seq dim (leave the SP region)."""

    @staticmethod
    def apply(x):
        return hint_tensor(x, *([None] * x.ndim))


class AllGatherOp(GatherOp):
    pass


class ReduceScatterOp(ScatterOp):
    pass


def scatter(x):
    return ScatterOp.apply(x)


def all_gather(x):
    return GatherOp.apply(x)


class ColumnSequenceParallelLinear:
    """Column-parallel linear consuming seq-sharded input (allgather happens
    at the matmul via GSPMD when the weight is mp-column-sharded)."""

    def __new__(cls, *args, **kwargs):
        from ..meta_parallel.parallel_layers.mp_layers import ColumnParallelLinear
        layer = ColumnParallelLinear(*args, **kwargs)
        return layer


class RowSequenceParallelLinear:
    def __new__(cls, *args, **kwargs):
        from ..meta_parallel.parallel_layers.mp_layers import RowParallelLinear
        layer = RowParallelLinear(*args, **kwargs)
        orig_forward = layer.forward

        def forward(x):
            out = orig_forward(x)
            return ScatterOp.apply(out)

        layer.forward = forward
        return layer
