"""Megatron-style sequence parallelism
(ref: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py).

In the TP region, activations are sharded along the sequence dim over 'mp'
(saving activation memory ∝ mp_degree): allgather before attention/MLP
matmuls, reduce-scatter after. Under GSPMD these are sharding constraints —
ScatterOp/GatherOp below pin the seq dim sharding and XLA emits the
all-gather / reduce-scatter pair over ICI.
"""
from __future__ import annotations

from ....tensor.tensor import Tensor
from ...sharding_utils import hint_tensor
from ..topology import get_hybrid_communicate_group


def mark_as_sequence_parallel_parameter(parameter):
    """Params of seq-parallel layers (LayerNorm in the SP region): their grads
    are partial over mp and need an allreduce. Under GSPMD the replicated
    param spec forces that psum automatically; the marker is kept so
    register_sequence_parallel_allreduce_hooks remains API-compatible."""
    parameter.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_allreduce=True):
    return None  # GSPMD emits the allreduce from the sharding specs


class ScatterOp:
    """Scatter activation along seq dim over 'mp' (enter the SP region)."""

    @staticmethod
    def apply(x):
        # layout [B, S, H]: shard S over mp
        spec = [None, "mp"] + [None] * (x.ndim - 2)
        return hint_tensor(x, *spec)


class GatherOp:
    """Gather activation along seq dim (leave the SP region)."""

    @staticmethod
    def apply(x):
        return hint_tensor(x, *([None] * x.ndim))


class AllGatherOp(GatherOp):
    pass


class ReduceScatterOp(ScatterOp):
    pass


def scatter(x):
    return ScatterOp.apply(x)


def all_gather(x):
    return GatherOp.apply(x)


class ColumnSequenceParallelLinear:
    """Column-parallel linear consuming seq-sharded input (allgather happens
    at the matmul via GSPMD when the weight is mp-column-sharded)."""

    def __new__(cls, *args, **kwargs):
        from ..meta_parallel.parallel_layers.mp_layers import ColumnParallelLinear
        layer = ColumnParallelLinear(*args, **kwargs)
        return layer


class RowSequenceParallelLinear:
    def __new__(cls, *args, **kwargs):
        from ..meta_parallel.parallel_layers.mp_layers import RowParallelLinear
        layer = RowParallelLinear(*args, **kwargs)
        orig_forward = layer.forward

        def forward(x):
            out = orig_forward(x)
            return ScatterOp.apply(out)

        layer.forward = forward
        return layer


def fused_sequence_parallel_ffn(column_layer, row_layer, x, activation=None):
    """Run a Column->activation->Row SP pair as ONE collective-matmul island
    when the overlap applies: the column matmul, (sharded) column bias and
    activation stay on the mp shard, the row matmul rides the chunked reduce
    ring, and the intermediate [B, S, I] activation is never gathered. The
    output re-enters the SP region via ScatterOp, like
    RowSequenceParallelLinear. Falls back to ``row(activation(column(x)))``
    through the individual layers (which carry their own overlap plans)
    whenever the fused plan doesn't apply."""
    from ..meta_parallel.parallel_layers.mp_layers import fused_ffn_plan
    from ....parallel.collective_matmul import gelu_tanh
    from ....tensor.tensor import _run_op
    act = activation if activation is not None else gelu_tanh
    plan = fused_ffn_plan(x, (column_layer.weight,), row_layer.weight, act,
                          col_bias=column_layer.bias is not None)
    if plan is not None:
        if column_layer.bias is not None:
            def f(a, w_in, b_in, w_out):
                return plan(a, (w_in,), w_out, (b_in,))
            args = (x, column_layer.weight, column_layer.bias,
                    row_layer.weight)
        else:
            def f(a, w_in, w_out):
                return plan(a, (w_in,), w_out)
            args = (x, column_layer.weight, row_layer.weight)
        out = _run_op("fused_ffn_overlap", f, args, {})
        if row_layer.bias is not None:
            out = out + row_layer.bias
        return ScatterOp.apply(out)
    h = column_layer(x)
    h = _run_op("ffn_activation", act, (h,), {})
    return ScatterOp.apply(row_layer(h))
