"""Distributed launcher (ref: python/paddle/distributed/launch/).

``python -m paddle_tpu.distributed.launch [opts] train.py [args...]``

TPU-native process model: ONE process per host joins the SPMD program (jax
single-controller; devices on the host all belong to that process), unlike the
reference's one-proc-per-GPU. ``--nproc_per_node`` therefore defaults to 1;
values > 1 exist for CPU simulation (each proc gets its own virtual device
count via XLA_FLAGS) and for tests.

The node controller:
  * rank-0 node starts the native TCPStore rendezvous server (runtime/,
    csrc/tcp_store.cc) — the ProcessGroup bootstrap analog;
  * every proc registers in the store and barriers before user code runs;
  * children get ``PADDLE_TRAINER_ID`` / ``PADDLE_TRAINERS_NUM`` /
    ``PADDLE_MASTER`` env (consumed by distributed/env.py init_parallel_env);
  * the controller watches children, tears the job down on failure, and with
    ``--max_restarts`` > 0 relaunches the whole node (checkpoint-restart
    elasticity — a TPU slice cannot resize in place, so "elastic" means
    restart + resume, see fleet/elastic/).
"""
from .controller import LaunchConfig, launch  # noqa: F401
