"""CLI entry (ref: python/paddle/distributed/launch/main.py)."""
from __future__ import annotations

import argparse
import sys

from .controller import LaunchConfig, launch


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="Launch distributed training (one proc per host on TPU; "
                    "--nproc_per_node>1 for CPU simulation/tests)")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int, default=0)
    p.add_argument("--master", type=str, default=None,
                   help="host:port of the rendezvous store (multi-node)")
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="elastic checkpoint-restart rounds on failure")
    p.add_argument("--devices", "--gpus", type=str, default=None,
                   help="device list for parity with the reference CLI")
    p.add_argument("--heartbeat_interval", type=float, default=5.0)
    p.add_argument("-m", "--module", action="store_true",
                   help="run script as a module (python -m)")
    p.add_argument("script", type=str)
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cfg = LaunchConfig(
        script=args.script,
        script_args=args.script_args,
        nproc_per_node=args.nproc_per_node,
        nnodes=args.nnodes,
        node_rank=args.node_rank,
        master=args.master,
        job_id=args.job_id,
        log_dir=args.log_dir,
        max_restarts=args.max_restarts,
        devices=args.devices,
        run_module=args.module,
        heartbeat_interval=args.heartbeat_interval,
    )
    return launch(cfg)


if __name__ == "__main__":
    sys.exit(main())
