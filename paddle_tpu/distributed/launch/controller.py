"""Node controller: spawn/monitor/restart per-rank processes
(ref: python/paddle/distributed/launch/controllers/collective.py).
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import List, Optional

from ... import runtime as rt
from ...observability import get_logger, log_event


@dataclass
class LaunchConfig:
    script: str = ""
    script_args: List[str] = field(default_factory=list)
    nproc_per_node: int = 1
    nnodes: int = 1
    node_rank: int = 0
    master: Optional[str] = None      # "host:port"; None -> local ephemeral
    job_id: str = "default"
    log_dir: str = "log"
    max_restarts: int = 0
    devices: Optional[str] = None     # parity with --gpus/--devices
    envs: dict = field(default_factory=dict)
    # run module (python -m mod) instead of a script
    run_module: bool = False
    heartbeat_interval: float = 5.0


class NodeController:
    def __init__(self, cfg: LaunchConfig):
        self.cfg = cfg
        self.server = None
        self.procs: List[subprocess.Popen] = []
        self.log_files = []

    # -- rendezvous bootstrap --------------------------------------------
    def _start_master(self):
        """Rank-0 node hosts the store. Its address is either fixed by
        --master (multi-node) or an ephemeral local port (single node)."""
        if self.cfg.master:
            host, port = self.cfg.master.rsplit(":", 1)
            if self.cfg.node_rank == 0:
                self.server = rt.TCPStoreServer(int(port))
            return host, int(port)
        self.server = rt.TCPStoreServer()
        return "127.0.0.1", self.server.port

    # -- child env --------------------------------------------------------
    def _child_env(self, local_rank: int, host: str, port: int,
                   restart_round: int) -> dict:
        world = self.cfg.nnodes * self.cfg.nproc_per_node
        rank = self.cfg.node_rank * self.cfg.nproc_per_node + local_rank
        env = dict(os.environ)
        env.update(self.cfg.envs)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_NNODES": str(self.cfg.nnodes),
            "PADDLE_NODE_RANK": str(self.cfg.node_rank),
            "PADDLE_MASTER": host,
            "MASTER_ADDR": host,
            "MASTER_PORT": str(port),
            "PADDLE_JOB_ID": self.cfg.job_id,
            "PADDLE_RESTART_ROUND": str(restart_round),
            "PADDLE_ELASTIC_MAX_RESTARTS": str(self.cfg.max_restarts),
            "PADDLE_HEARTBEAT_INTERVAL": str(self.cfg.heartbeat_interval),
        })
        if self.cfg.devices is not None:
            env["PADDLE_SELECTED_DEVICES"] = self.cfg.devices
        return env

    # -- spawn ------------------------------------------------------------
    def _spawn(self, host: str, port: int, restart_round: int):
        os.makedirs(self.cfg.log_dir, exist_ok=True)
        self.procs, self.log_files = [], []
        for local_rank in range(self.cfg.nproc_per_node):
            rank = (self.cfg.node_rank * self.cfg.nproc_per_node + local_rank)
            cmd = [sys.executable]
            if self.cfg.run_module:
                cmd += ["-m", self.cfg.script]
            else:
                cmd += [self.cfg.script]
            cmd += self.cfg.script_args
            log_path = os.path.join(self.cfg.log_dir,
                                    f"workerlog.{rank}")
            # rank 0 tees to the controller's stdout like the reference.
            if rank == 0:
                lf = open(log_path, "wb")
                p = subprocess.Popen(
                    cmd, env=self._child_env(local_rank, host, port,
                                             restart_round),
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            else:
                lf = open(log_path, "wb")
                p = subprocess.Popen(
                    cmd, env=self._child_env(local_rank, host, port,
                                             restart_round),
                    stdout=lf, stderr=subprocess.STDOUT)
            self.procs.append(p)
            self.log_files.append(lf)

    def _pump_rank0(self):
        """Forward rank-0 output to our stdout AND its log file."""
        p0 = self.procs[0]
        if p0.stdout is None:
            return
        data = p0.stdout.read1(65536) if hasattr(p0.stdout, "read1") else b""
        if data:
            sys.stdout.buffer.write(data)
            sys.stdout.buffer.flush()
            self.log_files[0].write(data)
            self.log_files[0].flush()

    def _poll(self) -> Optional[int]:
        """None while all alive; else the first nonzero exit code or 0."""
        all_done = True
        for p in self.procs:
            rc = p.poll()
            if rc is None:
                all_done = False
            elif rc != 0:
                return rc
        return 0 if all_done else None

    def _terminate_all(self):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + 10
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
        for lf in self.log_files:
            try:
                lf.close()
            except Exception:
                pass

    # -- main loop --------------------------------------------------------
    def run(self) -> int:
        host, port = self._start_master()
        restart_round = 0
        try:
            while True:
                self._spawn(host, port, restart_round)
                status = None
                while status is None:
                    self._pump_rank0()
                    status = self._poll()
                    if status is None:
                        time.sleep(0.05)
                self._pump_rank0()
                self._terminate_all()
                if status == 0:
                    return 0
                # rank-tagged structured logging (observability.get_logger
                # writes [ts] [rank N] ... to stderr)
                log = get_logger("paddle_tpu.launch")
                if restart_round >= self.cfg.max_restarts:
                    log.error("job failed with exit code %s after %s "
                              "restarts", status, restart_round)
                    log_event(log, "job_failed", exit_code=status,
                              restarts=restart_round)
                    return status
                restart_round += 1
                log.error("worker failed (exit %s); restart %s/%s",
                          status, restart_round, self.cfg.max_restarts)
                # Scrub job keys so the next round re-rendezvouses cleanly.
                if self.server is not None:
                    try:
                        c = rt.TCPStore(host, port, timeout=5.0)
                        c.set(f"{self.cfg.job_id}/restart_round",
                              str(restart_round).encode())
                        c.close()
                    except Exception:
                        pass
        finally:
            self._terminate_all()
            if self.server is not None:
                self.server.stop()


def launch(cfg: LaunchConfig) -> int:
    return NodeController(cfg).run()
