"""init_parallel_env + DataParallel (ref: python/paddle/distributed/parallel.py).

DataParallel on TPU: the reference broadcasts params then bucket-allreduces
grads during backward (EagerReducer over NCCL). Single-controller SPMD holds
ONE copy of the params for all devices, so the eager wrapper is numerically
the identity; the dp communication pattern materializes when the step is
compiled over a mesh with the batch sharded on 'dp' (TrainStep(batch_spec=
P('dp')) — XLA inserts the grad psum that the reducer used to issue).
no_sync is honored in compiled mode by skipping the step's optimizer update.
"""
from __future__ import annotations

import contextlib

from ..nn.layer.layers import Layer
from .env import get_rank, get_world_size, init_parallel_env  # noqa: F401


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self.group = group
        # picked up by TrainStep(grad_sync="bucketed") as the bucket cap,
        # mirroring the reference reducer's comm_buffer_size (MB)
        self._comm_buffer_mb = comm_buffer_size

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        yield

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, *args, **kwargs):
        return self._layers.set_state_dict(*args, **kwargs)

    def parameters(self, *args, **kwargs):
        return self._layers.parameters(*args, **kwargs)

    def named_parameters(self, *args, **kwargs):
        return self._layers.named_parameters(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        return None


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """The reference forks one process per GPU. TPU SPMD needs one process
    per HOST; on a single host run the function directly."""
    func(*args)
