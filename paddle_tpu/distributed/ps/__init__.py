"""Parameter-server mode, minimal but real (ref: paddle/fluid/distributed/ps/
and python/paddle/distributed/ps/ — SURVEY.md §2a 'Parameter server').

The reference's PS is a brpc service with sparse/dense tables for
recommendation workloads. This TPU-native equivalent keeps the same worker
API surface (pull/push dense + sparse tables, server/worker roles, fleet-style
init_server/init_worker) over the framework RPC layer. Dense training belongs
on the SPMD collective path; PS covers the huge-sparse-embedding case where
tables exceed device memory and live host-side.
"""
from .embedding import SparseEmbedding
from .service import (create_dense_table, create_sparse_table, drop_table,
                      load_table, pull_dense, pull_sparse, push_dense,
                      push_sparse, save_table, stat)
from .ps import PSClient, PSServer

__all__ = ["PSServer", "PSClient", "SparseEmbedding", "create_dense_table",
           "create_sparse_table", "drop_table", "load_table", "pull_dense",
           "push_dense", "pull_sparse", "push_sparse", "save_table", "stat"]
