"""PS-backed sparse embedding layer (ref: paddle.static.nn.sparse_embedding
+ the distributed lookup_table op wired to the PS pull/push accessors).

The huge table lives host-side on the parameter servers; the device only
ever sees the pulled rows for the current batch. Forward pulls rows (an rpc
per shard) and enters them into the autograd graph through a PyLayer whose
backward pushes the row gradients back to the servers — the optimizer for
these rows is the TABLE's accessor (server-side), not the device optimizer,
exactly the reference's split."""
from __future__ import annotations

import numpy as np

from ...autograd.py_layer import PyLayer
from ...nn.layer.layers import Layer
from ...tensor.tensor import Tensor


class _PSLookup(PyLayer):
    @staticmethod
    def forward(ctx, anchor, ids_np, rows_np, client, table, lr):
        ctx.ids = ids_np
        ctx.client = client
        ctx.table = table
        ctx.lr = lr
        import jax.numpy as jnp
        return Tensor._from_data(jnp.asarray(rows_np))

    @staticmethod
    def backward(ctx, d_rows):
        grads = np.asarray(d_rows._data, np.float32)
        ctx.client.push_sparse(ctx.table, ctx.ids, grads, lr=ctx.lr)
        # anchor grad: zeros (it exists only to attach this node to the
        # graph — sparse rows are updated server-side, not through it)
        import jax.numpy as jnp
        return Tensor._from_data(jnp.zeros((1,), jnp.float32))


class SparseEmbedding(Layer):
    """paddle-style Layer over a PS sparse table.

    emb = SparseEmbedding(client, "user_emb", dim=16)
    out = emb(ids)            # [.., dim] Tensor, differentiable
    loss.backward()           # row grads pushed to the table's accessor
    """

    def __init__(self, client, table_name, emb_dim, init_std=0.01,
                 accessor=None, entry_threshold=0, lr=None):
        super().__init__()
        self.client = client
        self.table = table_name
        self.dim = int(emb_dim)
        self.lr = lr
        client.create_sparse_table(table_name, emb_dim, init_std=init_std,
                                   accessor=accessor,
                                   entry_threshold=entry_threshold)
        # trainable scalar anchor: backward only visits nodes reachable from
        # a leaf with stop_gradient=False, and ids are integers
        from ... import zeros
        self._anchor = zeros([1])
        self._anchor.stop_gradient = False

    def forward(self, ids):
        ids_np = np.asarray(ids.numpy() if isinstance(ids, Tensor) else ids,
                            np.int64)
        shape = ids_np.shape
        flat = ids_np.reshape(-1)
        rows = self.client.pull_sparse(self.table, flat,
                                       training=self.training)
        out = _PSLookup.apply(self._anchor, flat, rows, self.client,
                              self.table, self.lr)  # [N, dim]
        return out.reshape(list(shape) + [self.dim])
