"""PS roles over the rpc layer (ref: python/paddle/distributed/ps/,
fleet PS mode: fleet.init_server / run_server / init_worker).

Sharding (ref: the brpc PS hash partition): a logical table is split over N
servers by ``key % N``; each shard lives as an independent physical table
named ``{table}#{shard}`` on its server. The client scatters pulls/pushes by
shard, issues the per-server rpcs concurrently, and reassembles results in
the caller's id order. Duplicate ids within a push are merged (grads summed)
client-side before the accessor applies — the reference's gradient merge.
"""
from __future__ import annotations

import queue as _queue
import threading

import numpy as np

from .. import rpc as rpc_mod
from . import service


class PSServer:
    """Server role: joins the rpc world as ``ps_server:{idx}`` and serves
    table requests until stop() (ref: fleet.run_server)."""

    def __init__(self, server_index=0, rank=None, world_size=None,
                 master_endpoint=None):
        self.name = f"ps_server:{server_index}"
        rpc_mod.init_rpc(self.name, rank=rank, world_size=world_size,
                         master_endpoint=master_endpoint)

    def stop(self):
        rpc_mod.shutdown()


def _merge_duplicates(ids, grads, extra=None):
    """Sum grads (and any extra per-id stat arrays) of duplicate ids;
    returns (unique_ids, merged_grads, merged_extras)."""
    ids = np.asarray(ids, np.int64)
    grads = np.asarray(grads, np.float32)
    uniq, inv = np.unique(ids, return_inverse=True)
    merged = np.zeros((len(uniq),) + grads.shape[1:], np.float32)
    np.add.at(merged, inv, grads)
    outs = []
    for a in (extra or ()):
        if a is None:
            outs.append(None)
            continue
        a = np.asarray(a, np.float32)
        m = np.zeros((len(uniq),), np.float32)
        np.add.at(m, inv, a)
        outs.append(m)
    return uniq, merged, outs


class PSClient:
    """Worker-side handle (ref: fleet init_worker + pull/push APIs).

    servers: explicit server-name list, or num_servers addressing
    ``ps_server:0..n-1``. Sparse tables shard key % num_servers.
    async_push=True applies pushes from a background thread (async-PS /
    geo-SGD flavor); barrier() drains it.
    """

    def __init__(self, worker_name, server_name=None, servers=None,
                 num_servers=None, rank=None, world_size=None,
                 master_endpoint=None, async_push=False):
        if servers is None:
            if num_servers is not None:
                servers = [f"ps_server:{i}" for i in range(num_servers)]
            else:
                servers = [server_name or "ps_server:0"]
        self.servers = list(servers)
        self.server = self.servers[0]  # legacy single-server attribute
        if rank is not None or rpc_mod.rpc._state["server"] is None:
            rpc_mod.init_rpc(worker_name, rank=rank, world_size=world_size,
                             master_endpoint=master_endpoint)
        self._push_q = None
        self._push_thread = None
        self._push_err = None
        self._geo = {}
        if async_push:
            self._push_q = _queue.Queue(maxsize=64)
            self._push_thread = threading.Thread(target=self._push_loop,
                                                 daemon=True)
            self._push_thread.start()

    # -- sharding helpers --------------------------------------------------

    def _shard_name(self, name, s):
        return f"{name}#{s}" if len(self.servers) > 1 else name

    # -- dense -------------------------------------------------------------

    def create_dense_table(self, name, shape, init="zeros", accessor=None):
        # dense tables are not sharded (dense training belongs on the SPMD
        # collective path; PS-dense exists for API parity / tiny models)
        return rpc_mod.rpc_sync(self.servers[0], service.create_dense_table,
                                args=(name, shape, init, 0, accessor))

    def pull_dense(self, name):
        return rpc_mod.rpc_sync(self.servers[0], service.pull_dense,
                                args=(name,))

    def push_dense(self, name, grad, lr=None):
        return rpc_mod.rpc_sync(self.servers[0], service.push_dense,
                                args=(name, grad, lr))

    # -- sparse ------------------------------------------------------------

    def create_sparse_table(self, name, emb_dim, init_std=0.01,
                            accessor=None, entry_threshold=0):
        futs = [rpc_mod.rpc_async(
                    srv, service.create_sparse_table,
                    args=(self._shard_name(name, s), emb_dim, init_std,
                          s, accessor, entry_threshold))
                for s, srv in enumerate(self.servers)]
        return all(f.result() for f in futs)

    def pull_sparse(self, name, ids, training=True):
        ids = np.asarray(ids, np.int64)
        if len(ids) == 0:  # server returns the dim-correct empty array
            return np.asarray(rpc_mod.rpc_sync(
                self.servers[0], service.pull_sparse,
                args=(self._shard_name(name, 0), [], training)), np.float32)
        n = len(self.servers)
        shard = ids % n
        futs, parts = [], []
        for s in range(n):
            pos = np.nonzero(shard == s)[0]
            parts.append(pos)
            if len(pos) == 0:
                futs.append(None)
                continue
            futs.append(rpc_mod.rpc_async(
                self.servers[s], service.pull_sparse,
                args=(self._shard_name(name, s), ids[pos].tolist(),
                      training)))
        rows = None
        for pos, fut in zip(parts, futs):
            if fut is None:
                continue
            part = np.asarray(fut.result(), np.float32)
            if rows is None:
                rows = np.zeros((len(ids), part.shape[1]), np.float32)
            rows[pos] = part
        return rows

    def push_sparse(self, name, ids, grads, lr=None, shows=None,
                    clicks=None):
        uniq, merged, (mshows, mclicks) = _merge_duplicates(
            ids, grads, (shows, clicks))
        if self._push_q is not None:
            self._raise_pending()
            self._push_q.put((name, uniq, merged, lr, mshows, mclicks))
            return True
        return self._push_now(name, uniq, merged, lr, mshows, mclicks)

    def _push_now(self, name, uniq, merged, lr, shows=None, clicks=None):
        n = len(self.servers)
        futs = []
        for s, srv in enumerate(self.servers):
            sel = uniq % n == s
            if not sel.any():
                continue
            futs.append(rpc_mod.rpc_async(
                srv, service.push_sparse,
                args=(self._shard_name(name, s), uniq[sel].tolist(),
                      merged[sel], lr,
                      None if shows is None else shows[sel].tolist(),
                      None if clicks is None else clicks[sel].tolist())))
        return all(f.result() for f in futs)

    def shrink_sparse_table(self, name, score_threshold=0.0, decay=None):
        """CTR table maintenance: decay show/click stats on every shard and
        evict rows scoring below the threshold. Returns total evictions."""
        self.barrier()
        futs = [rpc_mod.rpc_async(
                    srv, service.shrink_sparse_table,
                    args=(self._shard_name(name, s), score_threshold, decay))
                for s, srv in enumerate(self.servers)]
        return sum(f.result() for f in futs)

    # -- geo-SGD mode (ref: GeoCommunicator / fleet a_sync_configs) --------

    def init_geo(self, name, shape, sync_steps=4, init="zeros"):
        """Register a dense table for geo-SGD: workers train LOCALLY and
        every `sync_steps` geo_step() calls push their parameter DELTA
        (local - last_synced) to the server (which sums deltas from all
        workers) and pull the merged global back."""
        if int(sync_steps) < 1:
            raise ValueError(
                f"init_geo: sync_steps must be >= 1, got {sync_steps}; "
                "k_steps=0 (fully-async PS) is served by "
                "PSClient(async_push=True) pushes, not geo-SGD")
        ok = self.create_dense_table(name, list(shape), init=init,
                                     accessor={"type": "sum"})
        w = self.pull_dense(name)
        self._geo[name] = {"last": w.copy(), "k": int(sync_steps),
                           "count": 0}
        return ok, w

    def geo_step(self, name, local_w):
        """Advance one local step; on every k-th call sync with the server.
        Returns the weights to continue training from (the merged global
        on sync steps, local_w otherwise)."""
        st = self._geo[name]
        st["count"] += 1
        if st["count"] % st["k"]:
            return local_w
        local_w = np.asarray(local_w, np.float32)
        delta = local_w - st["last"]
        # dense tables are not sharded (see create_dense_table)
        rpc_mod.rpc_sync(self.servers[0], service.push_geo_dense,
                         args=(name, delta))
        merged = self.pull_dense(name)
        st["last"] = merged.copy()
        return merged

    def _push_loop(self):
        while True:
            item = self._push_q.get()
            if item is None:
                self._push_q.task_done()
                return
            try:
                self._push_now(*item)
            except BaseException as e:  # surfaced at the next push/barrier
                self._push_err = RuntimeError(f"async push failed: {e}")
            finally:
                self._push_q.task_done()

    def _raise_pending(self):
        if self._push_err is not None:
            err, self._push_err = self._push_err, None
            raise err

    def barrier(self):
        """Drain in-flight async pushes (ref: fleet barrier_worker)."""
        if self._push_q is not None:
            self._push_q.join()
        self._raise_pending()
        return True

    # -- persistence (ref: fleet.save_persistables PS mode) ----------------

    def save_sparse_table(self, name, dirname):
        self.barrier()
        futs = [rpc_mod.rpc_async(
                    srv, service.save_table,
                    args=(self._shard_name(name, s),
                          f"{dirname}/{name}.shard{s}"))
                for s, srv in enumerate(self.servers)]
        return all(f.result() for f in futs)

    def load_sparse_table(self, name, dirname):
        futs = [rpc_mod.rpc_async(
                    srv, service.load_table,
                    args=(self._shard_name(name, s),
                          f"{dirname}/{name}.shard{s}"))
                for s, srv in enumerate(self.servers)]
        return all(f.result() for f in futs)

    def stat(self):
        if len(self.servers) == 1:  # legacy flat shape
            return rpc_mod.rpc_sync(self.servers[0], service.stat)
        return {srv: rpc_mod.rpc_sync(srv, service.stat)
                for srv in self.servers}

    def stop(self):
        if self._push_q is not None:
            self._push_q.put(None)
            self._push_thread.join(timeout=10)
            self._raise_pending()  # a failed final push must not vanish
        rpc_mod.shutdown()
