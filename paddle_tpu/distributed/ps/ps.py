"""PS roles over the rpc layer (ref: python/paddle/distributed/ps/,
fleet PS mode: fleet.init_server / run_server / init_worker)."""
from __future__ import annotations

from .. import rpc as rpc_mod
from . import service


class PSServer:
    """Server role: joins the rpc world as ``ps_server:{idx}`` and serves
    table requests until stop() (ref: fleet.run_server)."""

    def __init__(self, server_index=0, rank=None, world_size=None,
                 master_endpoint=None):
        self.name = f"ps_server:{server_index}"
        rpc_mod.init_rpc(self.name, rank=rank, world_size=world_size,
                         master_endpoint=master_endpoint)

    def stop(self):
        rpc_mod.shutdown()


class PSClient:
    """Worker-side handle (ref: fleet init_worker + pull/push APIs)."""

    def __init__(self, worker_name, server_name="ps_server:0", rank=None,
                 world_size=None, master_endpoint=None):
        self.server = server_name
        if rank is not None or rpc_mod.rpc._state["server"] is None:
            rpc_mod.init_rpc(worker_name, rank=rank, world_size=world_size,
                             master_endpoint=master_endpoint)

    # dense ---------------------------------------------------------------
    def create_dense_table(self, name, shape, init="zeros"):
        return rpc_mod.rpc_sync(self.server, service.create_dense_table,
                                args=(name, shape, init))

    def pull_dense(self, name):
        return rpc_mod.rpc_sync(self.server, service.pull_dense, args=(name,))

    def push_dense(self, name, grad, lr=0.01):
        return rpc_mod.rpc_sync(self.server, service.push_dense,
                                args=(name, grad, lr))

    # sparse --------------------------------------------------------------
    def create_sparse_table(self, name, emb_dim, init_std=0.01):
        return rpc_mod.rpc_sync(self.server, service.create_sparse_table,
                                args=(name, emb_dim, init_std))

    def pull_sparse(self, name, ids):
        return rpc_mod.rpc_sync(self.server, service.pull_sparse,
                                args=(name, list(map(int, ids))))

    def push_sparse(self, name, ids, grads, lr=0.01):
        return rpc_mod.rpc_sync(self.server, service.push_sparse,
                                args=(name, list(map(int, ids)), grads, lr))

    def stat(self):
        return rpc_mod.rpc_sync(self.server, service.stat)

    def stop(self):
        rpc_mod.shutdown()
