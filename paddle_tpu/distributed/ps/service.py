"""Table service functions — executed inside the server process.

Module-level functions pickle by reference, so an rpc call from a worker
binds to THIS module's state on the server side (the table registry below
lives in the server process only), mirroring how the reference's table
accessors live in the brpc server (ref: paddle/fluid/distributed/ps/table/
memory_sparse_table.cc + accessor/ctr_*_accessor.cc).

Reference feature map implemented here:
- sparse tables: create-on-miss rows, per-row optimizer state (accessor),
  show-count entry threshold (rows only materialize after `entry_threshold`
  pulls — the reference's frequency-gated feature admission)
- accessors: 'sgd', 'adagrad', 'adam' — the update runs server-side on push,
  as the reference's accessors do
- dense tables with the same accessor choices
- save/load of whole tables (model persistence for PS mode)

Sharding across servers is the CLIENT's job (key % num_servers — the
reference's hash partition); each shard is an independent table here.
"""
from __future__ import annotations

import os
import pickle
import threading

import numpy as np

_TABLES = {}
_LOCK = threading.Lock()


# -- accessors (server-side optimizers) -------------------------------------

def _accessor_state(kind, shape):
    if kind == "sgd":
        return {}
    if kind == "adagrad":
        return {"g2": np.zeros(shape, np.float32)}
    if kind == "adam":
        return {"m": np.zeros(shape, np.float32),
                "v": np.zeros(shape, np.float32), "t": 0}
    raise ValueError(f"unknown accessor '{kind}'")


def _accessor_apply(acc, w, state, grad):
    kind, lr = acc["type"], acc["lr"]
    if kind == "sgd":
        w -= lr * grad
        return
    if kind == "adagrad":
        state["g2"] += grad * grad
        w -= lr * grad / (np.sqrt(state["g2"]) + acc.get("eps", 1e-8))
        return
    if kind == "adam":
        b1, b2 = acc.get("beta1", 0.9), acc.get("beta2", 0.999)
        eps = acc.get("eps", 1e-8)
        state["t"] += 1
        state["m"][:] = b1 * state["m"] + (1 - b1) * grad
        state["v"][:] = b2 * state["v"] + (1 - b2) * grad * grad
        mhat = state["m"] / (1 - b1 ** state["t"])
        vhat = state["v"] / (1 - b2 ** state["t"])
        w -= lr * mhat / (np.sqrt(vhat) + eps)


def _norm_accessor(accessor):
    if accessor is None:
        return {"type": "sgd", "lr": 0.01}
    if isinstance(accessor, str):
        return {"type": accessor, "lr": 0.01}
    acc = dict(accessor)
    acc.setdefault("type", "sgd")
    acc.setdefault("lr", 0.01)
    return acc


# -- dense tables -----------------------------------------------------------

def create_dense_table(name, shape, init="zeros", seed=0, accessor=None):
    with _LOCK:
        if name in _TABLES:
            return False
        if init == "zeros":
            data = np.zeros(shape, np.float32)
        else:
            rng = np.random.RandomState(seed)
            data = (rng.standard_normal(shape) * 0.01).astype(np.float32)
        acc = _norm_accessor(accessor)
        _TABLES[name] = {"kind": "dense", "data": data, "accessor": acc,
                         "state": _accessor_state(acc["type"], data.shape)}
    return True


def pull_dense(name):
    return _TABLES[name]["data"]


def push_dense(name, grad, lr=None):
    """Apply a dense gradient through the table's accessor (async-PS
    semantics: workers push whenever, server serializes applies)."""
    t = _TABLES[name]
    with _LOCK:
        acc = dict(t["accessor"])
        if lr is not None:  # per-push lr override (legacy arg)
            acc["lr"] = lr
        _accessor_apply(acc, t["data"], t["state"], np.asarray(grad, np.float32))
    return True


# -- sparse tables ----------------------------------------------------------

def create_sparse_table(name, emb_dim, init_std=0.01, seed=0, accessor=None,
                        entry_threshold=0):
    with _LOCK:
        if name in _TABLES:
            return False
        _TABLES[name] = {"kind": "sparse", "dim": int(emb_dim),
                         "rows": {}, "std": init_std,
                         "rng": np.random.RandomState(seed),
                         "accessor": _norm_accessor(accessor),
                         "entry_threshold": int(entry_threshold),
                         "counts": {}}
    return True


def pull_sparse(name, ids, training=True):
    """Fetch rows for ids. Unseen ids below the entry threshold return zeros
    (not yet admitted — the reference's frequency gate); once an id has been
    shown `entry_threshold` times it materializes create-on-miss. Eval pulls
    (training=False) never mutate the table: unknown ids return zeros
    instead of allocating rows."""
    t = _TABLES[name]
    thr = t["entry_threshold"]
    with _LOCK:
        out = np.empty((len(ids), t["dim"]), np.float32)
        for i, key in enumerate(ids):
            key = int(key)
            if thr > 0 and training:
                c = t["counts"].get(key, 0) + 1
                t["counts"][key] = c
                if c < thr:
                    out[i] = 0.0
                    continue
            row = t["rows"].get(key)
            if row is None:
                if not training or (thr > 0 and
                                    t["counts"].get(key, 0) < thr):
                    out[i] = 0.0
                    continue
                row = {"w": (t["rng"].standard_normal(t["dim"])
                             * t["std"]).astype(np.float32),
                       "state": _accessor_state(t["accessor"]["type"],
                                                (t["dim"],))}
                t["rows"][key] = row
            out[i] = row["w"]
    return out


def push_sparse(name, ids, grads, lr=None):
    """Accessor-apply per-row grads. Ids must be unique per call (the client
    merges duplicates); unadmitted/unknown rows are skipped."""
    t = _TABLES[name]
    grads = np.asarray(grads, np.float32)
    with _LOCK:
        acc = dict(t["accessor"])
        if lr is not None:
            acc["lr"] = lr
        for key, g in zip(ids, grads):
            row = t["rows"].get(int(key))
            if row is not None:
                _accessor_apply(acc, row["w"], row["state"], g)
    return True


# -- persistence (ref: fleet.save_persistables PS mode) ---------------------

def save_table(name, path):
    t = _TABLES[name]
    # snapshot under the lock, serialize/write OUTSIDE it: a multi-GB pickle
    # must not stall every concurrent pull/push on this server
    with _LOCK:
        blob = dict(t)
        blob.pop("rng", None)
        if t["kind"] == "sparse":
            blob["rows"] = {k: {"w": r["w"].copy(),
                                "state": {sk: (sv.copy()
                                               if isinstance(sv, np.ndarray)
                                               else sv)
                                          for sk, sv in r["state"].items()}}
                            for k, r in t["rows"].items()}
            blob["counts"] = dict(t["counts"])
        else:
            blob["data"] = t["data"].copy()
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(blob, f)
    return True


def load_table(name, path, overwrite=True):
    with open(path, "rb") as f:
        blob = pickle.load(f)
    blob["rng"] = np.random.RandomState(0)
    with _LOCK:
        if name in _TABLES and not overwrite:
            return False
        if blob["kind"] == "dense":
            blob.pop("rng")
        _TABLES[name] = blob
    return True


def drop_table(name):
    with _LOCK:
        return _TABLES.pop(name, None) is not None


def stat():
    with _LOCK:
        return {name: (t["kind"],
                       t["data"].shape if t["kind"] == "dense"
                       else len(t["rows"]))
                for name, t in _TABLES.items()}
