"""Table service functions — executed inside the server process.

Module-level functions pickle by reference, so an rpc call from a worker
binds to THIS module's state on the server side (the table registry below
lives in the server process only), mirroring how the reference's table
accessors live in the brpc server (ref: paddle/fluid/distributed/ps/table/
memory_sparse_table.cc + accessor/ctr_*_accessor.cc).

Reference feature map implemented here:
- sparse tables: create-on-miss rows, per-row optimizer state (accessor),
  show-count entry threshold (rows only materialize after `entry_threshold`
  pulls — the reference's frequency-gated feature admission)
- accessors: 'sgd', 'adagrad', 'adam' — the update runs server-side on push,
  as the reference's accessors do
- dense tables with the same accessor choices
- save/load of whole tables (model persistence for PS mode)

Sharding across servers is the CLIENT's job (key % num_servers — the
reference's hash partition); each shard is an independent table here.

Disk tier (ref: the reference's SSD/disk-backed sparse tables,
ssd_sparse_table.cc): a sparse table created with ``max_mem_rows=N`` keeps
at most N hot rows in memory (LRU by access order) and spills the cold
tail to an append-only pickle log with an in-memory key->offset index;
a pull/push of a spilled key promotes the row back (evicting others).
save_table merges both tiers, so persistence sees the full table.
"""
from __future__ import annotations

import os
import pickle
import tempfile
import threading
from collections import OrderedDict

import numpy as np

_TABLES = {}
_LOCK = threading.Lock()


# -- disk spill tier ---------------------------------------------------------

class _SpillLog:
    """Append-only row store: offsets index a pickle per row. Updated rows
    re-append (the old record becomes garbage); save_table compacts by
    rewriting through the normal save path.

    Own lock: drop_table/load_table close() outside the registry _LOCK
    while an RPC thread that already fetched the table dict may still be
    about to read — all file ops and close() serialize here, and ops on a
    closed log degrade to misses instead of ValueError on a closed file."""

    def __init__(self, path=None):
        if path is None:
            fd, path = tempfile.mkstemp(prefix="pd_ps_spill_",
                                        suffix=".log")
            os.close(fd)
        self.path = path
        self._f = open(path, "a+b")
        self._lock = threading.Lock()
        self._closed = False
        self.index = {}

    def put(self, key, row):
        with self._lock:
            if self._closed:
                return
            self._f.seek(0, os.SEEK_END)
            off = self._f.tell()
            pickle.dump(row, self._f, protocol=pickle.HIGHEST_PROTOCOL)
            self._f.flush()
            self.index[key] = off

    def _get_locked(self, key):
        off = self.index.get(key)
        if off is None or self._closed:
            return None
        self._f.seek(off)
        return pickle.load(self._f)

    def get(self, key):
        with self._lock:
            return self._get_locked(key)

    def pop(self, key):
        with self._lock:
            row = self._get_locked(key)
            self.index.pop(key, None)
            return row

    def keys(self):
        with self._lock:
            return list(self.index.keys())

    def close(self):
        with self._lock:
            self._closed = True
            try:
                self._f.close()
                os.unlink(self.path)
            except OSError:
                pass


def _evict_if_needed(t):
    """Spill the least-recently-used ~1/8 of rows once over budget (batch
    eviction amortizes the append cost). Caller holds _LOCK."""
    cap = t.get("max_mem_rows") or 0
    if cap <= 0 or len(t["rows"]) <= cap:
        return
    n_evict = max(1, cap // 8)
    spill = t["spill"]
    for _ in range(n_evict):
        if not t["rows"]:
            break
        key, row = t["rows"].popitem(last=False)   # LRU front
        spill.put(key, row)


def _get_row(t, key):
    """Row lookup through both tiers; promotes a spilled row. Caller holds
    _LOCK. Returns None if absent everywhere."""
    row = t["rows"].get(key)
    if row is not None:
        if t.get("max_mem_rows"):
            t["rows"].move_to_end(key)
        return row
    spill = t.get("spill")
    if spill is not None:
        row = spill.pop(key)
        if row is not None:
            t["rows"][key] = row
            _evict_if_needed(t)
            return row
    return None


# -- accessors (server-side optimizers) -------------------------------------

def _accessor_state(kind, shape):
    if kind == "sgd":
        return {}
    if kind == "sum":
        # geo-SGD delta table: push ADDS the worker's delta verbatim
        return {}
    if kind == "adagrad":
        return {"g2": np.zeros(shape, np.float32)}
    if kind == "adam":
        return {"m": np.zeros(shape, np.float32),
                "v": np.zeros(shape, np.float32), "t": 0}
    if kind == "ctr":
        # ref: accessor/ctr_common_accessor — adagrad-style embedding
        # update plus per-row show/click statistics for admission,
        # scoring, and shrink
        return {"g2": np.zeros(shape, np.float32),
                "show": 0.0, "click": 0.0}
    raise ValueError(f"unknown accessor '{kind}'")


def _accessor_apply(acc, w, state, grad):
    kind, lr = acc["type"], acc["lr"]
    if kind == "sgd":
        w -= lr * grad
        return
    if kind == "sum":
        w += grad
        return
    if kind in ("adagrad", "ctr"):
        state["g2"] += grad * grad
        w -= lr * grad / (np.sqrt(state["g2"]) + acc.get("eps", 1e-8))
        return
    if kind == "adam":
        b1, b2 = acc.get("beta1", 0.9), acc.get("beta2", 0.999)
        eps = acc.get("eps", 1e-8)
        state["t"] += 1
        state["m"][:] = b1 * state["m"] + (1 - b1) * grad
        state["v"][:] = b2 * state["v"] + (1 - b2) * grad * grad
        mhat = state["m"] / (1 - b1 ** state["t"])
        vhat = state["v"] / (1 - b2 ** state["t"])
        w -= lr * mhat / (np.sqrt(vhat) + eps)


def _ctr_score(acc, state):
    """Row score (ref: CtrCommonAccessor::ShowClickScore): weighted
    show/click mass; shrink evicts rows whose score decays below the
    threshold."""
    return (acc.get("show_coeff", 0.2) * state.get("show", 0.0)
            + acc.get("click_coeff", 1.0) * state.get("click", 0.0))


def _norm_accessor(accessor):
    if accessor is None:
        return {"type": "sgd", "lr": 0.01}
    if isinstance(accessor, str):
        return {"type": accessor, "lr": 0.01}
    acc = dict(accessor)
    acc.setdefault("type", "sgd")
    acc.setdefault("lr", 0.01)
    return acc


# -- dense tables -----------------------------------------------------------

def create_dense_table(name, shape, init="zeros", seed=0, accessor=None):
    with _LOCK:
        if name in _TABLES:
            return False
        if init == "zeros":
            data = np.zeros(shape, np.float32)
        else:
            rng = np.random.RandomState(seed)
            data = (rng.standard_normal(shape) * 0.01).astype(np.float32)
        acc = _norm_accessor(accessor)
        _TABLES[name] = {"kind": "dense", "data": data, "accessor": acc,
                         "state": _accessor_state(acc["type"], data.shape)}
    return True


def pull_dense(name):
    # snapshot under the lock: _accessor_apply mutates the array in place
    # on push, and a concurrent RPC pull could otherwise serialize a torn
    # half-updated weight vector
    with _LOCK:
        return _TABLES[name]["data"].copy()


def push_dense(name, grad, lr=None):
    """Apply a dense gradient through the table's accessor (async-PS
    semantics: workers push whenever, server serializes applies)."""
    t = _TABLES[name]
    with _LOCK:
        acc = dict(t["accessor"])
        if lr is not None:  # per-push lr override (legacy arg)
            acc["lr"] = lr
        _accessor_apply(acc, t["data"], t["state"], np.asarray(grad, np.float32))
    return True


# -- sparse tables ----------------------------------------------------------

def create_sparse_table(name, emb_dim, init_std=0.01, seed=0, accessor=None,
                        entry_threshold=0, max_mem_rows=0, spill_path=None):
    """max_mem_rows > 0 enables the disk tier: at most that many rows stay
    in memory (LRU), the rest spill to an on-disk log (spill_path or a
    tempfile) and promote back on access."""
    with _LOCK:
        if name in _TABLES:
            return False
        _TABLES[name] = {"kind": "sparse", "dim": int(emb_dim),
                         "rows": OrderedDict(), "std": init_std,
                         "rng": np.random.RandomState(seed),
                         "accessor": _norm_accessor(accessor),
                         "entry_threshold": int(entry_threshold),
                         "counts": {},
                         "max_mem_rows": int(max_mem_rows),
                         "spill": (_SpillLog(spill_path)
                                   if max_mem_rows > 0 else None)}
    return True


def pull_sparse(name, ids, training=True):
    """Fetch rows for ids. Unseen ids below the entry threshold return zeros
    (not yet admitted — the reference's frequency gate); once an id has been
    shown `entry_threshold` times it materializes create-on-miss. Eval pulls
    (training=False) never mutate the table: unknown ids return zeros
    instead of allocating rows."""
    t = _TABLES[name]
    thr = t["entry_threshold"]
    with _LOCK:
        out = np.empty((len(ids), t["dim"]), np.float32)
        for i, key in enumerate(ids):
            key = int(key)
            if thr > 0 and training:
                c = t["counts"].get(key, 0) + 1
                t["counts"][key] = c
                if c < thr:
                    out[i] = 0.0
                    continue
            row = _get_row(t, key)
            if row is None:
                if not training or (thr > 0 and
                                    t["counts"].get(key, 0) < thr):
                    out[i] = 0.0
                    continue
                row = {"w": (t["rng"].standard_normal(t["dim"])
                             * t["std"]).astype(np.float32),
                       "state": _accessor_state(t["accessor"]["type"],
                                                (t["dim"],))}
                t["rows"][key] = row
                _evict_if_needed(t)
            out[i] = row["w"]
    return out


def push_sparse(name, ids, grads, lr=None, shows=None, clicks=None):
    """Accessor-apply per-row grads. Ids must be unique per call (the client
    merges duplicates); unadmitted/unknown rows are skipped. shows/clicks
    (per-id impression/click increments) feed the CTR accessor's row
    statistics."""
    t = _TABLES[name]
    grads = np.asarray(grads, np.float32)
    with _LOCK:
        acc = dict(t["accessor"])
        if lr is not None:
            acc["lr"] = lr
        for i, (key, g) in enumerate(zip(ids, grads)):
            row = _get_row(t, int(key))
            if row is not None:
                _accessor_apply(acc, row["w"], row["state"], g)
                if shows is not None:
                    row["state"]["show"] = (row["state"].get("show", 0.0)
                                            + float(shows[i]))
                if clicks is not None:
                    row["state"]["click"] = (row["state"].get("click", 0.0)
                                             + float(clicks[i]))
    return True


def shrink_sparse_table(name, score_threshold=0.0, decay=None):
    """CTR table maintenance (ref: MemorySparseTable::Shrink): decay every
    row's show/click statistics (decay defaults to the accessor's
    show_click_decay_rate, 0.98), then evict rows whose score falls below
    score_threshold. Returns the number of evicted rows."""
    t = _TABLES[name]
    evicted = 0
    with _LOCK:
        acc = t["accessor"]
        d = decay if decay is not None else acc.get("show_click_decay_rate",
                                                    0.98)
        spill = t.get("spill")
        for key in list(t["rows"].keys()):
            st = t["rows"][key]["state"]
            st["show"] = st.get("show", 0.0) * d
            st["click"] = st.get("click", 0.0) * d
            if _ctr_score(acc, st) < score_threshold:
                t["rows"].pop(key, None)
                t["counts"].pop(key, None)
                evicted += 1
        if spill is not None:
            # cold tier: read WITHOUT promoting (promotion would LRU-churn
            # ~the whole hot tier and rewrite the append-only log once per
            # cold row); survivors write back in place of their old record
            for key in [k for k in spill.keys() if k not in t["rows"]]:
                row = spill.get(key)
                if row is None:
                    continue
                st = row["state"]
                st["show"] = st.get("show", 0.0) * d
                st["click"] = st.get("click", 0.0) * d
                if _ctr_score(acc, st) < score_threshold:
                    spill.pop(key)
                    t["counts"].pop(key, None)
                    evicted += 1
                else:
                    spill.put(key, row)
    return evicted


def push_geo_dense(name, delta):
    """geo-SGD merge (ref: GeoCommunicator): the worker's parameter DELTA
    since its last sync is summed into the global dense weights."""
    t = _TABLES[name]
    with _LOCK:
        t["data"] += np.asarray(delta, np.float32)
    return True


# -- persistence (ref: fleet.save_persistables PS mode) ---------------------

def save_table(name, path):
    t = _TABLES[name]
    # snapshot under the lock, serialize/write OUTSIDE it: a multi-GB pickle
    # must not stall every concurrent pull/push on this server
    def copy_row(r):
        return {"w": r["w"].copy(),
                "state": {sk: (sv.copy()
                               if isinstance(sv, np.ndarray) else sv)
                          for sk, sv in r["state"].items()}}

    with _LOCK:
        blob = dict(t)
        blob.pop("rng", None)
        blob.pop("spill", None)
        if t["kind"] == "sparse":
            rows = {k: copy_row(r) for k, r in t["rows"].items()}
            spill = t.get("spill")
            blob["rows"] = rows
            blob["counts"] = dict(t["counts"])
        else:
            blob["data"] = t["data"].copy()
    if t["kind"] == "sparse" and spill is not None:
        # merge the disk tier OUTSIDE the registry lock: per-row disk
        # reads must not stall concurrent pulls/pushes (the _SpillLog has
        # its own lock). A row promoted to memory between the snapshot and
        # the read is fetched from the hot tier instead.
        for k in spill.keys():
            if k in rows:
                continue
            row = spill.get(k)
            if row is None:
                with _LOCK:
                    r = t["rows"].get(k)
                    row = copy_row(r) if r is not None else None
            if row is not None:
                rows[k] = row
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(blob, f)
    return True


def load_table(name, path, overwrite=True):
    with open(path, "rb") as f:
        blob = pickle.load(f)
    blob["rng"] = np.random.RandomState(0)
    with _LOCK:
        if name in _TABLES and not overwrite:
            return False
        if blob["kind"] == "dense":
            blob.pop("rng")
        else:
            rows = OrderedDict(blob.get("rows", {}))
            cap = int(blob.get("max_mem_rows") or 0)
            blob["rows"] = rows
            blob["max_mem_rows"] = cap
            blob["spill"] = _SpillLog() if cap > 0 else None
            if cap > 0:  # re-spill the cold tail through normal eviction
                t = blob
                while len(t["rows"]) > cap:
                    key, row = t["rows"].popitem(last=False)
                    t["spill"].put(key, row)
        old = _TABLES.pop(name, None)
        _TABLES[name] = blob
    if old is not None and old.get("spill") is not None:
        old["spill"].close()
    return True


def drop_table(name):
    with _LOCK:
        t = _TABLES.pop(name, None)
    if t is not None and t.get("spill") is not None:
        t["spill"].close()
    return t is not None


def stat():
    with _LOCK:
        out = {}
        for name, t in _TABLES.items():
            if t["kind"] == "dense":
                out[name] = (t["kind"], t["data"].shape)
            else:
                spilled = len(t["spill"].index) if t.get("spill") else 0
                out[name] = (t["kind"], len(t["rows"]) + spilled)
        return out
