"""Table service functions — executed inside the server process.

Module-level functions pickle by reference, so an rpc call from a worker
binds to THIS module's state on the server side (the table registry below
lives in the server process only), mirroring how the reference's table
accessors live in the brpc server (ref: paddle/fluid/distributed/ps/table/).
"""
from __future__ import annotations

import threading

import numpy as np

_TABLES = {}
_LOCK = threading.Lock()


def create_dense_table(name, shape, init="zeros", seed=0):
    with _LOCK:
        if name in _TABLES:
            return False
        if init == "zeros":
            data = np.zeros(shape, np.float32)
        else:
            rng = np.random.RandomState(seed)
            data = (rng.standard_normal(shape) * 0.01).astype(np.float32)
        _TABLES[name] = {"kind": "dense", "data": data}
    return True


def pull_dense(name):
    return _TABLES[name]["data"]


def push_dense(name, grad, lr=0.01):
    """SGD-apply a dense gradient on the server (async-PS semantics)."""
    with _LOCK:
        _TABLES[name]["data"] -= lr * np.asarray(grad, np.float32)
    return True


def create_sparse_table(name, emb_dim, init_std=0.01, seed=0):
    with _LOCK:
        if name in _TABLES:
            return False
        _TABLES[name] = {"kind": "sparse", "dim": int(emb_dim),
                         "rows": {}, "std": init_std,
                         "rng": np.random.RandomState(seed)}
    return True


def pull_sparse(name, ids):
    """Fetch rows for ids; unseen ids are lazily initialized (the reference's
    accessor 'create on miss' behavior)."""
    t = _TABLES[name]
    with _LOCK:
        out = np.empty((len(ids), t["dim"]), np.float32)
        for i, key in enumerate(ids):
            row = t["rows"].get(int(key))
            if row is None:
                row = (t["rng"].standard_normal(t["dim"])
                       * t["std"]).astype(np.float32)
                t["rows"][int(key)] = row
            out[i] = row
    return out


def push_sparse(name, ids, grads, lr=0.01):
    t = _TABLES[name]
    grads = np.asarray(grads, np.float32)
    with _LOCK:
        for key, g in zip(ids, grads):
            row = t["rows"].get(int(key))
            if row is not None:
                row -= lr * g
    return True


def stat():
    with _LOCK:
        return {name: (t["kind"],
                       t["data"].shape if t["kind"] == "dense"
                       else len(t["rows"]))
                for name, t in _TABLES.items()}
