"""paddle.distributed.rpc parity (ref: python/paddle/distributed/rpc/).

init_rpc / rpc_sync / rpc_async / shutdown over a plain TCP protocol: each
worker runs a daemon server thread executing pickled (fn, args, kwargs)
requests. Worker discovery goes through the framework's TCPStore (the same
C++ store used for collective rendezvous — SURVEY.md §5.8).
"""
from .rpc import (WorkerInfo, get_all_worker_infos, get_current_worker_info,
                  get_worker_info, init_rpc, rpc_async, rpc_sync, shutdown)

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown", "get_worker_info",
           "get_all_worker_infos", "get_current_worker_info", "WorkerInfo"]
