"""RPC core (ref: python/paddle/distributed/rpc/rpc.py).

Protocol: 4-byte big-endian length + pickle payload, one request per
connection. The reference rides brpc; here a stdlib socketserver keeps the
runtime dependency-free — throughput-sensitive tensor traffic belongs on the
XLA collective path, not RPC (RPC is control-plane, like the reference's).
"""
from __future__ import annotations

import concurrent.futures as futures
import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from collections import namedtuple

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_state = {"workers": {}, "server": None, "name": None, "pool": None}


def _send_msg(sock, obj):
    payload = pickle.dumps(obj)
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv_msg(sock):
    header = b""
    while len(header) < 4:
        chunk = sock.recv(4 - len(header))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        header += chunk
    n = struct.unpack(">I", header)[0]
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return pickle.loads(buf)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            req = _recv_msg(self.request)
        except ConnectionError:
            return
        if req.get("op") == "call":
            try:
                fn = req["fn"]
                result = fn(*req["args"], **req["kwargs"])
                _send_msg(self.request, {"ok": True, "value": result})
            except Exception as e:  # noqa: BLE001 - errors travel to caller
                _send_msg(self.request, {"ok": False, "error": repr(e)})
        elif req.get("op") == "ping":
            _send_msg(self.request, {"ok": True, "value": "pong"})


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start the local RPC server and rendezvous with peers.

    master_endpoint: "ip:port" of the TCPStore master (defaults to
    PADDLE_MASTER / PADDLE_TRAINER_ENDPOINTS env like the reference).
    """
    from ...runtime import TCPStore, TCPStoreServer

    rank = rank if rank is not None else int(os.environ.get("PADDLE_TRAINER_ID", 0))
    world_size = world_size or int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER", "127.0.0.1:29601")
    ip, port = master_endpoint.rsplit(":", 1)

    server = _Server(("127.0.0.1", 0), _Handler)
    my_port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    _state["server"] = server
    _state["name"] = name
    _state["pool"] = futures.ThreadPoolExecutor(max_workers=8)

    if rank == 0:
        store_server = TCPStoreServer(port=int(port))
        _state["store_server"] = store_server
    deadline = time.time() + 30
    store = None
    while time.time() < deadline:
        try:
            store = TCPStore(ip, int(port))
            break
        except (ConnectionError, OSError):
            time.sleep(0.05)
    if store is None:
        raise ConnectionError(f"rpc: cannot reach store at {master_endpoint}")

    info = WorkerInfo(name, rank, "127.0.0.1", my_port)
    store.set(f"rpc/{rank}", pickle.dumps(info))
    store.add("rpc/count", 1)
    while store.add("rpc/count", 0) < world_size:
        time.sleep(0.02)
    for r in range(world_size):
        peer = pickle.loads(store.get(f"rpc/{r}", timeout=30.0))
        _state["workers"][peer.name] = peer
    _state["store"] = store


def _call(to, fn, args, kwargs, timeout):
    peer = _state["workers"][to]
    with socket.create_connection((peer.ip, peer.port), timeout=timeout) as s:
        _send_msg(s, {"op": "call", "fn": fn, "args": args or (),
                      "kwargs": kwargs or {}})
        s.settimeout(timeout)
        resp = _recv_msg(s)
    if not resp["ok"]:
        raise RuntimeError(f"rpc to {to} failed: {resp['error']}")
    return resp["value"]


def rpc_sync(to, fn, args=None, kwargs=None, timeout=180.0):
    return _call(to, fn, args, kwargs, timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=180.0):
    return _state["pool"].submit(_call, to, fn, args, kwargs, timeout)


def shutdown():
    server = _state.get("server")
    if server is not None:
        server.shutdown()
        server.server_close()
    pool = _state.get("pool")
    if pool is not None:
        pool.shutdown(wait=False)
    _state["workers"].clear()
    _state["server"] = None


def get_worker_info(name):
    return _state["workers"][name]


def get_all_worker_infos():
    return list(_state["workers"].values())


def get_current_worker_info():
    return _state["workers"][_state["name"]]
