"""paddle.distributed.sharding parity (ref: python/paddle/distributed/sharding/)."""
from ..fleet.meta_parallel.sharding.group_sharded import (
    group_sharded_parallel, save_group_sharded_model)
