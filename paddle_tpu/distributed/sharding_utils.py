"""Sharding annotation plumbing shared by the fleet layers.

GSPMD design: parallel layers attach ``PartitionSpec``s to parameters
(``param.pspec``) and drop ``with_sharding_constraint`` hints on activations.
Eagerly (no mesh active) the hints are no-ops and every layer computes dense —
exactly the reference's single-card fallback. Inside a jitted step under
``use_mesh(mesh)`` XLA partitions the graph and inserts the ICI collectives
the reference issued manually through NCCL.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..observability import trace as _obs

P = PartitionSpec

_active_mesh: Optional[Mesh] = None


@contextlib.contextmanager
def auto_shard(mesh: Mesh):
    """Activate sharding hints for code traced inside this context.

    Hints are explicit NamedShardings, so no jax-level mesh context is needed;
    this just tells the hint() calls which mesh to target.
    """
    global _active_mesh
    prev = _active_mesh
    _active_mesh = mesh
    try:
        yield
    finally:
        _active_mesh = prev


def active_mesh() -> Optional[Mesh]:
    return _active_mesh


def hint(data, *spec):
    """with_sharding_constraint when a mesh is active, identity otherwise."""
    if _active_mesh is None:
        return data
    return jax.lax.with_sharding_constraint(
        data, NamedSharding(_active_mesh, P(*spec)))


def hint_tensor(tensor, *spec):
    from ..tensor.tensor import _run_op
    if _active_mesh is None:
        return tensor
    return _run_op("shard_hint", lambda a: hint(a, *spec), (tensor,), {})


def param_sharding(param, mesh: Mesh) -> NamedSharding:
    """The NamedSharding for a parameter, from its attached pspec."""
    spec = getattr(param, "pspec", None) or P()
    return NamedSharding(mesh, spec)


# ---------------------------------------------------------------------------
# Bucketed gradient synchronization (ref: DataParallel's EagerReducer /
# comm_buffer_size). Grads are grouped into size-capped buckets in REVERSE
# parameter order — the approximate order backward produces them — and each
# bucket is all-reduced as one fused collective. Inside the compiled step the
# buckets are independent ops whose operands become ready progressively
# during backward, so XLA's async collective scheduler overlaps each bucket's
# reduce with the remaining backward compute instead of one end-of-step
# barrier (and far fewer launches than per-parameter reduces).
# ---------------------------------------------------------------------------

def plan_grad_buckets(shapes: dict, cap_bytes: int, reverse: bool = True):
    """Group param names into size-capped buckets.

    shapes: {name: (shape_tuple, itemsize_bytes)}. Order of dict insertion is
    forward/creation order; ``reverse`` walks it backwards (reverse-
    topological, grads-ready-first). A single oversized grad gets its own
    bucket. Returns a list of name lists.
    """
    names = list(shapes)
    if reverse:
        names = names[::-1]
    buckets, cur, cur_bytes = [], [], 0
    for name in names:
        shape, itemsize = shapes[name]
        nbytes = int(itemsize)
        for d in shape:
            nbytes *= int(d)
        if cur and cur_bytes + nbytes > cap_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(name)
        cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def bucket_bytes(shapes: dict, buckets) -> list:
    """Per-bucket payload bytes for a ``plan_grad_buckets`` plan.

    shapes: {name: (shape_tuple, itemsize_bytes)} as given to the planner.
    Telemetry helper — the numbers the step log reports per grad-sync bucket.
    """
    sizes = []
    for bucket in buckets:
        total = 0
        for name in bucket:
            shape, itemsize = shapes[name]
            nbytes = int(itemsize)
            for d in shape:
                nbytes *= int(d)
            total += nbytes
        sizes.append(total)
    return sizes


def prefetch_param_gathers(params: dict, buckets, shardings: dict):
    """Stage-3 (ZeRO-3) parameter-gather prefetch, bucketed in FORWARD order.

    Left alone, GSPMD inserts each stage-3 param's all-gather right where the
    layer first consumes it — correct, but the gather sits on the critical
    path in front of its layer. Here each size-capped bucket of params gets
    its full (pre-ZeRO) sharding constraint applied up front, and bucket i's
    inputs are chained on bucket i-1's GATHERED values with an
    optimization_barrier: bucket i's all-gathers are free to run while bucket
    i-1's layers compute (one bucket ahead of first use, mirroring the
    reference stage-3 prefetch queue) but can't all pile up at step start —
    the barrier bounds in-flight gather memory to ~one bucket.

    Pure data-movement: sharding constraints and barriers never change
    values, so the step's loss is bit-identical to the non-prefetched stage 3.
    Each bucket's gather runs under a ``param_gather.bucketNN`` comm_span
    carrying the full gathered bytes.
    """
    out = dict(params)
    prev = None
    for i, bucket in enumerate(buckets):
        present = [n for n in bucket if n in params]
        if not present:
            continue
        vals = [params[n] for n in present]
        if prev is not None:
            chained = jax.lax.optimization_barrier(tuple(vals) + (prev,))
            vals = list(chained[:-1])
        nbytes = sum(v.size * v.dtype.itemsize for v in vals)
        with _obs.comm_span(f"param_gather.bucket{i:02d}", nbytes=nbytes,
                            site="param_gather.bucket"):
            gathered = [
                jax.lax.with_sharding_constraint(v, shardings[n])
                for v, n in zip(vals, present)]
        out.update(zip(present, gathered))
        prev = gathered[0]
    return out


def bucketed_psum(grads: dict, buckets, axis_names):
    """Per-bucket fused psum of a {name: grad} dict (call INSIDE shard_map).

    Each bucket is reduced as ONE variadic psum (XLA's combined all-reduce —
    many operands, one collective launch, no flatten/concat copies). psum is
    elementwise per leaf, so the result is bit-identical to per-parameter
    psums — bucketing changes the collective granularity, not the numerics.

    Each bucket's psum is traced under a named ``grad_sync.bucketNN`` span
    (observability.comm_span), so device profiles attribute every bucket's
    collective separately and counters carry the per-bucket local bytes.
    """
    out = dict(grads)
    for i, bucket in enumerate(buckets):
        present = [n for n in bucket if n in grads]
        if not present:
            continue
        nbytes = sum(grads[n].size * grads[n].dtype.itemsize
                     for n in present)
        with _obs.comm_span(f"grad_sync.bucket{i:02d}", nbytes=nbytes,
                            site="grad_sync.bucket"):
            reduced = jax.lax.psum(tuple(grads[n] for n in present),
                                   axis_names)
        out.update(zip(present, reduced))
    return out
