"""Sharding annotation plumbing shared by the fleet layers.

GSPMD design: parallel layers attach ``PartitionSpec``s to parameters
(``param.pspec``) and drop ``with_sharding_constraint`` hints on activations.
Eagerly (no mesh active) the hints are no-ops and every layer computes dense —
exactly the reference's single-card fallback. Inside a jitted step under
``use_mesh(mesh)`` XLA partitions the graph and inserts the ICI collectives
the reference issued manually through NCCL.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

_active_mesh: Optional[Mesh] = None


@contextlib.contextmanager
def auto_shard(mesh: Mesh):
    """Activate sharding hints for code traced inside this context.

    Hints are explicit NamedShardings, so no jax-level mesh context is needed;
    this just tells the hint() calls which mesh to target.
    """
    global _active_mesh
    prev = _active_mesh
    _active_mesh = mesh
    try:
        yield
    finally:
        _active_mesh = prev


def active_mesh() -> Optional[Mesh]:
    return _active_mesh


def hint(data, *spec):
    """with_sharding_constraint when a mesh is active, identity otherwise."""
    if _active_mesh is None:
        return data
    return jax.lax.with_sharding_constraint(
        data, NamedSharding(_active_mesh, P(*spec)))


def hint_tensor(tensor, *spec):
    from ..tensor.tensor import _run_op
    if _active_mesh is None:
        return tensor
    return _run_op("shard_hint", lambda a: hint(a, *spec), (tensor,), {})


def param_sharding(param, mesh: Mesh) -> NamedSharding:
    """The NamedSharding for a parameter, from its attached pspec."""
    spec = getattr(param, "pspec", None) or P()
    return NamedSharding(mesh, spec)
