"""ref: paddle.distributed.utils — MoE token-exchange primitives
(global_scatter / global_gather, the reference's expert-parallel ragged
all-to-all from distributed/utils/moe_utils.py).

TPU-native stance: XLA collectives are static-shape, so ragged token
exchange does not lower to a single collective; the first-class
expert-parallel path (paddle_tpu.parallel.moe) instead dispatches into
CAPACITY-PADDED buckets whose all-to-all is static — the design the
reference's gshard lineage also uses on TPU. These functions provide the
reference's eager single-world semantics (used by its unit tests and
single-rank paths) and point multi-rank callers at parallel.moe.
"""
from __future__ import annotations

import numpy as np


def _counts(x):
    return np.asarray(getattr(x, "_data", x)).astype(np.int64).ravel()


def _world(group):
    if group is not None:
        return group.nranks
    from ..env import get_world_size
    return get_world_size()


def global_scatter(x, local_count, global_count, group=None):
    """Tokens of x (grouped by destination expert, sizes in local_count)
    are exchanged so each rank holds the tokens for ITS experts (sizes in
    global_count). World size 1: the exchange is the identity on the
    token block (validated against the counts)."""
    lc, gc = _counts(local_count), _counts(global_count)
    if _world(group) > 1:
        raise NotImplementedError(
            "ragged global_scatter has no static-shape XLA lowering; "
            "multi-rank expert parallelism on TPU uses the capacity-"
            "bucketed dispatch in paddle_tpu.parallel.moe (all_to_all "
            "over the 'ep' mesh axis)")
    total = int(lc.sum())
    if int(gc.sum()) != total:
        raise ValueError(
            f"global_scatter: local_count sums to {total} but "
            f"global_count sums to {int(gc.sum())}")
    return x[:total] if total != x.shape[0] else x


def global_gather(x, local_count, global_count, group=None):
    """Inverse of global_scatter (experts' outputs return to the token
    owners)."""
    lc, gc = _counts(local_count), _counts(global_count)
    if _world(group) > 1:
        raise NotImplementedError(
            "ragged global_gather has no static-shape XLA lowering; "
            "multi-rank expert parallelism on TPU uses "
            "paddle_tpu.parallel.moe")
    total = int(gc.sum())
    if int(lc.sum()) != total:
        raise ValueError(
            f"global_gather: global_count sums to {total} but "
            f"local_count sums to {int(lc.sum())}")
    return x[:total] if total != x.shape[0] else x
