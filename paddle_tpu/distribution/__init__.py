"""paddle.distribution parity (ref: python/paddle/distribution/ †).

Distributions, bijective transforms, TransformedDistribution and the KL
registry, all over taped eager Tensors with reparameterized sampling where
jax's samplers are implicitly differentiable.
"""
from .distribution import Distribution  # noqa: F401
from .distributions import (  # noqa: F401
    Bernoulli, Beta, Binomial, Categorical, Cauchy, ContinuousBernoulli,
    Dirichlet, Exponential, Gamma, Geometric, Gumbel, Independent, Laplace,
    LogNormal, Multinomial, MultivariateNormal, Normal, Poisson, StudentT,
    Uniform,
)
from .transform import (  # noqa: F401
    AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    SoftmaxTransform, StackTransform, StickBreakingTransform, TanhTransform,
    Transform,
)
from .transformed_distribution import TransformedDistribution  # noqa: F401
from .kl import kl_divergence, register_kl  # noqa: F401
