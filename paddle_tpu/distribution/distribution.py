"""Distribution base class (ref: python/paddle/distribution/distribution.py †).

Probability distributions over eager Tensors. Parameters are stored as
Tensors; density methods run through ``_run_op`` so ``log_prob`` et al. are
differentiable w.r.t. the parameters (reparameterized ``rsample`` where the
sampler allows it — jax's gamma/dirichlet/normal samplers are implicitly
differentiable, which the CUDA reference cannot offer).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework import random as rnd
from ..tensor.tensor import Tensor, _run_op


def param(x, dtype=np.float32):
    """Coerce a distribution parameter to a Tensor (floats -> float32)."""
    if isinstance(x, Tensor):
        return x
    arr = np.asarray(x)
    if arr.dtype in (np.float64, np.int32, np.int64, int, float):
        arr = arr.astype(dtype)
    return Tensor(arr)


def _shape(t):
    return tuple(t._data.shape)


def broadcast_batch(*tensors):
    return tuple(np.broadcast_shapes(*[_shape(t) for t in tensors]))


def sum_rightmost(x, k):
    """Sum a Tensor over its rightmost ``k`` axes (taped)."""
    if k <= 0:
        return x
    return _run_op("sum_rightmost",
                   lambda a: a.sum(axis=tuple(range(a.ndim - k, a.ndim))),
                   (x,), {})


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    # -- sampling ----------------------------------------------------------
    def sample(self, shape=()):
        """Draw a detached sample of shape ``shape + batch_shape + event_shape``."""
        s = self.rsample(shape)
        return s.detach()

    def rsample(self, shape=()):
        raise NotImplementedError(
            f"{type(self).__name__} does not implement rsample")

    def _extended_shape(self, shape):
        return tuple(shape) + self._batch_shape + self._event_shape

    @staticmethod
    def _key():
        return rnd.next_key()

    # -- densities ---------------------------------------------------------
    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return _run_op("prob", jnp.exp, (self.log_prob(value),), {})

    probs = prob

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)

    def __repr__(self):
        return (f"{type(self).__name__}(batch_shape={self.batch_shape}, "
                f"event_shape={self.event_shape})")
