"""Concrete distributions (ref: python/paddle/distribution/{normal,uniform,
bernoulli,categorical,beta,dirichlet,gamma,exponential,laplace,gumbel,
lognormal,multinomial,geometric,cauchy,poisson,binomial,student_t,
multivariate_normal}.py †).

Continuous families are reparameterized (``rsample`` differentiates through
jax's implicit-gradient samplers); discrete families sample detached.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import jax.scipy.special as jss
import numpy as np

from ..tensor.tensor import Tensor, _run_op, unwrap
from .distribution import Distribution, broadcast_batch, param

__all__ = [
    "Normal", "LogNormal", "Uniform", "Exponential", "Gamma", "Beta",
    "Dirichlet", "Laplace", "Gumbel", "Cauchy", "StudentT", "Bernoulli",
    "ContinuousBernoulli", "Categorical", "Multinomial", "Binomial",
    "Geometric", "Poisson", "MultivariateNormal", "Independent",
]


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = param(loc)
        self.scale = param(scale)
        super().__init__(broadcast_batch(self.loc, self.scale))

    @property
    def mean(self):
        return self.loc

    @property
    def stddev(self):
        return self.scale

    @property
    def variance(self):
        return _run_op("square", jnp.square, (self.scale,), {})

    def rsample(self, shape=()):
        key = self._key()
        full = self._extended_shape(shape)
        return _run_op("normal_rsample",
                       lambda l, s: l + s * jax.random.normal(key, full, jnp.result_type(l, s)),
                       (self.loc, self.scale), {})

    def log_prob(self, value):
        def f(l, s, v):
            var = s ** 2
            return -((v - l) ** 2) / (2 * var) - jnp.log(s) - 0.5 * math.log(2 * math.pi)
        return _run_op("normal_log_prob", f, (self.loc, self.scale, param(value)), {})

    def entropy(self):
        return _run_op("normal_entropy",
                       lambda s: 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s)
                       + jnp.zeros(self._batch_shape, s.dtype),
                       (self.scale,), {})

    def cdf(self, value):
        def f(l, s, v):
            return 0.5 * (1.0 + jax.scipy.special.erf(
                (v - l) / (s * math.sqrt(2.0))))
        return _run_op("normal_cdf", f,
                       (self.loc, self.scale, param(value)), {})

    def icdf(self, q):
        def f(l, s, p):
            return l + s * math.sqrt(2.0) * jax.scipy.special.erfinv(
                2.0 * p - 1.0)
        return _run_op("normal_icdf", f,
                       (self.loc, self.scale, param(q)), {})


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = param(loc)
        self.scale = param(scale)
        self._base = Normal(self.loc, self.scale)
        super().__init__(self._base._batch_shape)

    @property
    def mean(self):
        return _run_op("lognormal_mean", lambda l, s: jnp.exp(l + s ** 2 / 2),
                       (self.loc, self.scale), {})

    @property
    def variance(self):
        return _run_op("lognormal_var",
                       lambda l, s: (jnp.exp(s ** 2) - 1) * jnp.exp(2 * l + s ** 2),
                       (self.loc, self.scale), {})

    def rsample(self, shape=()):
        base = self._base.rsample(shape)
        return _run_op("exp", jnp.exp, (base,), {})

    def log_prob(self, value):
        v = param(value)
        def f(l, s, v):
            logv = jnp.log(v)
            return (-((logv - l) ** 2) / (2 * s ** 2) - jnp.log(s)
                    - 0.5 * math.log(2 * math.pi) - logv)
        return _run_op("lognormal_log_prob", f, (self.loc, self.scale, v), {})

    def entropy(self):
        return _run_op("lognormal_entropy",
                       lambda l, s: l + 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
                       (self.loc, self.scale), {})


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = param(low)
        self.high = param(high)
        super().__init__(broadcast_batch(self.low, self.high))

    @property
    def mean(self):
        return _run_op("uniform_mean", lambda a, b: (a + b) / 2,
                       (self.low, self.high), {})

    @property
    def variance(self):
        return _run_op("uniform_var", lambda a, b: (b - a) ** 2 / 12,
                       (self.low, self.high), {})

    def rsample(self, shape=()):
        key = self._key()
        full = self._extended_shape(shape)
        return _run_op("uniform_rsample",
                       lambda a, b: a + (b - a) * jax.random.uniform(
                           key, full, jnp.result_type(a, b)),
                       (self.low, self.high), {})

    def log_prob(self, value):
        def f(a, b, v):
            inside = (v >= a) & (v < b)
            return jnp.where(inside, -jnp.log(b - a), -jnp.inf)
        return _run_op("uniform_log_prob", f, (self.low, self.high, param(value)), {})

    def entropy(self):
        return _run_op("uniform_entropy", lambda a, b: jnp.log(b - a),
                       (self.low, self.high), {})


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = param(rate)
        super().__init__(broadcast_batch(self.rate))

    @property
    def mean(self):
        return _run_op("exp_mean", lambda r: 1 / r, (self.rate,), {})

    @property
    def variance(self):
        return _run_op("exp_var", lambda r: 1 / r ** 2, (self.rate,), {})

    def rsample(self, shape=()):
        key = self._key()
        full = self._extended_shape(shape)
        return _run_op("exponential_rsample",
                       lambda r: jax.random.exponential(key, full, r.dtype) / r,
                       (self.rate,), {})

    def log_prob(self, value):
        return _run_op("exponential_log_prob",
                       lambda r, v: jnp.log(r) - r * v, (self.rate, param(value)), {})

    def entropy(self):
        return _run_op("exponential_entropy", lambda r: 1 - jnp.log(r),
                       (self.rate,), {})


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = param(concentration)
        self.rate = param(rate)
        super().__init__(broadcast_batch(self.concentration, self.rate))

    @property
    def mean(self):
        return _run_op("gamma_mean", lambda c, r: c / r,
                       (self.concentration, self.rate), {})

    @property
    def variance(self):
        return _run_op("gamma_var", lambda c, r: c / r ** 2,
                       (self.concentration, self.rate), {})

    def rsample(self, shape=()):
        key = self._key()
        full = self._extended_shape(shape)
        return _run_op("gamma_rsample",
                       lambda c, r: jax.random.gamma(
                           key, jnp.broadcast_to(c, full), full) / r,
                       (self.concentration, self.rate), {})

    def log_prob(self, value):
        def f(c, r, v):
            return (c * jnp.log(r) + (c - 1) * jnp.log(v) - r * v - jss.gammaln(c))
        return _run_op("gamma_log_prob", f,
                       (self.concentration, self.rate, param(value)), {})

    def entropy(self):
        def f(c, r):
            return c - jnp.log(r) + jss.gammaln(c) + (1 - c) * jss.digamma(c)
        return _run_op("gamma_entropy", f, (self.concentration, self.rate), {})


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = param(alpha)
        self.beta = param(beta)
        super().__init__(broadcast_batch(self.alpha, self.beta))

    @property
    def mean(self):
        return _run_op("beta_mean", lambda a, b: a / (a + b),
                       (self.alpha, self.beta), {})

    @property
    def variance(self):
        return _run_op("beta_var",
                       lambda a, b: a * b / ((a + b) ** 2 * (a + b + 1)),
                       (self.alpha, self.beta), {})

    def rsample(self, shape=()):
        key1, key2 = jax.random.split(self._key())
        full = self._extended_shape(shape)

        def f(a, b):
            ga = jax.random.gamma(key1, jnp.broadcast_to(a, full), full)
            gb = jax.random.gamma(key2, jnp.broadcast_to(b, full), full)
            return ga / (ga + gb)
        return _run_op("beta_rsample", f, (self.alpha, self.beta), {})

    def log_prob(self, value):
        def f(a, b, v):
            return ((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
                    - (jss.gammaln(a) + jss.gammaln(b) - jss.gammaln(a + b)))
        return _run_op("beta_log_prob", f, (self.alpha, self.beta, param(value)), {})

    def entropy(self):
        def f(a, b):
            total = a + b
            return (jss.gammaln(a) + jss.gammaln(b) - jss.gammaln(total)
                    - (a - 1) * jss.digamma(a) - (b - 1) * jss.digamma(b)
                    + (total - 2) * jss.digamma(total))
        return _run_op("beta_entropy", f, (self.alpha, self.beta), {})


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = param(concentration)
        shape = tuple(self.concentration._data.shape)
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return _run_op("dirichlet_mean",
                       lambda c: c / c.sum(-1, keepdims=True),
                       (self.concentration,), {})

    @property
    def variance(self):
        def f(c):
            a0 = c.sum(-1, keepdims=True)
            m = c / a0
            return m * (1 - m) / (a0 + 1)
        return _run_op("dirichlet_var", f, (self.concentration,), {})

    def rsample(self, shape=()):
        key = self._key()
        full = self._extended_shape(shape)

        def f(c):
            g = jax.random.gamma(key, jnp.broadcast_to(c, full), full)
            return g / g.sum(-1, keepdims=True)
        return _run_op("dirichlet_rsample", f, (self.concentration,), {})

    def log_prob(self, value):
        def f(c, v):
            return (((c - 1) * jnp.log(v)).sum(-1)
                    + jss.gammaln(c.sum(-1)) - jss.gammaln(c).sum(-1))
        return _run_op("dirichlet_log_prob", f,
                       (self.concentration, param(value)), {})

    def entropy(self):
        def f(c):
            a0 = c.sum(-1)
            k = c.shape[-1]
            return (jss.gammaln(c).sum(-1) - jss.gammaln(a0)
                    + (a0 - k) * jss.digamma(a0)
                    - ((c - 1) * jss.digamma(c)).sum(-1))
        return _run_op("dirichlet_entropy", f, (self.concentration,), {})


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = param(loc)
        self.scale = param(scale)
        super().__init__(broadcast_batch(self.loc, self.scale))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return _run_op("laplace_var", lambda s: 2 * s ** 2, (self.scale,), {})

    @property
    def stddev(self):
        return _run_op("laplace_std", lambda s: math.sqrt(2) * s, (self.scale,), {})

    def rsample(self, shape=()):
        key = self._key()
        full = self._extended_shape(shape)

        def f(l, s):
            u = jax.random.uniform(key, full, s.dtype, -1 + 1e-7, 1.0)
            return l - s * jnp.sign(u) * jnp.log1p(-jnp.abs(u))
        return _run_op("laplace_rsample", f, (self.loc, self.scale), {})

    def log_prob(self, value):
        return _run_op("laplace_log_prob",
                       lambda l, s, v: -jnp.abs(v - l) / s - jnp.log(2 * s),
                       (self.loc, self.scale, param(value)), {})

    def entropy(self):
        return _run_op("laplace_entropy", lambda s: 1 + jnp.log(2 * s),
                       (self.scale,), {})

    def cdf(self, value):
        def f(l, s, v):
            z = (v - l) / s
            return 0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z))
        return _run_op("laplace_cdf", f, (self.loc, self.scale, param(value)), {})

    def icdf(self, q):
        def f(l, s, p):
            t = p - 0.5
            return l - s * jnp.sign(t) * jnp.log1p(-2 * jnp.abs(t))
        return _run_op("laplace_icdf", f, (self.loc, self.scale, param(q)), {})


class Gumbel(Distribution):
    _EULER = 0.57721566490153286060

    def __init__(self, loc, scale, name=None):
        self.loc = param(loc)
        self.scale = param(scale)
        super().__init__(broadcast_batch(self.loc, self.scale))

    @property
    def mean(self):
        return _run_op("gumbel_mean", lambda l, s: l + self._EULER * s,
                       (self.loc, self.scale), {})

    @property
    def variance(self):
        return _run_op("gumbel_var", lambda s: (math.pi ** 2 / 6) * s ** 2,
                       (self.scale,), {})

    def rsample(self, shape=()):
        key = self._key()
        full = self._extended_shape(shape)
        return _run_op("gumbel_rsample",
                       lambda l, s: l + s * jax.random.gumbel(key, full, s.dtype),
                       (self.loc, self.scale), {})

    def log_prob(self, value):
        def f(l, s, v):
            z = (v - l) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)
        return _run_op("gumbel_log_prob", f, (self.loc, self.scale, param(value)), {})

    def entropy(self):
        return _run_op("gumbel_entropy", lambda s: jnp.log(s) + 1 + self._EULER,
                       (self.scale,), {})


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = param(loc)
        self.scale = param(scale)
        super().__init__(broadcast_batch(self.loc, self.scale))

    def rsample(self, shape=()):
        key = self._key()
        full = self._extended_shape(shape)
        return _run_op("cauchy_rsample",
                       lambda l, s: l + s * jax.random.cauchy(key, full, s.dtype),
                       (self.loc, self.scale), {})

    def log_prob(self, value):
        def f(l, s, v):
            return (-math.log(math.pi) - jnp.log(s)
                    - jnp.log1p(((v - l) / s) ** 2))
        return _run_op("cauchy_log_prob", f, (self.loc, self.scale, param(value)), {})

    def entropy(self):
        return _run_op("cauchy_entropy", lambda s: math.log(4 * math.pi) + jnp.log(s),
                       (self.scale,), {})

    def cdf(self, value):
        def f(l, s, v):
            return jnp.arctan((v - l) / s) / math.pi + 0.5
        return _run_op("cauchy_cdf", f, (self.loc, self.scale, param(value)), {})


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = param(df)
        self.loc = param(loc)
        self.scale = param(scale)
        super().__init__(broadcast_batch(self.df, self.loc, self.scale))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        def f(df, s):
            return jnp.where(df > 2, s ** 2 * df / (df - 2), jnp.inf)
        return _run_op("studentt_var", f, (self.df, self.scale), {})

    def rsample(self, shape=()):
        key = self._key()
        full = self._extended_shape(shape)

        def f(df, l, s):
            t = jax.random.t(key, jnp.broadcast_to(df, full), full, s.dtype)
            return l + s * t
        return _run_op("studentt_rsample", f, (self.df, self.loc, self.scale), {})

    def log_prob(self, value):
        def f(df, l, s, v):
            z = (v - l) / s
            return (jss.gammaln((df + 1) / 2) - jss.gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(s)
                    - (df + 1) / 2 * jnp.log1p(z ** 2 / df))
        return _run_op("studentt_log_prob", f,
                       (self.df, self.loc, self.scale, param(value)), {})

    def entropy(self):
        def f(df, s):
            h = ((df + 1) / 2 * (jss.digamma((df + 1) / 2) - jss.digamma(df / 2))
                 + 0.5 * jnp.log(df) + jss.betaln(df / 2, 0.5))
            return h + jnp.log(s)
        return _run_op("studentt_entropy", f, (self.df, self.scale), {})


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs / logits")
        if probs is not None:
            self.probs_param = param(probs)
            self.logits = _run_op("logit",
                                  lambda p: jnp.log(p) - jnp.log1p(-p),
                                  (self.probs_param,), {})
        else:
            self.logits = param(logits)
            self.probs_param = _run_op("sigmoid", jax.nn.sigmoid, (self.logits,), {})
        super().__init__(broadcast_batch(self.logits))

    @property
    def mean(self):
        return self.probs_param

    @property
    def variance(self):
        return _run_op("bern_var", lambda p: p * (1 - p), (self.probs_param,), {})

    def sample(self, shape=()):
        key = self._key()
        full = self._extended_shape(shape)
        data = jax.random.bernoulli(key, unwrap(self.probs_param), full)
        return Tensor._from_data(data.astype(jnp.float32))

    def rsample(self, shape=(), temperature=1.0):
        """Gumbel-sigmoid relaxation (ref exposes rsample via temperature)."""
        key = self._key()
        full = self._extended_shape(shape)

        def f(lg):
            u = jax.random.uniform(key, full, lg.dtype, 1e-6, 1 - 1e-6)
            g = jnp.log(u) - jnp.log1p(-u)
            return jax.nn.sigmoid((lg + g) / temperature)
        return _run_op("bernoulli_rsample", f, (self.logits,), {})

    def log_prob(self, value):
        def f(lg, v):
            return v * jax.nn.log_sigmoid(lg) + (1 - v) * jax.nn.log_sigmoid(-lg)
        return _run_op("bernoulli_log_prob", f, (self.logits, param(value)), {})

    def entropy(self):
        def f(lg):
            p = jax.nn.sigmoid(lg)
            return -(p * jax.nn.log_sigmoid(lg) + (1 - p) * jax.nn.log_sigmoid(-lg))
        return _run_op("bernoulli_entropy", f, (self.logits,), {})


class ContinuousBernoulli(Distribution):
    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs_param = param(probs)
        self._lims = lims
        super().__init__(broadcast_batch(self.probs_param))

    def _log_norm(self, p):
        # log C(p); taylor fallback near p=0.5 for numerical stability
        lo, hi = self._lims
        safe = jnp.clip(p, 1e-6, 1 - 1e-6)
        cut = (safe < lo) | (safe > hi)
        pc = jnp.where(cut, safe, 0.499)
        log_norm = jnp.log(jnp.abs(2 * jnp.arctanh(1 - 2 * pc))) - jnp.log(
            jnp.abs(1 - 2 * pc))
        taylor = math.log(2.0) + 4 / 3 * (p - 0.5) ** 2
        return jnp.where(cut, log_norm, taylor)

    def log_prob(self, value):
        def f(p, v):
            return (v * jnp.log(jnp.clip(p, 1e-6)) +
                    (1 - v) * jnp.log(jnp.clip(1 - p, 1e-6)) + self._log_norm(p))
        return _run_op("cb_log_prob", f, (self.probs_param, param(value)), {})

    def sample(self, shape=()):
        key = self._key()
        full = self._extended_shape(shape)

        def icdf(p, u):
            safe = jnp.clip(p, 1e-6, 1 - 1e-6)
            cut = (safe < self._lims[0]) | (safe > self._lims[1])
            pc = jnp.where(cut, safe, 0.4)
            x = (jnp.log1p(u * (2 * pc - 1) / (1 - pc)) /
                 (jnp.log(pc) - jnp.log1p(-pc)))
            return jnp.where(cut, x, u)
        p = unwrap(self.probs_param)
        u = jax.random.uniform(key, full, p.dtype if hasattr(p, "dtype") else jnp.float32)
        return Tensor._from_data(icdf(p, u))


class Categorical(Distribution):
    """Categorical over the last axis (ref: distribution/categorical.py).

    Reference semantics: ``logits`` are UNNORMALIZED NON-NEGATIVE weights,
    normalized by their sum (NOT softmax) — `Categorical([0.5, 0.5, 0.0])`
    never samples class 2. ``probs=`` is an alias for the same weights.
    """

    def __init__(self, logits=None, probs=None, name=None):
        if (logits is None) == (probs is None):
            raise ValueError("pass exactly one of logits / probs")
        w = param(probs if probs is not None else logits)
        self.probs_param = _run_op(
            "normalize_weights", lambda p: p / p.sum(-1, keepdims=True),
            (w,), {})
        self.logits = _run_op("log", jnp.log, (self.probs_param,), {})
        shape = tuple(self.logits._data.shape)
        super().__init__(shape[:-1])
        self._num_events = shape[-1]

    @property
    def mean(self):
        raise NotImplementedError("Categorical has no mean")

    def sample(self, shape=()):
        key = self._key()
        full = tuple(shape) + self._batch_shape
        data = jax.random.categorical(key, unwrap(self.logits), shape=full)
        return Tensor._from_data(data)

    def log_prob(self, value):
        # self.logits are already normalized log-probs (log_softmax would
        # be an identity plus a wasted logsumexp)
        def f(logp, v):
            logp = jnp.broadcast_to(logp, v.shape + logp.shape[-1:])
            return jnp.take_along_axis(
                logp, v[..., None].astype(jnp.int32), axis=-1)[..., 0]
        return _run_op("categorical_log_prob", f, (self.logits, param(value)), {})

    def entropy(self):
        def f(p):
            # 0 * log(0) -> 0, not NaN (zero-probability classes)
            return -jnp.sum(jnp.where(p > 0, p * jnp.log(
                jnp.maximum(p, 1e-38)), 0.0), -1)
        return _run_op("categorical_entropy", f, (self.probs_param,), {})


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_param = param(probs)
        shape = tuple(self.probs_param._data.shape)
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return _run_op("multinomial_mean",
                       lambda p: self.total_count * p / p.sum(-1, keepdims=True),
                       (self.probs_param,), {})

    def sample(self, shape=()):
        """Conditional-binomial chain: O(batch*K) memory regardless of
        total_count (a one-hot over total_count draws would be O(N*batch*K))."""
        p = unwrap(self.probs_param)
        full = tuple(shape) + self._batch_shape
        pn = p / p.sum(-1, keepdims=True)
        k = pn.shape[-1]
        remaining = jnp.full(full, float(self.total_count), jnp.float32)
        tail = jnp.ones(full, jnp.float32)  # P(category >= i)
        counts = []
        for i in range(k - 1):
            pi = jnp.broadcast_to(pn[..., i], full)
            cond = jnp.clip(pi / jnp.clip(tail, 1e-12), 0.0, 1.0)
            # f64 args: jax's binomial internals clamp with weak float
            # literals (f64 under the package-global x64), so f32 args trip
            # lax.clamp's same-dtype check
            ci = jax.random.binomial(self._key(),
                                     remaining.astype(jnp.float64),
                                     cond.astype(jnp.float64),
                                     shape=full).astype(jnp.float32)
            counts.append(ci)
            remaining = remaining - ci
            tail = tail - pi
        counts.append(remaining)
        return Tensor._from_data(jnp.stack(counts, -1))

    def log_prob(self, value):
        def f(p, v):
            pn = p / p.sum(-1, keepdims=True)
            return (jss.gammaln(v.sum(-1) + 1) - jss.gammaln(v + 1).sum(-1)
                    + (v * jnp.log(pn)).sum(-1))
        return _run_op("multinomial_log_prob", f,
                       (self.probs_param, param(value)), {})

    def entropy(self):
        """Monte-Carlo-free upper bound is not in the reference; compute the
        exact sum only for small total_count via sampling approximation."""
        samples = self.sample((128,))
        lp = self.log_prob(samples)
        return _run_op("mean0", lambda a: -a.mean(0), (lp,), {})


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = param(total_count, dtype=np.float32)
        self.probs_param = param(probs)
        super().__init__(broadcast_batch(self.total_count, self.probs_param))

    @property
    def mean(self):
        return _run_op("binomial_mean", lambda n, p: n * p,
                       (self.total_count, self.probs_param), {})

    @property
    def variance(self):
        return _run_op("binomial_var", lambda n, p: n * p * (1 - p),
                       (self.total_count, self.probs_param), {})

    def sample(self, shape=()):
        key = self._key()
        full = self._extended_shape(shape)
        n = unwrap(self.total_count)
        p = unwrap(self.probs_param)
        data = jax.random.binomial(
            key, jnp.broadcast_to(n, full).astype(jnp.float64),
            jnp.broadcast_to(p, full).astype(jnp.float64), shape=full)
        return Tensor._from_data(data.astype(jnp.float32))

    def log_prob(self, value):
        def f(n, p, v):
            return (jss.gammaln(n + 1) - jss.gammaln(v + 1) - jss.gammaln(n - v + 1)
                    + v * jnp.log(jnp.clip(p, 1e-9))
                    + (n - v) * jnp.log(jnp.clip(1 - p, 1e-9)))
        return _run_op("binomial_log_prob", f,
                       (self.total_count, self.probs_param, param(value)), {})


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p for k = 0, 1, 2, … (failures before first success)."""

    def __init__(self, probs, name=None):
        self.probs_param = param(probs)
        super().__init__(broadcast_batch(self.probs_param))

    @property
    def mean(self):
        return _run_op("geom_mean", lambda p: (1 - p) / p, (self.probs_param,), {})

    @property
    def variance(self):
        return _run_op("geom_var", lambda p: (1 - p) / p ** 2,
                       (self.probs_param,), {})

    def sample(self, shape=()):
        key = self._key()
        full = self._extended_shape(shape)
        p = unwrap(self.probs_param)
        u = jax.random.uniform(key, full, jnp.float32, 1e-7, 1.0)
        data = jnp.floor(jnp.log(u) / jnp.log1p(-p))
        return Tensor._from_data(data)

    def log_prob(self, value):
        return _run_op("geom_log_prob",
                       lambda p, v: v * jnp.log1p(-p) + jnp.log(p),
                       (self.probs_param, param(value)), {})

    def entropy(self):
        def f(p):
            q = 1 - p
            return -(q * jnp.log(q) + p * jnp.log(p)) / p
        return _run_op("geom_entropy", f, (self.probs_param,), {})


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = param(rate)
        super().__init__(broadcast_batch(self.rate))

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        key = self._key()
        full = self._extended_shape(shape)
        data = jax.random.poisson(key, unwrap(self.rate), full)
        return Tensor._from_data(data.astype(jnp.float32))

    def log_prob(self, value):
        return _run_op("poisson_log_prob",
                       lambda r, v: v * jnp.log(r) - r - jss.gammaln(v + 1),
                       (self.rate, param(value)), {})


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, scale_tril=None, name=None):
        self.loc = param(loc)
        if (covariance_matrix is None) == (scale_tril is None):
            raise ValueError("pass exactly one of covariance_matrix / scale_tril")
        if covariance_matrix is not None:
            self.covariance_matrix = param(covariance_matrix)
            self.scale_tril = _run_op("cholesky", jnp.linalg.cholesky,
                                      (self.covariance_matrix,), {})
        else:
            self.scale_tril = param(scale_tril)
            self.covariance_matrix = _run_op(
                "mvn_cov", lambda L: L @ jnp.swapaxes(L, -1, -2),
                (self.scale_tril,), {})
        d = self.loc._data.shape[-1]
        batch = np.broadcast_shapes(self.loc._data.shape[:-1],
                                    self.scale_tril._data.shape[:-2])
        super().__init__(tuple(batch), (d,))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return _run_op("mvn_var",
                       lambda L: jnp.square(L).sum(-1),
                       (self.scale_tril,), {})

    def rsample(self, shape=()):
        key = self._key()
        full = self._extended_shape(shape)

        def f(l, L):
            eps = jax.random.normal(key, full, L.dtype)
            return l + jnp.einsum("...ij,...j->...i", L, eps)
        return _run_op("mvn_rsample", f, (self.loc, self.scale_tril), {})

    def log_prob(self, value):
        def f(l, L, v):
            d = l.shape[-1]
            diff = v - l
            sol = jax.scipy.linalg.solve_triangular(
                jnp.broadcast_to(L, diff.shape[:-1] + L.shape[-2:]),
                diff[..., None], lower=True)[..., 0]
            maha = jnp.square(sol).sum(-1)
            logdet = jnp.log(jnp.abs(jnp.diagonal(L, axis1=-2, axis2=-1))).sum(-1)
            return -0.5 * (maha + d * math.log(2 * math.pi)) - logdet
        return _run_op("mvn_log_prob", f,
                       (self.loc, self.scale_tril, param(value)), {})

    def entropy(self):
        def f(L):
            d = L.shape[-1]
            logdet = jnp.log(jnp.abs(jnp.diagonal(L, axis1=-2, axis2=-1))).sum(-1)
            return 0.5 * d * (1 + math.log(2 * math.pi)) + logdet
        return _run_op("mvn_entropy", f, (self.scale_tril,), {})


class Independent(Distribution):
    """Reinterpret the rightmost ``reinterpreted_batch_rank`` batch dims as
    event dims (ref: python/paddle/distribution/independent.py †)."""

    def __init__(self, base, reinterpreted_batch_rank, name=None):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        b = tuple(base._batch_shape)
        k = self.reinterpreted_batch_rank
        if k > len(b):
            raise ValueError("reinterpreted_batch_rank exceeds batch rank")
        super().__init__(b[:len(b) - k], b[len(b) - k:] + tuple(base._event_shape))

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        from .distribution import sum_rightmost
        return sum_rightmost(self.base.log_prob(value),
                             self.reinterpreted_batch_rank)

    def entropy(self):
        from .distribution import sum_rightmost
        return sum_rightmost(self.base.entropy(),
                             self.reinterpreted_batch_rank)
