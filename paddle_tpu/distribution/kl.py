"""KL divergences (ref: python/paddle/distribution/kl.py †).

``register_kl`` dispatch by (type(p), type(q)) with MRO-aware lookup, closed
forms for the standard pairs, exactly like the reference's registry.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import jax.scipy.special as jss

from ..tensor.tensor import _run_op
from . import distributions as D

__all__ = ["kl_divergence", "register_kl"]

_REGISTRY = {}


def register_kl(cls_p, cls_q):
    def deco(fn):
        _REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return deco


def _dispatch(tp, tq):
    if (tp, tq) in _REGISTRY:
        return _REGISTRY[(tp, tq)]
    matches = [(p, q) for (p, q) in _REGISTRY
               if issubclass(tp, p) and issubclass(tq, q)]
    if not matches:
        raise NotImplementedError(
            f"no KL(p||q) registered for ({tp.__name__}, {tq.__name__})")
    # most-derived match wins
    matches.sort(key=lambda pq: (len(tp.__mro__) - tp.__mro__.index(pq[0]),
                                 len(tq.__mro__) - tq.__mro__.index(pq[1])),
                 reverse=True)
    return _REGISTRY[matches[0]]


def kl_divergence(p, q):
    return _dispatch(type(p), type(q))(p, q)


@register_kl(D.Normal, D.Normal)
def _kl_normal_normal(p, q):
    def f(l1, s1, l2, s2):
        vr = (s1 / s2) ** 2
        return 0.5 * (vr + ((l1 - l2) / s2) ** 2 - 1 - jnp.log(vr))
    return _run_op("kl_normal", f, (p.loc, p.scale, q.loc, q.scale), {})


@register_kl(D.LogNormal, D.LogNormal)
def _kl_lognormal(p, q):
    return _kl_normal_normal(p._base, q._base)


@register_kl(D.Uniform, D.Uniform)
def _kl_uniform(p, q):
    def f(a1, b1, a2, b2):
        ratio = jnp.log((b2 - a2) / (b1 - a1))
        return jnp.where((a2 <= a1) & (b1 <= b2), ratio, jnp.inf)
    return _run_op("kl_uniform", f, (p.low, p.high, q.low, q.high), {})


@register_kl(D.Exponential, D.Exponential)
def _kl_exponential(p, q):
    def f(r1, r2):
        rr = r2 / r1
        return rr - 1 - jnp.log(rr)
    return _run_op("kl_exponential", f, (p.rate, q.rate), {})


@register_kl(D.Gamma, D.Gamma)
def _kl_gamma(p, q):
    def f(c1, r1, c2, r2):
        return ((c1 - c2) * jss.digamma(c1) - jss.gammaln(c1) + jss.gammaln(c2)
                + c2 * (jnp.log(r1) - jnp.log(r2)) + c1 * (r2 / r1 - 1))
    return _run_op("kl_gamma", f,
                   (p.concentration, p.rate, q.concentration, q.rate), {})


@register_kl(D.Beta, D.Beta)
def _kl_beta(p, q):
    def f(a1, b1, a2, b2):
        t1 = jss.gammaln(a2) + jss.gammaln(b2) - jss.gammaln(a2 + b2)
        t2 = jss.gammaln(a1) + jss.gammaln(b1) - jss.gammaln(a1 + b1)
        return (t1 - t2 + (a1 - a2) * jss.digamma(a1)
                + (b1 - b2) * jss.digamma(b1)
                + (a2 - a1 + b2 - b1) * jss.digamma(a1 + b1))
    return _run_op("kl_beta", f, (p.alpha, p.beta, q.alpha, q.beta), {})


@register_kl(D.Dirichlet, D.Dirichlet)
def _kl_dirichlet(p, q):
    def f(c1, c2):
        a0 = c1.sum(-1)
        return (jss.gammaln(a0) - jss.gammaln(c1).sum(-1)
                - jss.gammaln(c2.sum(-1)) + jss.gammaln(c2).sum(-1)
                + ((c1 - c2) * (jss.digamma(c1)
                                - jss.digamma(a0)[..., None])).sum(-1))
    return _run_op("kl_dirichlet", f, (p.concentration, q.concentration), {})


@register_kl(D.Bernoulli, D.Bernoulli)
def _kl_bernoulli(p, q):
    def f(p1, p2):
        p1c = jnp.clip(p1, 1e-7, 1 - 1e-7)
        p2c = jnp.clip(p2, 1e-7, 1 - 1e-7)
        return (p1c * (jnp.log(p1c) - jnp.log(p2c))
                + (1 - p1c) * (jnp.log1p(-p1c) - jnp.log1p(-p2c)))
    return _run_op("kl_bernoulli", f, (p.probs_param, q.probs_param), {})


@register_kl(D.Categorical, D.Categorical)
def _kl_categorical(p, q):
    def f(l1, l2):
        lp1 = l1 - jss.logsumexp(l1, -1, keepdims=True)
        lp2 = l2 - jss.logsumexp(l2, -1, keepdims=True)
        return (jnp.exp(lp1) * (lp1 - lp2)).sum(-1)
    return _run_op("kl_categorical", f, (p.logits, q.logits), {})


@register_kl(D.Laplace, D.Laplace)
def _kl_laplace(p, q):
    def f(l1, s1, l2, s2):
        d = jnp.abs(l1 - l2)
        return (jnp.log(s2 / s1) + (s1 * jnp.exp(-d / s1) + d) / s2 - 1)
    return _run_op("kl_laplace", f, (p.loc, p.scale, q.loc, q.scale), {})


@register_kl(D.Geometric, D.Geometric)
def _kl_geometric(p, q):
    def f(p1, p2):
        return (-(1 - p1) / p1 * (jnp.log1p(-p2) - jnp.log1p(-p1))
                + jnp.log(p1) - jnp.log(p2))
    return _run_op("kl_geometric", f, (p.probs_param, q.probs_param), {})


@register_kl(D.Poisson, D.Poisson)
def _kl_poisson(p, q):
    def f(r1, r2):
        return r1 * (jnp.log(r1) - jnp.log(r2)) - r1 + r2
    return _run_op("kl_poisson", f, (p.rate, q.rate), {})


@register_kl(D.Gumbel, D.Gumbel)
def _kl_gumbel(p, q):
    # KL = log(s2/s1) + γ·(s1/s2 - 1) + (l1-l2)/s2 + Γ(1+s1/s2)·e^{(l2-l1)/s2} - 1
    def g(l1, s1, l2, s2):
        ratio = s1 / s2
        return (jnp.log(s2) - jnp.log(s1) + D.Gumbel._EULER * (ratio - 1) - 1
                + (l1 - l2) / s2
                + jnp.exp(jss.gammaln(1 + ratio) + (l2 - l1) / s2))
    return _run_op("kl_gumbel", g, (p.loc, p.scale, q.loc, q.scale), {})


@register_kl(D.MultivariateNormal, D.MultivariateNormal)
def _kl_mvn(p, q):
    import jax
    def f(l1, L1, l2, L2):
        d = l1.shape[-1]
        # tr(S2^-1 S1) via triangular solves against L2
        M = jax.scipy.linalg.solve_triangular(
            jnp.broadcast_to(L2, L1.shape), jnp.broadcast_to(L1, L1.shape),
            lower=True)
        tr = jnp.square(M).sum((-2, -1))
        diff = l2 - l1
        sol = jax.scipy.linalg.solve_triangular(
            jnp.broadcast_to(L2, diff.shape[:-1] + L2.shape[-2:]),
            diff[..., None], lower=True)[..., 0]
        maha = jnp.square(sol).sum(-1)
        ld1 = jnp.log(jnp.abs(jnp.diagonal(L1, axis1=-2, axis2=-1))).sum(-1)
        ld2 = jnp.log(jnp.abs(jnp.diagonal(L2, axis1=-2, axis2=-1))).sum(-1)
        return 0.5 * (tr + maha - d) + ld2 - ld1
    return _run_op("kl_mvn", f, (p.loc, p.scale_tril, q.loc, q.scale_tril), {})


@register_kl(D.Independent, D.Independent)
def _kl_independent(p, q):
    if p.reinterpreted_batch_rank != q.reinterpreted_batch_rank:
        raise NotImplementedError("mismatched reinterpreted_batch_rank")
    from .distribution import sum_rightmost
    return sum_rightmost(kl_divergence(p.base, q.base),
                         p.reinterpreted_batch_rank)
