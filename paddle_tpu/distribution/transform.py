"""Bijective transforms (ref: python/paddle/distribution/transform.py †).

Each transform provides forward/inverse maps and log|det J| in both
directions, all as taped eager ops so normalizing-flow stacks train with
autograd. Variable names and the public set match the reference:
Abs, Affine, Chain, Exp, Independent, Power, Reshape, Sigmoid, Softmax,
Stack, StickBreaking, Tanh.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor, _run_op
from .distribution import param

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


class Transform:
    _event_rank = 0  # rank of the event this transform acts on

    # domain/codomain event ranks; differ only for shape-changing transforms
    @property
    def _domain_rank(self):
        return self._event_rank

    @property
    def _codomain_rank(self):
        return self._event_rank

    def forward(self, x):
        return _run_op(f"{type(self).__name__}_fwd", self._forward, (x,), {})

    def inverse(self, y):
        return _run_op(f"{type(self).__name__}_inv", self._inverse, (y,), {})

    def forward_log_det_jacobian(self, x):
        return _run_op(f"{type(self).__name__}_fldj", self._fldj, (x,), {})

    def inverse_log_det_jacobian(self, y):
        # via the public methods so subclasses that only override those
        # (Affine, Power, Chain, Stack, Independent) inherit a working ildj
        x = self.inverse(y)
        ldj = self.forward_log_det_jacobian(x)
        return _run_op("neg", lambda a: -a, (ldj,), {})

    def forward_shape(self, shape):
        return list(shape)

    def inverse_shape(self, shape):
        return list(shape)

    def __call__(self, x):
        return self.forward(x)

    # jnp-level implementations
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _fldj(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # right inverse (the positive branch), like the reference


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = param(loc)
        self.scale = param(scale)

    def forward(self, x):
        return _run_op("affine_fwd", lambda l, s, x_: l + s * x_,
                       (self.loc, self.scale, x), {})

    def inverse(self, y):
        return _run_op("affine_inv", lambda l, s, y_: (y_ - l) / s,
                       (self.loc, self.scale, y), {})

    def forward_log_det_jacobian(self, x):
        return _run_op("affine_fldj",
                       lambda s, x_: jnp.broadcast_to(jnp.log(jnp.abs(s)), x_.shape),
                       (self.scale, x), {})

    def inverse_log_det_jacobian(self, y):
        return _run_op("affine_ildj",
                       lambda s, y_: jnp.broadcast_to(-jnp.log(jnp.abs(s)), y_.shape),
                       (self.scale, y), {})


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = param(power)

    def forward(self, x):
        return _run_op("power_fwd", lambda p, x_: jnp.power(x_, p),
                       (self.power, x), {})

    def inverse(self, y):
        return _run_op("power_inv", lambda p, y_: jnp.power(y_, 1 / p),
                       (self.power, y), {})

    def forward_log_det_jacobian(self, x):
        return _run_op("power_fldj",
                       lambda p, x_: jnp.log(jnp.abs(p * jnp.power(x_, p - 1))),
                       (self.power, x), {})


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return jax.nn.log_sigmoid(x) + jax.nn.log_sigmoid(-x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        # log(1 - tanh^2 x) = 2 (log 2 - x - softplus(-2x))
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    _event_rank = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)  # one right inverse; softmax is not injective


class StickBreakingTransform(Transform):
    _event_rank = 1

    def _forward(self, x):
        # R^{K-1} -> simplex^K
        offset = x.shape[-1] - jnp.arange(x.shape[-1])
        z = jax.nn.sigmoid(x - jnp.log(offset.astype(x.dtype)))
        zpad = jnp.concatenate([z, jnp.ones(z.shape[:-1] + (1,), z.dtype)], -1)
        onez = jnp.concatenate([jnp.ones(z.shape[:-1] + (1,), z.dtype), 1 - z], -1)
        return zpad * jnp.cumprod(onez, -1)

    def _inverse(self, y):
        y_crop = y[..., :-1]
        offset = y_crop.shape[-1] - jnp.arange(y_crop.shape[-1])
        sf = 1 - jnp.cumsum(y_crop, -1) + y_crop
        z = y_crop / sf
        return (jnp.log(z) - jnp.log1p(-z)
                + jnp.log(offset.astype(y.dtype)))

    def _fldj(self, x):
        offset = x.shape[-1] - jnp.arange(x.shape[-1])
        xs = x - jnp.log(offset.astype(x.dtype))
        z = jax.nn.sigmoid(xs)
        onez = jnp.concatenate([jnp.ones(z.shape[:-1] + (1,), z.dtype), 1 - z], -1)
        log_sf = jnp.log(jnp.cumprod(onez[..., :-1], -1))
        return (jax.nn.log_sigmoid(xs) + jax.nn.log_sigmoid(-xs) + log_sf).sum(-1)

    def forward_log_det_jacobian(self, x):
        return _run_op("stickbreaking_fldj", self._fldj, (x,), {})

    def forward_shape(self, shape):
        return list(shape[:-1]) + [shape[-1] + 1]

    def inverse_shape(self, shape):
        return list(shape[:-1]) + [shape[-1] - 1]


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)
        # compose event ranks: widen the domain when a member needs more
        # event dims than the running rank provides
        rank, need = 0, 0
        for t in self.transforms:
            if rank < t._domain_rank:
                need += t._domain_rank - rank
                rank = t._domain_rank
            rank = rank - t._domain_rank + t._codomain_rank
        self._chain_domain_rank = need
        self._chain_codomain_rank = rank
        self._event_rank = max(need, rank)

    @property
    def _domain_rank(self):
        return self._chain_domain_rank

    @property
    def _codomain_rank(self):
        return self._chain_codomain_rank

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        from .distribution import sum_rightmost
        total = None
        rank = self._chain_domain_rank
        for t in self.transforms:
            rank = max(rank, t._domain_rank)
            # reduce each member's per-element jacobian over the chain's
            # event dims beyond the member's own rank, so terms line up
            term = sum_rightmost(t.forward_log_det_jacobian(x),
                                 rank - t._domain_rank)
            total = term if total is None else _run_op(
                "add", lambda a, b: a + b, (total, term), {})
            rank = rank - t._domain_rank + t._codomain_rank
            x = t.forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape


class IndependentTransform(Transform):
    """Treat the rightmost ``reinterpreted_batch_rank`` dims as event dims:
    log-det sums over them."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        self._event_rank = base._event_rank + self.reinterpreted_batch_rank

    def forward(self, x):
        return self.base.forward(x)

    def inverse(self, y):
        return self.base.inverse(y)

    def forward_log_det_jacobian(self, x):
        from .distribution import sum_rightmost
        return sum_rightmost(self.base.forward_log_det_jacobian(x),
                             self.reinterpreted_batch_rank)


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        if int(np.prod(self.in_event_shape)) != int(np.prod(self.out_event_shape)):
            raise ValueError("in/out event shapes must have the same size")
        self._event_rank = len(self.in_event_shape)

    @property
    def _domain_rank(self):
        return len(self.in_event_shape)

    @property
    def _codomain_rank(self):
        return len(self.out_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _fldj(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)

    def forward_shape(self, shape):
        n = len(shape) - len(self.in_event_shape)
        return list(shape[:n]) + list(self.out_event_shape)

    def inverse_shape(self, shape):
        n = len(shape) - len(self.out_event_shape)
        return list(shape[:n]) + list(self.in_event_shape)


class StackTransform(Transform):
    """Apply a list of transforms to slices along ``axis``."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _slice(self, x, i):
        return _run_op("stack_slice",
                       lambda a: jnp.take(a, i, axis=self.axis), (x,), {})

    def forward(self, x):
        outs = [t.forward(self._slice(x, i))
                for i, t in enumerate(self.transforms)]
        return _run_op("stack", lambda *a: jnp.stack(a, self.axis), tuple(outs), {})

    def inverse(self, y):
        outs = [t.inverse(self._slice(y, i))
                for i, t in enumerate(self.transforms)]
        return _run_op("stack", lambda *a: jnp.stack(a, self.axis), tuple(outs), {})

    def forward_log_det_jacobian(self, x):
        outs = [t.forward_log_det_jacobian(self._slice(x, i))
                for i, t in enumerate(self.transforms)]
        return _run_op("stack", lambda *a: jnp.stack(a, self.axis), tuple(outs), {})
