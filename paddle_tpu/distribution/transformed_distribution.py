"""TransformedDistribution (ref: python/paddle/distribution/transformed_distribution.py †)."""
from __future__ import annotations

from ..tensor.tensor import _run_op
from .distribution import Distribution, sum_rightmost


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms, name=None):
        self.base = base
        self.transforms = list(transforms)
        shape = tuple(base._batch_shape) + tuple(base._event_shape)
        # track the event rank through the chain: each transform needs at
        # least its domain rank, and maps domain rank -> codomain rank
        rank = len(base._event_shape)
        for t in self.transforms:
            rank = max(rank, t._domain_rank)
            rank = rank - t._domain_rank + t._codomain_rank
            shape = tuple(t.forward_shape(shape))
        cut = len(shape) - rank
        super().__init__(shape[:cut], shape[cut:])

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        # walk backwards tracking the event rank of y at each point
        rank = len(self._event_shape)
        lp = None
        y = value
        for t in reversed(self.transforms):
            x = t.inverse(y)
            ldj = t.forward_log_det_jacobian(x)
            term = sum_rightmost(
                _run_op("neg", lambda a: -a, (ldj,), {}),
                rank - t._codomain_rank)
            lp = term if lp is None else _run_op("add", lambda a, b: a + b,
                                                 (lp, term), {})
            rank = rank - t._codomain_rank + t._domain_rank
            y = x
        base_lp = sum_rightmost(self.base.log_prob(y),
                                rank - len(self.base._event_shape))
        if lp is None:
            return base_lp
        return _run_op("add", lambda a, b: a + b, (lp, base_lp), {})
