"""Central registry of ``PADDLE_TPU_*`` environment knobs.

Every environment variable the package reads is declared here once, with
its type, default, validator and a doc string — and read through
:func:`get` so junk values always raise a ``ValueError`` naming the
variable (the PR-3 "house pattern", previously re-implemented per site).
The static-analysis rule PTA005 (``paddle_tpu.analysis``) enforces that
no module reads ``os.environ``/``os.getenv`` for a ``PADDLE_TPU_*`` key
directly, and that every knob named anywhere in the package is registered
(and therefore documented) here.

Values are parsed on every :func:`get` call — never cached — so tests can
flip knobs via ``monkeypatch.setenv`` exactly as before. :func:`raw`
returns the unparsed string (or None) for cache keys that must track the
environment verbatim (e.g. the collective-matmul plan cache).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = ["Knob", "get", "raw", "knobs", "is_registered"]


@dataclasses.dataclass(frozen=True)
class Knob:
    """One registered environment variable."""
    name: str
    kind: str          # "bool" | "int" | "float" | "enum" | "str"
    default: Any       # the parsed value returned when the var is unset
    doc: str
    parse: Callable[[Optional[str]], Any]  # raw (or None) -> value; raises
    choices: Tuple[str, ...] = ()          # for kind == "enum"


_REGISTRY: Dict[str, Knob] = {}


def _register(name, kind, default, doc, parse, choices=()):
    knob = Knob(name=name, kind=kind, default=default, doc=doc,
                parse=parse, choices=choices)
    _REGISTRY[name] = knob
    return knob


def get(name: str):
    """Parsed, validated value of a registered knob (default when unset).

    Raises ``KeyError`` for unregistered names and ``ValueError`` (naming
    the variable) when the environment holds a junk value.
    """
    return _REGISTRY[name].parse(os.environ.get(name))


def raw(name: str) -> Optional[str]:
    """The unparsed environment string (None when unset) of a registered
    knob — for cache keys that must follow the environment verbatim."""
    _REGISTRY[name]  # KeyError on unregistered names, same as get()
    return os.environ.get(name)


def knobs() -> Tuple[Knob, ...]:
    """All registered knobs, sorted by name (for docs and lint rules)."""
    return tuple(sorted(_REGISTRY.values(), key=lambda k: k.name))


def is_registered(name: str) -> bool:
    return name in _REGISTRY


# ---------------------------------------------------------------------------
# parser factories (each returned parser takes the raw string-or-None)
# ---------------------------------------------------------------------------

def _truthy(truthy_values, unset="0"):
    """Lenient boolean: membership in ``truthy_values`` after strip+lower;
    anything else is False (these switches predate the strict pattern and
    tests rely on '0'/'junk' reading as off)."""
    def parse(value):
        return (value if value is not None else unset).strip().lower() \
            in truthy_values
    return parse


def _strict_bool(name):
    """Strict boolean: unset/''/falsey spellings -> False, truthy -> True,
    anything else raises naming the variable."""
    def parse(value):
        v = (value or "").strip().lower()
        if v in ("1", "true", "yes", "on"):
            return True
        if v in ("", "0", "false", "no", "off"):
            return False
        raise ValueError(
            f"{name}={v!r}: expected a boolean "
            "(1/0/true/false/yes/no/on/off)")
    return parse


def _positive_int(name, default, allow_auto=False):
    """Strictly positive int; '' / 'auto' mean None when ``allow_auto``.
    Error text matches the PR-3 collective_matmul pattern (pinned by
    tests/test_overlap_parity.py)."""
    def parse(value):
        if value is None:
            return default
        s = value.strip().lower()
        if allow_auto and s in ("", "auto"):
            return None
        try:
            v = int(s)
        except ValueError:
            raise ValueError(
                f"{name} must be a positive integer"
                + (" or 'auto'" if allow_auto else "") + f", got {value!r}")
        if v <= 0:
            raise ValueError(f"{name} must be positive, got {value!r}")
        return v
    return parse


def _positive_float(name, default):
    def parse(value):
        if value is None or not value.strip():
            return default
        try:
            v = float(value.strip())
        except ValueError:
            raise ValueError(
                f"{name} must be a number, got {value!r}")
        if v <= 0:
            raise ValueError(f"{name} must be positive, got {value!r}")
        return v
    return parse


def _enum(name, choices, default):
    def parse(value):
        mode = (value if value is not None else default).strip().lower()
        if mode not in choices:
            raise ValueError(
                f"{name} must be " + _spell(choices) + f", got {mode!r}")
        return mode
    return parse


def _spell(choices):
    if len(choices) == 2:
        return f"'{choices[0]}' or '{choices[1]}'"
    return "one of " + "/".join(choices)


# ---------------------------------------------------------------------------
# the registry — one _register() call per knob, literal name + doc so the
# PTA005 rule can read this file statically (no import required)
# ---------------------------------------------------------------------------

_register(
    "PADDLE_TPU_TP_OVERLAP", "bool", False,
    doc="Turn on the collective-matmul ppermute ring so TP linears overlap "
        "each hop's transfer with its partial matmul (PR 1); also the "
        "default for the stage-3 param-gather prefetch (PR 3).",
    parse=_truthy(("1", "true", "ring", "on")))

_register(
    "PADDLE_TPU_TP_OVERLAP_MIN_CHUNK", "int", 64,
    doc="Smallest per-hop sub-tile (rows) the auto chunker targets when "
        "splitting ring hops at mp>2 (PR 3). Positive integer.",
    parse=_positive_int("PADDLE_TPU_TP_OVERLAP_MIN_CHUNK", 64))

_register(
    "PADDLE_TPU_TP_OVERLAP_CHUNKS", "int", None,
    doc="Explicit per-hop sub-tile count for the chunked ring (PR 3); "
        "''/'auto' lets the library target ~MIN_CHUNK rows per sub-tile.",
    parse=_positive_int("PADDLE_TPU_TP_OVERLAP_CHUNKS", None,
                        allow_auto=True))

_register(
    "PADDLE_TPU_PP_OVERLAP", "bool", False,
    doc="Async 1F1B pipeline p2p sends (PR 1): issue each stage's send "
        "one skew tick early so the transfer hides under compute.",
    parse=_truthy(("1", "true", "on")))

_register(
    "PADDLE_TPU_GRAD_SYNC", "enum", "auto",
    doc="Gradient-sync schedule for DataParallel / GroupSharded stage-1/2 "
        "(PR 1): 'auto' (GSPMD implicit), 'explicit' (manual psum island) "
        "or 'bucketed' (fused reverse-topological buckets).",
    parse=_enum("PADDLE_TPU_GRAD_SYNC", ("auto", "explicit", "bucketed"),
                "auto"),
    choices=("auto", "explicit", "bucketed"))

_register(
    "PADDLE_TPU_DP_BUCKET_MB", "float", 25.0,
    doc="Gradient-bucket size cap (MB) for grad_sync='bucketed' (PR 1). "
        "Positive number.",
    parse=_positive_float("PADDLE_TPU_DP_BUCKET_MB", 25.0))

_register(
    "PADDLE_TPU_TELEMETRY", "bool", False,
    doc="Step-level telemetry switch (PR 2): StepMetrics interval timing, "
        "comm/compute spans and counters. An explicit telemetry= argument "
        "to jit.TrainStep wins over the env.",
    parse=_truthy(("1", "true", "on", "yes")))

_register(
    "PADDLE_TPU_TELEMETRY_DIR", "str", None,
    doc="Directory for the JSONL step-log exporter (PR 2); unset/empty "
        "means no file output.",
    parse=lambda value: value or None)

_register(
    "PADDLE_TPU_LEDGER", "bool", False,
    doc="Always-on roofline step ledger (PR 17): TrainStep captures each "
        "compiled program's per-kernel cost_estimate totals at trace time "
        "and itemizes step time into named roofline-classified lines with "
        "an explicit unattributed remainder. Measurement-only (losses "
        "bit-identical). An explicit ledger= argument to jit.TrainStep "
        "wins over the env.",
    parse=_truthy(("1", "true", "on", "yes")))

_register(
    "PADDLE_TPU_LEDGER_DIR", "str", None,
    doc="Directory for RooflineLedger JSONL report output (PR 17); "
        "unset/empty falls back to PADDLE_TPU_TELEMETRY_DIR, and with "
        "neither set no ledger file is written.",
    parse=lambda value: value or None)

_register(
    "PADDLE_TPU_REGRESS_BAND", "float", 0.15,
    doc="Default fractional noise band for the bench regression ratchet "
        "(PR 17, observability.regress): a rung worse than its "
        "PERF_BASELINE.json value by more than the band fails --check. "
        "Per-entry bands in the baseline and the --band flag win over "
        "the env.",
    parse=_positive_float("PADDLE_TPU_REGRESS_BAND", 0.15))

_register(
    "PADDLE_TPU_PEAK_FLOPS", "float", None,
    doc="Per-chip peak FLOP/s override for MFU attribution (PR 2); unset "
        "falls back to the PJRT device_kind table in observability."
        "metrics.PEAK_FLOPS_TABLE.",
    parse=_positive_float("PADDLE_TPU_PEAK_FLOPS", None))

_register(
    "PADDLE_TPU_FLASH_SOFTMAX", "enum", "auto",
    doc="Flash-attention softmax recurrence: 'auto' (fixed-base wherever "
        "its VMEM budget fits) or 'online' (unconditionally-stable "
        "running-max recurrence, for heavy-tailed logits).",
    parse=_enum("PADDLE_TPU_FLASH_SOFTMAX", ("auto", "online"), "auto"),
    choices=("auto", "online"))

_register(
    "PADDLE_TPU_FLASH_BWD", "enum", "auto",
    doc="Dense flash backward path (PR 7): 'auto' (fused k-major flat "
        "pass when its scratch fits) or 'split' (bitwise-pinned legacy "
        "two-kernel / dq-partials dispatch).",
    parse=_enum("PADDLE_TPU_FLASH_BWD", ("auto", "split"), "auto"),
    choices=("auto", "split"))

_register(
    "PADDLE_TPU_DECODE_HD64_STACK", "bool", False,
    doc="Opt decode_attention_slab into the PAIR-STACKED hd64 kernel (two "
        "head_dim-64 heads per 128-lane MXU tile, PR 5). Default keeps "
        "the batch-block-diagonal kernel.",
    parse=_truthy(("1", "true", "yes", "on")))


def _parse_decode_block_t(value):
    # exact messages pinned by tests/test_decode_block_choice.py
    if value is None or not value.strip():
        return None
    try:
        val = int(value.strip())
    except ValueError:
        raise ValueError(
            f"PADDLE_TPU_DECODE_BLOCK_T={value!r}: expected an integer "
            "number of lanes (a power of two >= 128)")
    if val < 128 or val & (val - 1):
        raise ValueError(
            f"PADDLE_TPU_DECODE_BLOCK_T={val}: must be a power of two "
            ">= 128")
    return val


_register(
    "PADDLE_TPU_DECODE_BLOCK_T", "int", None,
    doc="Forced decode-attention T tile (lanes), a power of two >= 128; "
        "unset lets _fit_block_t size the tile to the VMEM window budget "
        "(PR 6 bench A/B override).",
    parse=_parse_decode_block_t)


def _parse_moe_dropless(value):
    # tri-state spelled as a boolean; exact message predates the registry
    v = (value or "").strip().lower()
    if v in ("1", "true", "yes", "on"):
        return "ragged"
    if v in ("", "0", "false", "no", "off"):
        return "capacity"
    raise ValueError(
        f"PADDLE_TPU_MOE_DROPLESS={v!r}: expected a boolean "
        "(1/0/true/false/yes/no/on/off)")


_register(
    "PADDLE_TPU_MOE_DROPLESS", "enum", "capacity",
    doc="MoE dispatch default (PR 5): truthy selects the ragged "
        "grouped-GEMM dropless path, falsy/unset the capacity slot "
        "schedule (reference drop parity).",
    parse=_parse_moe_dropless,
    choices=("capacity", "ragged"))

_register(
    "PADDLE_TPU_MOE_A2A", "enum", "ring",
    doc="Ragged expert-dispatch transport (PR 10): 'ring' moves each "
        "destination's actual token rows over n-1 per-hop ppermutes "
        "(overlappable with expert compute); 'dense' carries the SAME "
        "tile-aligned chunk layout through one XLA all_to_all — the "
        "bitwise-equal fallback with no per-hop overlap.",
    parse=_enum("PADDLE_TPU_MOE_A2A", ("ring", "dense"), "ring"),
    choices=("ring", "dense"))

_register(
    "PADDLE_TPU_MOE_A2A_OVERLAP", "bool", False,
    doc="Overlap ragged expert-dispatch hops with expert compute "
        "(PR 10): drop the blocking barrier so each chunk's grouped-GEMM "
        "starts as soon as its hop lands, while later ppermute hops are "
        "still in flight. Bitwise-equal to the blocking schedule "
        "(identical per-chunk kernels, disjoint rows).",
    parse=_strict_bool("PADDLE_TPU_MOE_A2A_OVERLAP"))

_register(
    "PADDLE_TPU_TRACE_REQUESTS", "bool", False,
    doc="Request-lifecycle tracing in the serving engine (PR 12): per-"
        "request span trees (queue wait, prefill chunks, decode "
        "iterations, evictions) exportable as JSONL and Chrome trace "
        "JSON. Measurement-only: tokens are bit-identical on/off. An "
        "explicit trace_requests= argument to InferenceEngine wins.",
    parse=_strict_bool("PADDLE_TPU_TRACE_REQUESTS"))

_register(
    "PADDLE_TPU_FLIGHT_RECORDER", "bool", False,
    doc="Failure flight recorder (PR 12): keep a bounded ring of the "
        "last N iteration/step records in the engine and TrainStep, "
        "dumped to PADDLE_TPU_TELEMETRY_DIR on exception, eviction "
        "storm, or step-time spike. An explicit flight_recorder= "
        "argument wins over the env.",
    parse=_strict_bool("PADDLE_TPU_FLIGHT_RECORDER"))

_register(
    "PADDLE_TPU_FLIGHT_RECORDER_SIZE", "int", 256,
    doc="Ring capacity (records) of the failure flight recorder (PR 12). "
        "Positive integer; also bounds the step-time window the spike "
        "detector computes its median/MAD over.",
    parse=_positive_int("PADDLE_TPU_FLIGHT_RECORDER_SIZE", 256))

_register(
    "PADDLE_TPU_SPIKE_MAD", "float", 8.0,
    doc="Step-time spike threshold for the flight recorder (PR 12), in "
        "robust sigmas: a step further than this many MAD-derived "
        "standard deviations (MAD x 1.4826) from the window median "
        "triggers a dump. Positive number.",
    parse=_positive_float("PADDLE_TPU_SPIKE_MAD", 8.0))

_register(
    "PADDLE_TPU_CKPT_KEEP", "int", 3,
    doc="Rolling-checkpoint retention for CheckpointManager (PR 13): the "
        "keep-N garbage collector deletes complete step dirs beyond the "
        "newest N. Positive integer; an explicit keep= argument wins.",
    parse=_positive_int("PADDLE_TPU_CKPT_KEEP", 3))

_register(
    "PADDLE_TPU_CKPT_INTERVAL", "int", None,
    doc="Steps between CheckpointManager.on_step async saves (PR 13); "
        "''/'auto'/unset disables interval pacing (explicit save() calls "
        "only). An explicit interval= argument wins.",
    parse=_positive_int("PADDLE_TPU_CKPT_INTERVAL", None, allow_auto=True))

_register(
    "PADDLE_TPU_PREEMPT_GRACE", "float", 30.0,
    doc="Seconds a preemption shutdown (PR 13) waits for the in-flight "
        "async checkpoint write before abandoning it and taking the "
        "final sync save. Positive number.",
    parse=_positive_float("PADDLE_TPU_PREEMPT_GRACE", 30.0))

_register(
    "PADDLE_TPU_FAULTS", "bool", False,
    doc="Gate for the deterministic fault-injection harness "
        "(paddle_tpu.testing.faults, PR 13): arming an injection point "
        "raises unless this is set, so production code can never run "
        "with live fault hooks. The hooks themselves cost one flag "
        "check when disarmed.",
    parse=_strict_bool("PADDLE_TPU_FAULTS"))

_register(
    "PADDLE_TPU_SERVE_MAX_QUEUE", "int", None,
    doc="Bounded waiting-queue depth for the serving engine's admission "
        "control (PR 14): submit() rejects with cause 'queue_full' once "
        "this many requests wait. ''/'auto'/unset means 4 x max_batch; "
        "ServeConfig(max_queue=) wins.",
    parse=_positive_int("PADDLE_TPU_SERVE_MAX_QUEUE", None,
                        allow_auto=True))

_register(
    "PADDLE_TPU_SERVE_RATE", "float", None,
    doc="Token-bucket admission rate for the serving engine (PR 14), in "
        "requests per engine-clock unit (seconds in wall mode, "
        "iterations in deterministic replay). Unset/empty disables rate "
        "limiting; ServeConfig(rate_limit=) wins.",
    parse=_positive_float("PADDLE_TPU_SERVE_RATE", None))

_register(
    "PADDLE_TPU_SERVE_BURST", "int", None,
    doc="Token-bucket burst capacity for serve admission rate limiting "
        "(PR 14). ''/'auto'/unset means max(2, max_batch); "
        "ServeConfig(burst=) wins.",
    parse=_positive_int("PADDLE_TPU_SERVE_BURST", None, allow_auto=True))

_register(
    "PADDLE_TPU_SERVE_OVERCOMMIT", "float", 4.0,
    doc="Free-block-aware admission estimate (PR 14): submit() rejects "
        "with cause 'overcommit' when the worst-case block demand of "
        "everything queued+active plus the new request exceeds this "
        "factor times the usable pool. Positive number; "
        "ServeConfig(overcommit=) wins.",
    parse=_positive_float("PADDLE_TPU_SERVE_OVERCOMMIT", 4.0))

_register(
    "PADDLE_TPU_SERVE_NAN_CHECK", "bool", True,
    doc="Per-row non-finite logit screen in the serving engine (PR 14): "
        "a request whose prefill/decode logits contain NaN/Inf is "
        "quarantined (failed with cause, blocks released) while the "
        "rest of the batch keeps serving. Default ON; "
        "ServeConfig(nan_check=) wins.",
    parse=_truthy(("1", "true", "yes", "on"), unset="1"))

_register(
    "PADDLE_TPU_SERVE_JOURNAL", "str", None,
    doc="Path of the serving engine's crash-recoverable request/token "
        "journal (PR 14): append-only JSONL of accepted requests and "
        "emitted tokens; a fresh engine's recover() re-drives to bit-"
        "identical streams. Unset/empty disables journaling; "
        "InferenceEngine(journal=) wins.",
    parse=lambda value: value or None)

_register(
    "PADDLE_TPU_SERVE_JOURNAL_FSYNC", "bool", False,
    doc="fsync the serve journal once per engine iteration (PR 14) for "
        "power-failure durability; default flushes to the OS only "
        "(process-crash durability).",
    parse=_strict_bool("PADDLE_TPU_SERVE_JOURNAL_FSYNC"))

_register(
    "PADDLE_TPU_SERVE_PREFIX_CACHE", "bool", False,
    doc="Copy-on-write prefix caching for the serving engine (PR 16): "
        "prefilled prompts' full KV blocks stay indexed by their exact "
        "token prefix after release, and a later request whose prompt "
        "starts identically shares those blocks (ref-counted, COW) and "
        "skips prefill for the hit span — TTFT becomes a cache hit for "
        "shared system prompts. Parked cache blocks are reclaimed "
        "LRU-last, so caching never steals capacity from live "
        "sequences. Hit output is bitwise-identical to a cold run "
        "(PARITY.md). Default OFF; ServeConfig(prefix_cache=) wins.",
    parse=_strict_bool("PADDLE_TPU_SERVE_PREFIX_CACHE"))

_register(
    "PADDLE_TPU_SERVE_KV_DTYPE", "enum", "auto",
    doc="Paged KV cache storage dtype for the serving engine (PR 16). "
        "'auto' stores the model dtype — the pre-PR-16 path, "
        "bit-identical. 'int8' stores per-block/per-kv-head/per-column "
        "absmax-quantized bytes (quantization/ conventions: qmax 127, "
        "scale floor 1e-8) with fused dequant inside the paged "
        "kernels — half the pool bytes per cached token, the one "
        "documented numeric deviation (PARITY.md). "
        "ServeConfig(kv_dtype=) wins.",
    parse=_enum("PADDLE_TPU_SERVE_KV_DTYPE", ("auto", "int8"), "auto"),
    choices=("auto", "int8"))

_register(
    "PADDLE_TPU_SERVE_SPEC", "bool", False,
    doc="Greedy speculative decoding in the serving engine (PR 18): a "
        "small draft model (default: the base truncated to its first "
        "layer, embedding shared) proposes up to K tokens per sequence "
        "per iteration and ONE batched multi-token verification pass "
        "scores all K+1 positions, committing only the accepted "
        "prefix's KV. Every emitted token is the BASE model's greedy "
        "argmax, so streams are bit-identical to sequential decode "
        "(PARITY.md) — speculation only moves latency. Default OFF; "
        "ServeConfig(speculative=) wins.",
    parse=_strict_bool("PADDLE_TPU_SERVE_SPEC"))

_register(
    "PADDLE_TPU_SERVE_SPEC_K", "int", 4,
    doc="Draft proposal depth K for speculative serving (PR 18): up to "
        "K lookahead tokens are proposed and K+1 positions verified "
        "per sequence per iteration. Higher K amortizes more scheduler "
        "iterations per verified span at the cost of wasted draft work "
        "when acceptance is low. The verify program's token width is "
        "pinned at K+1, so K is part of the bounded compiled-shape "
        "family. ServeConfig(draft_k=) wins.",
    parse=_positive_int("PADDLE_TPU_SERVE_SPEC_K", 4))

_register(
    "PADDLE_TPU_SERVE_MP", "int", 1,
    doc="Tensor-parallel degree of the serving engine (PR 19): mp > 1 "
        "runs prefill/decode/speculative-verify inside an ('mp',)-"
        "sharded mesh — weights sliced per param_pspecs, KV/scale/draft "
        "pools sharded by kv-head — with token streams identical to "
        "mp=1 (greedy argmax; PARITY.md). Needs num_attention_heads, "
        "num_key_value_heads, vocab_size and intermediate_size all "
        "divisible by mp, and mp local devices. ServeConfig(mp=) wins.",
    parse=_positive_int("PADDLE_TPU_SERVE_MP", 1))

_register(
    "PADDLE_TPU_FLEET_SERVE_REPLICAS", "int", 2,
    doc="Replica count of the serving FleetRouter (PR 20): N "
        "InferenceEngine replicas behind one prefix-affinity router. "
        "Positive integer; FleetRouter(n_replicas=) wins.",
    parse=_positive_int("PADDLE_TPU_FLEET_SERVE_REPLICAS", 2))

_register(
    "PADDLE_TPU_FLEET_SERVE_SPILL", "int", 4,
    doc="Queue-depth spill threshold of the FleetRouter's prefix-"
        "affinity dispatch (PR 20): when the affinity replica's queue "
        "depth + in-flight count reaches this, the request spills to "
        "the least-loaded live replica instead (counted as a "
        "rebalance), so adversarial prefix skew never starves N-1 "
        "replicas. Positive integer; FleetRouter(spill=) wins.",
    parse=_positive_int("PADDLE_TPU_FLEET_SERVE_SPILL", 4))

_register(
    "PADDLE_TPU_FLEET_SERVE_JOURNAL_DIR", "str", None,
    doc="Directory for per-replica FleetRouter journals (PR 20): each "
        "replica writes replica_<i>.jsonl there, and kill_replica() "
        "re-drives a dead replica's unfinished journal entries onto "
        "survivors bit-identically. Unset/empty disables fleet "
        "journaling; FleetRouter(journal_dir=) wins.",
    parse=lambda value: value or None)

_register(
    "PADDLE_TPU_FLEET", "bool", False,
    doc="Wire a FleetMonitor (PR 15) into jit.TrainStep: per-rank step "
        "times, per-site comm_span hop stats and all-device memory are "
        "aggregated across ranks every reporting interval (one small "
        "host-side allgather, nothing on the step hot path). An explicit "
        "TrainStep(fleet=) argument wins.",
    parse=_strict_bool("PADDLE_TPU_FLEET"))

_register(
    "PADDLE_TPU_FLEET_INTERVAL", "int", 32,
    doc="Steps between FleetMonitor fleet-health reports (PR 15); each "
        "report is one host-side allgather + one JSONL record. Positive "
        "integer; FleetMonitor(interval=) wins.",
    parse=_positive_int("PADDLE_TPU_FLEET_INTERVAL", 32))

_register(
    "PADDLE_TPU_FLEET_HBM_WATERMARK", "float", 0.92,
    doc="HBM high-watermark anomaly threshold for the FleetMonitor "
        "(PR 15): a device whose peak_bytes_in_use exceeds this fraction "
        "of its bytes_limit trips an hbm_high_watermark anomaly and a "
        "flight-recorder dump. Positive number (fraction of the limit); "
        "FleetMonitor(hbm_watermark=) wins.",
    parse=_positive_float("PADDLE_TPU_FLEET_HBM_WATERMARK", 0.92))

_register(
    "PADDLE_TPU_FLEET_DESYNC_STEPS", "int", 4,
    doc="Allowed rank step-count divergence before the FleetMonitor's "
        "desync detector (PR 15) raises a rank_desync anomaly (one rank "
        "stuck recompiling or spinning in host code while the others "
        "advance). Positive integer; FleetMonitor(desync_steps=) wins.",
    parse=_positive_int("PADDLE_TPU_FLEET_DESYNC_STEPS", 4))

_register(
    "PADDLE_TPU_SEP_STRATEGY", "enum", "ring",
    doc="Context-parallel attention strategy for the llama sep axis "
        "(PR 7): 'ring' (PR-1 ring attention) or 'ulysses' (head-sharded "
        "all-to-all). ParallelConfig(sep_strategy=) wins over the env.",
    parse=_enum("PADDLE_TPU_SEP_STRATEGY", ("ring", "ulysses"), "ring"),
    choices=("ring", "ulysses"))
