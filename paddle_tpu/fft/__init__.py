"""Discrete Fourier transforms (ref: python/paddle/fft.py †).

Thin autograd-taped front-ends over ``jnp.fft``: XLA lowers FFTs to its native
``fft`` HLO, which the TPU backend executes on-chip — no custom kernels needed.
All ops accept the reference's ``norm`` spellings ("backward"/"ortho"/"forward")
and run through ``_run_op`` so gradients come from the recorded vjp.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor.tensor import Tensor, _run_op

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft", "hfft2", "ihfft2", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")


def _check_norm(norm):
    """Validate and canonicalize; "backward" becomes None so jnp skips its
    norm-scaling path entirely (identity scale — and the scale multiply can
    land on the wrong device under a non-default current place)."""
    if norm is None or norm == "backward":
        return None
    if norm not in _NORMS:
        raise ValueError(
            f"Unexpected norm: {norm!r}. Norm should be 'forward', 'backward' "
            f"or 'ortho'")
    return norm


def _1d(name, jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        nrm = _check_norm(norm)
        return _run_op(name, lambda a: jfn(a, n=n, axis=axis, norm=nrm), (x,), {})
    op.__name__ = name
    return op


def _nd(name, jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        nrm = _check_norm(norm)
        return _run_op(name, lambda a: jfn(a, s=s, axes=axes, norm=nrm), (x,), {})
    op.__name__ = name
    return op


def _2d(name, jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        nrm = _check_norm(norm)
        return _run_op(name, lambda a: jfn(a, s=s, axes=axes, norm=nrm), (x,), {})
    op.__name__ = name
    return op


fft = _1d("fft", jnp.fft.fft)
ifft = _1d("ifft", jnp.fft.ifft)
rfft = _1d("rfft", jnp.fft.rfft)
irfft = _1d("irfft", jnp.fft.irfft)
hfft = _1d("hfft", jnp.fft.hfft)
ihfft = _1d("ihfft", jnp.fft.ihfft)

fft2 = _2d("fft2", jnp.fft.fft2)
ifft2 = _2d("ifft2", jnp.fft.ifft2)
rfft2 = _2d("rfft2", jnp.fft.rfft2)
irfft2 = _2d("irfft2", jnp.fft.irfft2)

fftn = _nd("fftn", jnp.fft.fftn)
ifftn = _nd("ifftn", jnp.fft.ifftn)
rfftn = _nd("rfftn", jnp.fft.rfftn)
irfftn = _nd("irfftn", jnp.fft.irfftn)


def _hfft_nd(name, inverse):
    """hfft2/hfftn & ihfft2/ihfftn: jnp only ships the 1-d hermitian pair, so
    compose: full c2c over the leading axes + hermitian transform on the last."""
    def op(x, s=None, axes=None, norm="backward", name=None):
        nrm = _check_norm(norm)

        def f(a):
            if axes is not None:
                ax = list(axes)
            elif s is not None:
                ax = list(range(-len(s), 0))
            else:
                ax = list(range(a.ndim))
            sz = list(s) if s is not None else [None] * len(ax)
            lead_s = sz[:-1] if s is not None else None
            if not inverse:
                y = a
                if len(ax) > 1:
                    y = jnp.fft.fftn(y, s=lead_s, axes=ax[:-1], norm=nrm)
                return jnp.fft.hfft(y, n=sz[-1], axis=ax[-1], norm=nrm)
            y = jnp.fft.ihfft(a, n=sz[-1], axis=ax[-1], norm=nrm)
            if len(ax) > 1:
                y = jnp.fft.ifftn(y, s=lead_s, axes=ax[:-1], norm=nrm)
            return y
        return _run_op(name, f, (x,), {})
    op.__name__ = name
    return op


hfftn = _hfft_nd("hfftn", inverse=False)
ihfftn = _hfft_nd("ihfftn", inverse=True)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=axes, norm=norm)


def _freq(np_fn, n, d, dtype):
    # host-side numpy so the Tensor ctor places it on the current device
    import numpy as np
    out = np_fn(n, d=d)
    if dtype is None:
        out = out.astype(np.float32)
    return Tensor(out, dtype=dtype)


def fftfreq(n, d=1.0, dtype=None, name=None):
    import numpy as np
    return _freq(np.fft.fftfreq, n, d, dtype)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    import numpy as np
    return _freq(np.fft.rfftfreq, n, d, dtype)


def fftshift(x, axes=None, name=None):
    return _run_op("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes), (x,), {})


def ifftshift(x, axes=None, name=None):
    return _run_op("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes), (x,), {})
