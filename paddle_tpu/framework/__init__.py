"""Framework core: dtypes, places, flags, rng (ref: paddle/phi/common + paddle/common)."""
from . import dtype as _dtype_mod
from .dtype import (DType, convert_dtype, to_framework_dtype, get_default_dtype,
                    set_default_dtype)
from .place import (Place, CPUPlace, TPUPlace, GPUPlace, CUDAPlace, CustomPlace,
                    set_device, get_device, device_count,
                    is_compiled_with_cuda, is_compiled_with_tpu,
                    is_compiled_with_xpu, is_compiled_with_rocm,
                    is_compiled_with_custom_device)
from .flags import define_flag, get_flags, get_flag, set_flags
from .random import seed, get_rng_state, set_rng_state, get_rng_state_tracker


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """ref: paddle.set_printoptions — forwards to numpy's print options,
    which Tensor.__repr__ uses."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


class LazyGuard:
    """ref: paddle.LazyGuard — defer parameter materialization during
    Layer construction. Functional-runtime note: parameters here are jax
    arrays whose initialization is itself a traced/jit-able computation;
    there is no separate lazy-init graph to stage, so the guard simply
    scopes (construction proceeds eagerly with the same semantics the
    reference observes after its .initialize())."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def batch(reader, batch_size, drop_last=False):
    """ref: paddle.batch — wrap a sample reader into a batch reader."""
    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched
