"""Framework core: dtypes, places, flags, rng (ref: paddle/phi/common + paddle/common)."""
from . import dtype as _dtype_mod
from .dtype import (DType, convert_dtype, to_framework_dtype, get_default_dtype,
                    set_default_dtype)
from .place import (Place, CPUPlace, TPUPlace, GPUPlace, CUDAPlace, CustomPlace,
                    set_device, get_device, device_count,
                    is_compiled_with_cuda, is_compiled_with_tpu)
from .flags import define_flag, get_flags, get_flag, set_flags
from .random import seed, get_rng_state, set_rng_state, get_rng_state_tracker


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """ref: paddle.set_printoptions — forwards to numpy's print options,
    which Tensor.__repr__ uses."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)
