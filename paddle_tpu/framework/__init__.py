"""Framework core: dtypes, places, flags, rng (ref: paddle/phi/common + paddle/common)."""
from . import dtype as _dtype_mod
from .dtype import (DType, convert_dtype, to_framework_dtype, get_default_dtype,
                    set_default_dtype)
from .place import (Place, CPUPlace, TPUPlace, GPUPlace, CUDAPlace, CustomPlace,
                    set_device, get_device, device_count,
                    is_compiled_with_cuda, is_compiled_with_tpu)
from .flags import define_flag, get_flags, get_flag, set_flags
from .random import seed, get_rng_state, set_rng_state, get_rng_state_tracker
