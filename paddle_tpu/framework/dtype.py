"""Data types for paddle_tpu.

TPU-native rebuild of the reference's dtype surface (ref: paddle/phi/common/data_type.h).
Dtypes are thin named wrappers over numpy/jax dtypes so that ``paddle_tpu.float32`` etc.
work as drop-in dtype arguments everywhere, while the underlying arrays are jax arrays.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes


class DType:
    """A framework dtype. Compares equal to its string name and numpy dtype."""

    __slots__ = ("name", "np_dtype")

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = np.dtype(np_dtype)

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        if isinstance(other, str):
            return self.name == other or str(self.np_dtype) == other
        try:
            return np.dtype(other) == self.np_dtype
        except TypeError:
            return NotImplemented

    def __hash__(self):
        return hash(self.name)


bool_ = DType("bool", np.bool_)
uint8 = DType("uint8", np.uint8)
int8 = DType("int8", np.int8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
float16 = DType("float16", np.float16)
bfloat16 = DType("bfloat16", ml_dtypes.bfloat16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)
float8_e4m3fn = DType("float8_e4m3fn", ml_dtypes.float8_e4m3fn)
float8_e5m2 = DType("float8_e5m2", ml_dtypes.float8_e5m2)

_ALL = [bool_, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
        float64, complex64, complex128, float8_e4m3fn, float8_e5m2]
_BY_NAME = {d.name: d for d in _ALL}
_BY_NAME["bool"] = bool_

_DEFAULT_DTYPE = float32


def convert_dtype(dtype) -> np.dtype:
    """Normalize any dtype spec (DType, str, np/jnp dtype) to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, DType):
        return dtype.np_dtype
    if isinstance(dtype, str):
        d = _BY_NAME.get(dtype)
        if d is not None:
            return d.np_dtype
        return np.dtype(dtype)
    return np.dtype(dtype)


def to_framework_dtype(np_like) -> DType:
    """Map a numpy/jax dtype back to the framework DType object."""
    nd = np.dtype(np_like)
    for d in _ALL:
        if d.np_dtype == nd:
            return d
    raise TypeError(f"unsupported dtype: {np_like}")


def get_default_dtype() -> DType:
    return _DEFAULT_DTYPE


def set_default_dtype(dtype):
    global _DEFAULT_DTYPE
    nd = convert_dtype(dtype)
    _DEFAULT_DTYPE = to_framework_dtype(nd)


def is_floating(dtype) -> bool:
    nd = convert_dtype(dtype)
    return jnp.issubdtype(nd, np.floating)


def is_integer(dtype) -> bool:
    nd = convert_dtype(dtype)
    return jnp.issubdtype(nd, np.integer)


def is_complex(dtype) -> bool:
    nd = convert_dtype(dtype)
    return jnp.issubdtype(nd, np.complexfloating)


class _IInfo:
    def __init__(self, np_info):
        self.min = int(np_info.min)
        self.max = int(np_info.max)
        self.bits = int(np_info.bits)
        self.dtype = str(np_info.dtype)

    def __repr__(self):
        return (f"paddle.iinfo(min={self.min}, max={self.max}, "
                f"bits={self.bits}, dtype={self.dtype})")


class _FInfo:
    def __init__(self, np_info):
        self.min = float(np_info.min)
        self.max = float(np_info.max)
        self.eps = float(np_info.eps)
        self.tiny = float(np_info.tiny)
        self.smallest_normal = float(np_info.tiny)
        self.resolution = float(np_info.resolution)
        self.bits = int(np_info.bits)
        self.dtype = str(np_info.dtype)

    def __repr__(self):
        return (f"paddle.finfo(min={self.min}, max={self.max}, "
                f"eps={self.eps}, bits={self.bits}, dtype={self.dtype})")


def iinfo(dtype):
    """ref: paddle.iinfo."""
    import numpy as _np
    return _IInfo(_np.iinfo(convert_dtype(dtype)))


def finfo(dtype):
    """ref: paddle.finfo. Works for bfloat16 via ml_dtypes."""
    import jax.numpy as _jnp
    return _FInfo(_jnp.finfo(convert_dtype(dtype)))
