"""Typed flag registry with environment override (ref: paddle/common/flags.cc).

The reference has gflags-style ``FLAGS_*`` definitions settable via env or
``paddle.set_flags``. Here: a single registry; env vars named ``FLAGS_<name>``
override defaults at first read; ``set_flags`` overrides at runtime.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict

_REGISTRY: Dict[str, dict] = {}


def _parse_bool(s: str) -> bool:
    return s.lower() in ("1", "true", "yes", "on")


def define_flag(name: str, default, help_str: str = "", parser: Callable | None = None):
    if parser is None:
        if isinstance(default, bool):
            parser = _parse_bool
        elif isinstance(default, int):
            parser = int
        elif isinstance(default, float):
            parser = float
        else:
            parser = str
    _REGISTRY[name] = {"default": default, "help": help_str,
                       "parser": parser, "value": None}
    # Mirror into the native registry so C++ code reads the same flags
    # (ref: the reference's FLAGS_* are visible on both sides of pybind).
    from .. import runtime as _rt
    _rt.mirror_flag_define(name, default, help_str)


def get_flags(names) -> Dict[str, Any]:
    if isinstance(names, str):
        names = [names]
    out = {}
    for n in names:
        ent = _REGISTRY.get(n)
        if ent is None:
            raise KeyError(f"unknown flag: {n}")
        if ent["value"] is not None:
            out[n] = ent["value"]
        else:
            env = os.environ.get(f"FLAGS_{n}")
            out[n] = ent["parser"](env) if env is not None else ent["default"]
    return out


def get_flag(name: str):
    return get_flags([name])[name]


def set_flags(flags: Dict[str, Any]):
    from .. import runtime as _rt
    for k, v in flags.items():
        if k not in _REGISTRY:
            raise KeyError(f"unknown flag: {k}")
        _REGISTRY[k]["value"] = v
        _rt.mirror_flag_set(k, v)


# Core flags (TPU-relevant subset of the reference's surface).
define_flag("allocator_strategy", "auto_growth", "kept for API parity; XLA/PJRT owns device memory")
define_flag("check_nan_inf", False, "check outputs for nan/inf after each eager op")
define_flag("cudnn_deterministic", True, "parity alias: deterministic op selection")
define_flag("use_pallas_kernels", True, "use Pallas custom kernels when on TPU")
define_flag("eager_op_jit", False, "wrap each eager op in jax.jit (per-op cache)")
define_flag("log_level", 0, "framework VLOG level")
