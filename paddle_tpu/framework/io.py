"""Serialization: paddle.save / paddle.load parity (ref: python/paddle/framework/io.py).

State dicts (nested dict/list of Tensors) are saved as pickle with per-tensor
numpy payloads, like the reference. Sharded/async distributed checkpointing
lives in distributed/checkpoint (orbax/TensorStore-style).
"""
from __future__ import annotations

import os
import pickle

import numpy as np
import jax.numpy as jnp


class _TensorPayload:
    """Pickle-stable wrapper for a tensor's ndarray + metadata."""

    def __init__(self, array: np.ndarray, stop_gradient: bool = True):
        # bfloat16 has no portable numpy repr; store as uint16 view + tag
        self.dtype_name = str(array.dtype)
        if self.dtype_name == "bfloat16":
            self.buf = array.view(np.uint16)
        else:
            self.buf = array
        self.stop_gradient = stop_gradient

    def to_array(self) -> np.ndarray:
        if self.dtype_name == "bfloat16":
            import ml_dtypes
            return self.buf.view(ml_dtypes.bfloat16)
        return self.buf


def _pack(obj):
    from ..tensor.tensor import Tensor
    if isinstance(obj, Tensor):
        return _TensorPayload(obj.numpy(), obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    from ..tensor.tensor import Tensor
    if isinstance(obj, _TensorPayload):
        arr = obj.to_array()
        if return_numpy:
            return arr
        t = Tensor._from_data(jnp.asarray(arr))
        t.stop_gradient = obj.stop_gradient
        return t
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
