"""Device placement (ref: paddle/phi/common/place.h).

The reference keys kernels and allocations by ``phi::Place`` (CPUPlace/GPUPlace/...).
On TPU the device runtime is PJRT behind jax; a Place here names a jax device and
``set_device`` steers where eager ops place their outputs via jax's default-device.
"""
from __future__ import annotations

import jax


class Place:
    """Base place. Identifies a device type and an index."""

    device_type: str = "undefined"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (isinstance(other, Place)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def jax_device(self):
        devs = [d for d in jax.devices() if _dev_kind(d) == self.device_type]
        if not devs:
            # fall back to host CPU devices (always present)
            devs = jax.devices("cpu")
        return devs[min(self.device_id, len(devs) - 1)]


class CPUPlace(Place):
    device_type = "cpu"


class TPUPlace(Place):
    device_type = "tpu"


class CustomPlace(Place):
    def __init__(self, device_type: str, device_id: int = 0):
        super().__init__(device_id)
        self.device_type = device_type


# GPU alias for API parity: scripts that say "gpu" run on the accelerator present.
class GPUPlace(Place):
    device_type = "tpu"


CUDAPlace = GPUPlace

_current_place: Place | None = None


def _dev_kind(d) -> str:
    p = d.platform.lower()
    # treat any accelerator platform (tpu / experimental bridges) as "tpu"
    return "cpu" if p == "cpu" else "tpu"


def _default_place() -> Place:
    for d in jax.devices():
        if _dev_kind(d) == "tpu":
            return TPUPlace(0)
    return CPUPlace(0)


def get_device() -> str:
    p = _current_expected_place()
    return f"{p.device_type}:{p.device_id}"


def set_device(device: str) -> Place:
    """Set the global default device, e.g. 'tpu', 'tpu:0', 'cpu', 'gpu:0'."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
        return device
    name, _, idx = device.partition(":")
    idx = int(idx) if idx else 0
    name = name.lower()
    if name in ("tpu", "gpu", "cuda", "xpu", "npu"):
        _current_place = TPUPlace(idx)
    elif name == "cpu":
        _current_place = CPUPlace(idx)
    else:
        _current_place = CustomPlace(name, idx)
    return _current_place


def _current_expected_place() -> Place:
    global _current_place
    if _current_place is None:
        _current_place = _default_place()
    return _current_place


def is_compiled_with_cuda() -> bool:  # parity shim
    return False


def is_compiled_with_tpu() -> bool:
    return any(_dev_kind(d) == "tpu" for d in jax.devices())


def device_count() -> int:
    return jax.device_count()


def is_compiled_with_xpu() -> bool:  # parity shim
    return False


def is_compiled_with_rocm() -> bool:  # parity shim
    return False


def is_compiled_with_custom_device(device_type: str = None) -> bool:
    """The TPU backend registers through PJRT — the plugin mechanism the
    reference's custom-device API describes."""
    if device_type is None:
        return True
    return device_type.lower() in ("tpu", "axon")
