"""Global RNG state (ref: paddle/fluid/framework/generator.cc).

The reference has stateful per-device Generators. jax is functional (explicit keys);
we keep a global counter-based state: every random op folds a fresh subkey out of
the global key. ``get_rng_state``/``set_rng_state`` capture (key, counter) so
training runs are reproducible and resumable.

A named-state tracker (``RNGStatesTracker``) mirrors the reference's
fleet/meta_parallel/parallel_layers/random.py for tensor-parallel-deterministic
dropout: "global" state is identical across mp ranks, "local" state is folded with
the mp rank so dropout masks differ where they must.
"""
from __future__ import annotations

import contextlib

import jax
import numpy as np


class _GeneratorState:
    def __init__(self, seed: int = 0):
        self.seed = seed
        self.counter = 0

    def key(self):
        k = jax.random.fold_in(jax.random.PRNGKey(self.seed), self.counter)
        self.counter += 1
        return k

    def state(self):
        return (self.seed, self.counter)

    def set_state(self, state):
        self.seed, self.counter = state


_GLOBAL = _GeneratorState(seed=np.random.randint(0, 2**31 - 1))
_TRACE_KEY = None  # when set, next_key derives from this traced base key


@contextlib.contextmanager
def trace_rng(base_key):
    """Derive keys from a traced base key during jit tracing.

    Host-side stateful keys would bake into the compiled graph as constants
    (same dropout mask every step). Under this context, ``next_key`` folds a
    per-call counter into ``base_key`` — a traced array that varies per step.
    """
    global _TRACE_KEY
    prev = _TRACE_KEY
    _TRACE_KEY = [base_key, 0]
    try:
        yield
    finally:
        _TRACE_KEY = prev


def seed(s: int):
    """Set the global RNG seed (paddle.seed parity)."""
    _GLOBAL.seed = int(s)
    _GLOBAL.counter = 0
    np.random.seed(int(s) % (2**32))
    return _GLOBAL


def next_key():
    """Draw a fresh PRNG key from the global stateful generator."""
    if _TRACE_KEY is not None:
        base, n = _TRACE_KEY
        _TRACE_KEY[1] = n + 1
        return jax.random.fold_in(base, n)
    return _GLOBAL.key()


def get_rng_state():
    return _GLOBAL.state()


def set_rng_state(state):
    _GLOBAL.set_state(state)


class RNGStatesTracker:
    """Named RNG states for hybrid parallel (ref: fleet parallel_layers/random.py)."""

    def __init__(self):
        self.states = {}

    def add(self, name: str, seed: int):
        if name in self.states:
            raise ValueError(f"rng state {name} already exists")
        self.states[name] = _GeneratorState(seed)

    @contextlib.contextmanager
    def rng_state(self, name: str = "global_seed"):
        if name not in self.states:
            raise ValueError(f"rng state {name} not registered")
        global _GLOBAL
        orig = _GLOBAL
        _GLOBAL = self.states[name]
        try:
            yield
        finally:
            _GLOBAL = orig


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker


def model_parallel_random_seed(seed_: int, mp_rank: int = 0):
    """Register global/local states for TP-deterministic dropout."""
    global _tracker
    _tracker = RNGStatesTracker()
    _tracker.add("global_seed", seed_)
    _tracker.add("local_seed", seed_ + 1024 + mp_rank)


def get_cuda_rng_state():
    """Accelerator RNG state (ref: get_cuda_rng_state; one stream serves
    all devices under the functional-key design)."""
    return get_rng_state()


def set_cuda_rng_state(state):
    return set_rng_state(state)
