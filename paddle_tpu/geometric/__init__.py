"""paddle.geometric parity (ref: python/paddle/geometric/): graph message
passing + segment reductions, all as XLA segment ops (gather/segment_sum is
the TPU-native form of the reference's CUDA scatter kernels)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor, _run_op


def _num_segments(count, data):
    if count is not None:
        return int(count)
    ids = data._data if isinstance(data, Tensor) else data
    try:
        import numpy as _np
        return int(_np.asarray(ids).max()) + 1 if ids.size else 0
    except jax.errors.TracerArrayConversionError:
        raise ValueError(
            "segment op under tracing needs a static segment count: call "
            "send_u_recv/send_ue_recv with out_size=..., or run the segment "
            "reduction eagerly outside jit") from None


def _segment_reduce(msgs, seg_ids, n, op):
    """One shared reduction for every segment/message-passing op."""
    s32 = seg_ids.astype(jnp.int32)
    if op == "mean":
        tot = jax.ops.segment_sum(msgs, s32, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(msgs[..., :1]), s32,
                                  num_segments=n)
        return tot / jnp.maximum(cnt, 1)
    red = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
           "max": jax.ops.segment_max}[op]
    return red(msgs, s32, num_segments=n)


def _make_segment_op(op):
    def fn(data, segment_ids, name=None):
        n = _num_segments(None, segment_ids)
        return _run_op(f"segment_{op}",
                       lambda d, s: _segment_reduce(d, s, n, op),
                       (data, segment_ids), {})
    fn.__name__ = f"segment_{op}"
    return fn


segment_sum = _make_segment_op("sum")
segment_mean = _make_segment_op("mean")
segment_min = _make_segment_op("min")
segment_max = _make_segment_op("max")


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather source-node features along edges, reduce at destinations
    (ref: geometric.send_u_recv)."""
    n = int(out_size or x.shape[0])
    def f(feat, src, dst):
        msgs = feat[src.astype(jnp.int32)]
        return _segment_reduce(msgs, dst, n, reduce_op)
    return _run_op("send_u_recv", f, (x, src_index, dst_index), {})


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine node features with edge features, then reduce
    (ref: geometric.send_ue_recv)."""
    n = int(out_size or x.shape[0])
    def f(feat, edge, src, dst):
        msgs = feat[src.astype(jnp.int32)]
        msgs = {"add": msgs + edge, "sub": msgs - edge,
                "mul": msgs * edge, "div": msgs / edge}[message_op]
        return _segment_reduce(msgs, dst, n, reduce_op)
    return _run_op("send_ue_recv", f, (x, y, src_index, dst_index), {})


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge messages from src and dst node features
    (ref: geometric.send_uv)."""
    def f(xa, ya, src, dst):
        u = xa[src.astype(jnp.int32)]
        v = ya[dst.astype(jnp.int32)]
        return {"add": u + v, "sub": u - v, "mul": u * v,
                "div": u / v}[message_op]
    return _run_op("send_uv", f, (x, y, src_index, dst_index), {})


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """ref: paddle.geometric.sample_neighbors — one-hop neighbor
    sampling on a CSC graph (host-side op; see incubate/graph_sampling
    for the TPU-native stance)."""
    from ..incubate.graph_sampling import graph_sample_neighbors
    return graph_sample_neighbors(row, colptr, input_nodes, eids=eids,
                                  sample_size=sample_size,
                                  return_eids=return_eids)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """ref: paddle.geometric.reindex_graph — contiguous reindexing of a
    sampled neighborhood: out_nodes is x then newly-seen neighbors in
    first-appearance order; reindex_src maps each neighbor, reindex_dst
    repeats each center node per its neighbor count. Host-side op (the
    output shape is data-dependent)."""
    import numpy as _onp

    from ..incubate.graph_sampling import _np, _remap_ids, _wrap
    xs = _np(x).ravel()
    nb = _np(neighbors).ravel()
    cnt = _np(count).ravel()
    cat = _onp.concatenate([xs, nb])
    _, order = _onp.unique(cat, return_index=True)
    out_nodes = cat[_onp.sort(order)]
    reindex_src = _remap_ids(out_nodes, nb)
    reindex_dst = _onp.repeat(_remap_ids(out_nodes, xs), cnt)
    return (_wrap(reindex_src), _wrap(reindex_dst),
            _wrap(out_nodes, xs.dtype))
