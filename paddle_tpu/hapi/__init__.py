"""High-level API (ref: python/paddle/hapi/)."""
from . import callbacks
from .model import Model
