"""Training callbacks (ref: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import os
import time

import numpy as np


class Callback:
    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = (logs or {}).get("steps")
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float) else
                               f"{k}: {v}" for k, v in (logs or {}).items())
            total = f"/{self.steps}" if self.steps else ""
            print(f"Epoch {self.epoch}: step {step}{total} - {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            items = " - ".join(f"{k}: {v:.4f}" if isinstance(v, float) else
                               f"{k}: {v}" for k, v in (logs or {}).items())
            print(f"Epoch {epoch} done in {dt:.1f}s - {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        from ..optimizer.lr import LRScheduler as Sched
        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step and (s := self._sched()):
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch and (s := self._sched()):
            s.step()


class VisualDL(Callback):
    """Scalar logger writing TensorBoard-compatible event files when
    tensorboard(X) writers are available, else JSONL."""

    def __init__(self, log_dir="./log"):
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._f = open(os.path.join(log_dir, "scalars.jsonl"), "a")
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        import json
        self._step += 1
        rec = {"step": self._step, **{k: float(v) for k, v in (logs or {}).items()
                                      if isinstance(v, (int, float))}}
        self._f.write(json.dumps(rec) + "\n")

    def on_train_end(self, logs=None):
        self._f.close()
