"""High-level Model API (ref: python/paddle/hapi/model.py)."""
from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

from ..autograd import no_grad
from ..framework.io import load as _load
from ..framework.io import save as _save
from ..io import DataLoader, Dataset
from ..metric import Metric
from ..tensor.tensor import Tensor
from .callbacks import Callback, CallbackList, ProgBarLogger


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)

    # -- single-batch ops --------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = self._to_list(inputs)
        labels = self._to_list(labels)
        outputs = self.network(*inputs)
        loss = self._loss(outputs, *labels)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        return [float(loss.item())] + metrics

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = self._to_list(inputs)
        labels = self._to_list(labels)
        outputs = self.network(*inputs)
        loss = self._loss(outputs, *labels) if self._loss else None
        metrics = self._update_metrics(outputs, labels)
        return ([float(loss.item())] if loss is not None else []) + metrics

    @no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        inputs = self._to_list(inputs)
        out = self.network(*inputs)
        return out

    def _update_metrics(self, outputs, labels):
        vals = []
        for m in self._metrics:
            res = m.compute(outputs, *labels)
            v = m.update(res)
            vals.append(v)
        return vals

    @staticmethod
    def _to_list(x):
        if x is None:
            return []
        if isinstance(x, (list, tuple)):
            return list(x)
        return [x]

    # -- loops -------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = self._make_loader(train_data, batch_size, shuffle, drop_last,
                                   num_workers)
        eval_loader = self._make_loader(eval_data, batch_size, False, False,
                                        num_workers) if eval_data is not None else None
        cbks = CallbackList(callbacks or ([ProgBarLogger(log_freq, verbose)]
                                          if verbose else []))
        cbks.set_model(self)
        cbks.on_train_begin()
        steps = len(loader) if hasattr(loader, "__len__") else None
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch, {"steps": steps})
            for m in self._metrics:
                m.reset()
            it = 0
            for batch in loader:
                cbks.on_train_batch_begin(it)
                x, y = self._split_batch(batch)
                outs = self.train_batch(x, y)
                logs = {"loss": outs[0]}
                for m, v in zip(self._metrics, outs[1:]):
                    logs[m.name()] = v
                cbks.on_train_batch_end(it, logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    break
            epoch_logs = dict(logs) if it else {}
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_res = self.evaluate(eval_data, batch_size=batch_size,
                                         verbose=0)
                epoch_logs.update({f"eval_{k}": v for k, v in eval_res.items()})
            cbks.on_epoch_end(epoch, epoch_logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
        cbks.on_train_end()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = self._make_loader(eval_data, batch_size, False, False,
                                   num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for i, batch in enumerate(loader):
            x, y = self._split_batch(batch)
            outs = self.eval_batch(x, y)
            if self._loss:
                losses.append(outs[0])
            if num_iters is not None and i + 1 >= num_iters:
                break
        res = {}
        if losses:
            res["loss"] = float(np.mean(losses))
        for m in self._metrics:
            res[m.name()] = m.accumulate()
        return res

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=True,
                verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, False,
                                   num_workers)
        outs = []
        for batch in loader:
            x, _ = self._split_batch(batch, has_label=False)
            outs.append(self.predict_batch(x))
        return outs

    def _make_loader(self, data, batch_size, shuffle, drop_last, num_workers):
        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)

    @staticmethod
    def _split_batch(batch, has_label=True):
        if isinstance(batch, (list, tuple)):
            if has_label and len(batch) >= 2:
                return list(batch[:-1]), [batch[-1]]
            return list(batch), []
        return [batch], []

    # -- persistence --------------------------------------------------------
    def save(self, path, training=True):
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        self.network.set_state_dict(_load(path + ".pdparams"))
        opt_path = path + ".pdopt"
        if os.path.exists(opt_path) and self._optimizer is not None \
                and not reset_optimizer:
            self._optimizer.set_state_dict(_load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        total = sum(p.size for p in self.network.parameters())
        trainable = sum(p.size for p in self.network.parameters()
                        if not p.stop_gradient)
        s = (f"Total params: {total:,}\nTrainable params: {trainable:,}\n"
             f"Non-trainable params: {total - trainable:,}")
        print(s)
        return {"total_params": total, "trainable_params": trainable}
