"""paddle.summary / paddle.flops (ref: python/paddle/hapi/model_summary.py,
python/paddle/hapi/dynamic_flops.py)."""
from __future__ import annotations

import numpy as np


def summary(net, input_size=None, dtypes=None, input=None):
    """Layer-by-layer output shapes + param counts; returns the totals dict
    and prints a table like the reference."""
    import paddle_tpu as paddle
    from ..nn.layer.layers import Layer

    rows = []
    hooks = []

    def make_hook(name, layer):
        def hook(lyr, inputs, output):
            out = output[0] if isinstance(output, (tuple, list)) else output
            shape = list(getattr(out, "shape", [])) or ["-"]
            n_params = sum(int(np.prod(p.shape))
                           for p in lyr._parameters.values()
                           if p is not None)
            rows.append((f"{type(lyr).__name__}-{len(rows) + 1}", shape,
                         n_params))
        return hook

    for name, sub in net.named_sublayers():
        if not sub._sub_layers:  # leaves only
            hooks.append(sub.register_forward_post_hook(make_hook(name, sub)))

    if input is None:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        sizes = (input_size if isinstance(input_size, (list, tuple))
                 and isinstance(input_size[0], (list, tuple))
                 else [input_size])
        dts = dtypes or ["float32"] * len(sizes)
        input = [paddle.zeros(list(s), dtype=d) for s, d in zip(sizes, dts)]
        out = net(*input)
    else:
        out = net(input)
    for h in hooks:
        h.remove()

    total_params = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)

    line = "-" * 64
    print(line)
    print(f"{'Layer (type)':<28}{'Output Shape':<22}{'Param #':>12}")
    print(line)
    for name, shape, n in rows:
        print(f"{name:<28}{str(shape):<22}{n:>12,}")
    print(line)
    print(f"Total params: {total_params:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total_params - trainable:,}")
    print(line)
    return {"total_params": total_params, "trainable_params": trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough MAC count for Conv2D/Linear stacks (ref: paddle.flops)."""
    import paddle_tpu as paddle
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import Conv2D

    total = [0]
    hooks = []

    def conv_hook(lyr, inputs, output):
        out = output[0] if isinstance(output, (tuple, list)) else output
        oh, ow = out.shape[-2], out.shape[-1]
        # weight [out_c, in_c/groups, kh, kw] already reflects grouping
        macs = int(np.prod(lyr.weight.shape)) * oh * ow
        total[0] += macs

    def linear_hook(lyr, inputs, output):
        total[0] += int(np.prod(lyr.weight.shape))

    for _, sub in net.named_sublayers():
        if isinstance(sub, Conv2D):
            hooks.append(sub.register_forward_post_hook(conv_hook))
        elif isinstance(sub, Linear):
            hooks.append(sub.register_forward_post_hook(linear_hook))

    x = paddle.zeros(list(input_size))
    net(x)
    for h in hooks:
        h.remove()
    if print_detail:
        print(f"Total FLOPs (MACs): {total[0]:,}")
    return total[0]
