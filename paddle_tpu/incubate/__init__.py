"""Incubate: experimental API surface (ref: python/paddle/incubate/)."""
from . import asp
from . import distributed
from . import nn
from . import optimizer
