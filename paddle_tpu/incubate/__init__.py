"""Incubate: experimental API surface (ref: python/paddle/incubate/)."""
from . import asp
from . import autograd
from . import distributed
from . import nn
from . import optimizer

# segment ops + graph message passing (ref: python/paddle/incubate/tensor/
# math.py + operators/graph_send_recv.py — these predate paddle.geometric
# and alias the same implementations)
from ..geometric import (segment_sum, segment_mean,  # noqa: F401
                         segment_min, segment_max)
from ..geometric import send_u_recv as graph_send_recv  # noqa: F401
from .graph_sampling import (graph_khop_sampler,  # noqa: F401
                             graph_sample_neighbors)


def softmax_mask_fuse(x, mask, name=None):
    """Fused masked softmax (ref: incubate.softmax_mask_fuse): additive
    mask broadcast onto [B, H, Sq, Sk] scores; on TPU, XLA fuses the
    add+softmax chain, so one expression IS the fused kernel."""
    import jax
    from ..tensor.tensor import _run_op

    def f(a, m):
        import jax.numpy as jnp
        return jax.nn.softmax(a.astype(jnp.float32) + m.astype(jnp.float32),
                              axis=-1).astype(a.dtype)
    return _run_op("softmax_mask_fuse", f, (x, mask), {})


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal-masked fused softmax (ref: the GPT kernel variant)."""
    from ..tensor.tensor import _run_op

    def f(a):
        import jax
        import jax.numpy as jnp
        sq, sk = a.shape[-2], a.shape[-1]
        # bottom-right aligned causal band (supports Sq != Sk, e.g. a
        # decode step's [*, 1, Sk] scores attend the whole prefix)
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        z = jnp.where(causal, a.astype(jnp.float32), -1e30)
        return jax.nn.softmax(z, axis=-1).astype(a.dtype)
    return _run_op("softmax_mask_fuse_ut", f, (x,), {})


def identity_loss(x, reduction="none"):
    """ref: incubate.identity_loss (IPU pattern: mark a value as the loss).
    reduction: 'none'(0)/'sum'(1)/'mean'(2) — int codes accepted."""
    red = {0: "none", 1: "sum", 2: "mean"}.get(reduction, reduction)
    if red == "sum":
        return x.sum()
    if red == "mean":
        return x.mean()
    return x
