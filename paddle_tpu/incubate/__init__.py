"""Incubate: experimental API surface (ref: python/paddle/incubate/)."""
from . import nn
from . import distributed
