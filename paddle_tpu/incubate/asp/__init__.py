"""Automatic SParsity: 2:4 structured pruning
(ref: python/paddle/incubate/asp/ — prune_model, decorate, calculate_density).

TPU note: 2:4 sparsity has no MXU fast path (that's an Ampere tensor-core
feature), so here the masks buy model compression / regularization; matmuls
run dense. Mask semantics and the API match the reference.
"""
from __future__ import annotations

import numpy as np

_MASKS = {}          # id(param) -> (param, np mask)
_EXCLUDED = set()    # layer full names excluded from pruning


def calculate_density(x):
    arr = np.asarray(x.numpy() if hasattr(x, "numpy") else x)
    return float((arr != 0).sum() / arr.size)


def _mask_1d(weight, n=2, m=4):
    """Keep the n largest-|w| of every m consecutive weights along axis 0
    (the input dim of a Linear [in, out] weight)."""
    w = np.asarray(weight)
    flat = w.reshape(-1, w.shape[-1]) if w.ndim > 1 else w.reshape(-1, 1)
    rows, cols = flat.shape
    pad = (-rows) % m
    if pad:
        flat = np.concatenate([flat, np.zeros((pad, cols), flat.dtype)])
    groups = np.abs(flat).reshape(-1, m, cols)
    order = np.argsort(groups, axis=1)           # ascending
    mask = np.ones_like(groups)
    drop = order[:, : m - n, :]
    np.put_along_axis(mask, drop, 0.0, axis=1)
    mask = mask.reshape(-1, cols)[:rows]
    return mask.reshape(w.shape).astype(np.float32)


def check_sparsity(weight, n=2, m=4):
    """True if every m-group along axis 0 has at most n nonzeros."""
    w = np.asarray(weight)
    flat = w.reshape(-1, w.shape[-1]) if w.ndim > 1 else w.reshape(-1, 1)
    rows, cols = flat.shape
    pad = (-rows) % m
    if pad:
        flat = np.concatenate([flat, np.zeros((pad, cols), flat.dtype)])
    groups = flat.reshape(-1, m, cols)
    return bool(((groups != 0).sum(axis=1) <= n).all())


def set_excluded_layers(model, layer_names):
    for name in layer_names:
        _EXCLUDED.add(name)


def reset_excluded_layers(model=None):
    _EXCLUDED.clear()


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to every supported (Linear) weight in-place and
    remember them so `decorate`d optimizers re-apply after each step."""
    import jax.numpy as jnp

    from ...nn.layer.common import Linear
    pruned = {}
    for name, layer in model.named_sublayers():
        if not isinstance(layer, Linear) or name in _EXCLUDED:
            continue
        w = layer.weight
        mask = _mask_1d(w.numpy(), n=n, m=m)
        w._data = w._data * jnp.asarray(mask, w._data.dtype)
        if with_mask:
            _MASKS[id(w)] = (w, mask)
        pruned[name] = float(mask.mean())
    return pruned


def decorate(optimizer):
    """Wrap optimizer.step so masks survive the update (ref: asp.decorate)."""
    import jax.numpy as jnp
    orig_step = optimizer.step

    def masked_step(*args, **kwargs):
        out = orig_step(*args, **kwargs)
        for w, mask in _MASKS.values():
            w._data = w._data * jnp.asarray(mask, w._data.dtype)
        return out

    optimizer.step = masked_step
    return optimizer
