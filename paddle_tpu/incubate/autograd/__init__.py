"""ref: paddle.incubate.autograd — the prim/forward-AD API. The reference
lowers to primitive ops and transposes them; jax's jvp/vjp ARE that
machinery, so the API maps directly.
"""
from __future__ import annotations

from . import primapi  # noqa: F401
from .primapi import forward_grad, grad  # noqa: F401


_PRIM_ENABLED = False


def prim_enabled():
    return _PRIM_ENABLED


def enable_prim():
    global _PRIM_ENABLED
    _PRIM_ENABLED = True


def disable_prim():
    global _PRIM_ENABLED
    _PRIM_ENABLED = False
