"""ref: python/paddle/incubate/autograd/primapi.py — forward_grad (JVP)
and grad over the primitive system. TPU-native: jax.jvp / the existing
reverse-mode tape."""
from __future__ import annotations


def _unwrap(xs):
    from ...tensor.tensor import Tensor
    single = isinstance(xs, Tensor)
    lst = [xs] if single else list(xs)
    return single, [t._data for t in lst]


def forward_grad(outputs_fn_or_outputs, inputs, grad_inputs=None):
    """Forward-mode derivatives (JVP). Callable form:
    forward_grad(fn, inputs, tangents) -> (outputs, output_tangents);
    the reference's static form (outputs, inputs) is served by the same
    call with fn reconstructed from the tape — pass a callable here."""
    import jax
    import jax.numpy as jnp

    from ...tensor.tensor import Tensor
    if not callable(outputs_fn_or_outputs):
        raise TypeError(
            "forward_grad takes a callable on this backend (the static-"
            "program form has no separate primitive IR): "
            "forward_grad(fn, inputs, tangents)")
    fn = outputs_fn_or_outputs
    single, xs = _unwrap(inputs)
    if grad_inputs is None:
        vs = [jnp.ones_like(x) for x in xs]
    else:
        _, vs = _unwrap(grad_inputs)

    def raw(*arrays):
        args = [Tensor._from_data(a) for a in arrays]
        out = fn(*args) if not single else fn(args[0])
        return out._data if isinstance(out, Tensor) else out

    y, yd = jax.jvp(raw, tuple(xs), tuple(vs))
    return Tensor(y), Tensor(yd)


def grad(outputs, inputs, grad_outputs=None):
    """Reverse-mode gradients (ref: primapi.grad): same contract as
    paddle.grad over the eager tape."""
    from ...autograd import grad as _grad
    return _grad(outputs, inputs, grad_outputs=grad_outputs)
