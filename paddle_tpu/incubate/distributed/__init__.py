from . import models
