from . import moe
