"""MoE layer with expert parallelism
(ref: python/paddle/incubate/distributed/models/moe/moe_layer.py +
gates gshard/switch, collective ops global_scatter/global_gather).

TPU-native: gating + capacity bucketing as einsum dispatch
(paddle_tpu.parallel.moe); expert weights stacked on a leading axis sharded
over 'ep' — GSPMD turns the dispatch einsum into the all-to-all the reference
issues via global_scatter.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .....nn import functional as F
from .....nn.layer.layers import Layer
from .....nn import initializer as I
from .....parallel.moe import moe_dispatch_combine, top_k_gating
from .....tensor.tensor import Tensor, _run_op


class BaseGate(Layer):
    def __init__(self, d_model, num_experts):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts


class GShardGate(BaseGate):
    """top-2 gate with load-balancing aux loss (ref: gshard_gate.py)."""

    def __init__(self, d_model, num_experts, topk=2, capacity_factor=1.2,
                 group=None):
        super().__init__(d_model, num_experts)
        self.topk = topk
        self.capacity_factor = capacity_factor
        self.weight = self.create_parameter(
            [d_model, num_experts],
            default_initializer=I.Normal(0.0, d_model ** -0.5))


class SwitchGate(GShardGate):
    """top-1 switch gate (ref: switch_gate.py)."""

    def __init__(self, d_model, num_experts, capacity_factor=1.2, group=None):
        super().__init__(d_model, num_experts, topk=1,
                         capacity_factor=capacity_factor)


class ExpertMLP(Layer):
    """One expert FFN; MoELayer stacks num_experts of these into one tensor."""

    def __init__(self, d_model, d_hidden, activation="gelu"):
        super().__init__()
        self.w1 = self.create_parameter([d_model, d_hidden],
                                        default_initializer=I.XavierNormal())
        self.b1 = self.create_parameter([d_hidden], is_bias=True)
        self.w2 = self.create_parameter([d_hidden, d_model],
                                        default_initializer=I.XavierNormal())
        self.b2 = self.create_parameter([d_model], is_bias=True)
        self.activation = activation


class MoELayer(Layer):
    """Mixture-of-experts layer (ref: moe_layer.py MoELayer).

    experts: list[ExpertMLP] or (d_model, d_hidden) to auto-build.
    gate: 'gshard' | 'switch' | BaseGate instance.
    """

    def __init__(self, d_model=None, experts=None, gate="gshard",
                 num_experts=None, d_hidden=None, moe_group=None,
                 mp_group=None, recompute_interval=0, capacity_factor=1.2,
                 topk=None, activation="gelu", **kwargs):
        super().__init__()
        if isinstance(experts, (list, tuple)):
            self.num_experts = len(experts)
            d_model = experts[0].w1.shape[0]
            d_hidden = experts[0].w1.shape[1]
            self.experts_list = list(experts)
        else:
            assert num_experts and d_model and d_hidden
            self.num_experts = num_experts
            self.experts_list = [ExpertMLP(d_model, d_hidden, activation)
                                 for _ in range(num_experts)]
        for i, e in enumerate(self.experts_list):
            self.add_sublayer(f"expert_{i}", e)
        self.d_model = d_model
        self.activation = activation
        if isinstance(gate, BaseGate):
            self.gate = gate
        elif gate == "switch":
            self.gate = SwitchGate(d_model, self.num_experts,
                                   capacity_factor=capacity_factor)
        else:
            self.gate = GShardGate(d_model, self.num_experts,
                                   topk=topk or 2,
                                   capacity_factor=capacity_factor)
        self.capacity_factor = capacity_factor
        self.aux_loss = None

    def forward(self, x):
        """x: [B, S, D] (or [T, D]). Returns same shape; aux loss stored on
        self.aux_loss (reference behavior: retrieved by the trainer)."""
        shape = x.shape
        d = shape[-1]
        topk = self.gate.topk
        act_name = self.activation
        n_exp = self.num_experts
        cap_f = self.capacity_factor

        expert_stack = [
            (e.w1, e.b1, e.w2, e.b2) for e in self.experts_list]
        flat_ws = [w for tup in expert_stack for w in tup]

        def f(xa, gw, *ws):
            tokens = xa.reshape(-1, d)
            w1 = jnp.stack(ws[0::4])
            b1 = jnp.stack(ws[1::4])
            w2 = jnp.stack(ws[2::4])
            b2 = jnp.stack(ws[3::4])
            logits = tokens.astype(jnp.float32) @ gw.astype(jnp.float32)
            act = jax.nn.gelu if act_name == "gelu" else jax.nn.relu

            def expert_fn(params, toks):
                ew1, eb1, ew2, eb2 = params
                return act(toks @ ew1 + eb1) @ ew2 + eb2

            out, aux = moe_dispatch_combine(
                tokens, logits, expert_fn, (w1, b1, w2, b2), n_exp,
                k=topk, capacity_factor=cap_f)
            return out.reshape(xa.shape), aux

        out, aux = _run_op("moe_layer", f, (x, self.gate.weight) + tuple(flat_ws), {})
        self.aux_loss = aux
        return out
