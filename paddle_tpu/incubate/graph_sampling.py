"""Graph neighborhood sampling (ref: python/paddle/incubate/operators/
graph_khop_sampler.py and graph_sample_neighbors.py; CUDA kernels under
paddle/phi/kernels/gpu/graph_sample_neighbors_kernel.cu).

TPU-native stance: these are HOST-side data-preparation ops. Their outputs
are ragged (degree-dependent) and data-dependent — shapes XLA cannot
compile — and in real pipelines they run in the input pipeline (DataLoader
workers), not on the accelerator; the reference's GPU kernels exist because
its samplers feed GPU-resident graphs. NumPy is the right engine here; the
sampled, reindexed, fixed-shape subgraph tensors are what go to device.

Graph layout: CSC, matching the reference — ``colptr[i]:colptr[i+1]``
slices ``row`` to give the (in-)neighbors of node ``i``.
"""
from __future__ import annotations

import numpy as np

from ..tensor.tensor import Tensor


def _np(x):
    if isinstance(x, Tensor):
        return np.asarray(x._data)
    return np.asarray(x)


def _host_rng():
    """NumPy Generator seeded from the framework's global RNG state.

    The reference samplers draw from the stateful per-device Generator
    (pinned by ``paddle.seed``); an unseeded per-call ``default_rng()``
    made every run irreproducible (ADVICE r5). Each call folds a fresh
    subkey out of the global generator, so ``paddle.seed(s)`` pins the
    whole sample stream while consecutive calls still draw fresh
    randomness.
    """
    import jax
    import jax.numpy as jnp

    from ..framework.random import next_key
    key = next_key()
    if jnp.issubdtype(key.dtype, jnp.integer):  # old-style raw uint32 pair
        data = np.asarray(key)
    else:  # new-style typed key
        data = np.asarray(jax.random.key_data(key))
    return np.random.default_rng(data.astype(np.uint32).ravel().tolist())


def _wrap(a, dtype=None):
    import jax.numpy as jnp
    arr = np.asarray(a)
    if dtype is not None:
        arr = arr.astype(dtype)
    return Tensor._from_data(jnp.asarray(arr))


def _remap_ids(id_order, ids):
    """Positions of ``ids`` within ``id_order`` (whose values are unique),
    fully vectorized: sort id_order once, searchsorted, invert the sort
    permutation — a python dict + per-element loop at 1M-neighbor scale
    took seconds on the host data path (review r5)."""
    ids = np.asarray(ids)
    perm = np.argsort(id_order, kind="stable")
    pos_in_sorted = np.searchsorted(id_order, ids, sorter=perm)
    return perm[pos_in_sorted].astype(np.int64)


def _sample_one_hop(row, colptr, nodes, sample_size, eids, rng):
    """Sample up to ``sample_size`` neighbors (without replacement) for
    each node. Returns (neighbors, counts, edge_ids) concatenated in node
    order; sample_size < 0 keeps every neighbor."""
    srcs, counts, edges = [], [], []
    for n in nodes:
        beg, end = int(colptr[n]), int(colptr[n + 1])
        neigh = row[beg:end]
        eix = np.arange(beg, end)
        if 0 <= sample_size < len(neigh):
            pick = rng.choice(len(neigh), size=sample_size, replace=False)
            neigh = neigh[pick]
            eix = eix[pick]
        srcs.append(neigh)
        counts.append(len(neigh))
        edges.append(eids[eix] if eids is not None else eix)
    cat = (np.concatenate(srcs) if srcs
           else np.empty((0,), row.dtype))
    ecat = (np.concatenate(edges) if edges
            else np.empty((0,), np.int64))
    return cat, np.asarray(counts, np.int32), ecat


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """ref: paddle.incubate.graph_sample_neighbors — one-hop sampling.

    Returns (out_neighbors, out_count[, out_eids]): the sampled
    neighbors of each input node concatenated, the per-node neighbor
    counts, and (when return_eids) the edge ids of the sampled edges.

    ``perm_buffer`` / ``flag_perm_buffer`` are accepted for API parity
    and ignored: the reference's pre-allocated Fisher-Yates workspace is
    a CUDA-kernel optimization; the host-side NumPy sampler draws
    without replacement directly, so the buffer is a no-op here.
    """
    rng = _host_rng()
    row_np, col_np = _np(row), _np(colptr)
    nodes = _np(input_nodes).ravel()
    if return_eids and eids is None:
        raise ValueError(
            "graph_sample_neighbors: return_eids=True needs eids")
    eids_np = _np(eids).ravel() if eids is not None else None
    neigh, cnt, echosen = _sample_one_hop(row_np, col_np, nodes,
                                          int(sample_size), eids_np, rng)
    out = (_wrap(neigh, row_np.dtype), _wrap(cnt))
    if return_eids:
        return out + (_wrap(echosen),)
    return out


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sort_eids=None, return_eids=False, name=None):
    """ref: paddle.incubate.graph_khop_sampler — multi-hop sampling with
    subgraph reindexing.

    Hop i samples ``sample_sizes[i]`` neighbors of the current frontier;
    all sampled edges are collected and reindexed against the unique
    node set (input nodes first, then newly discovered nodes in order of
    first appearance). Returns (edge_src, edge_dst, sample_index,
    reindex_x[, edge_eids]): reindexed edge endpoints, the original ids
    of the unique nodes, and the positions of the input nodes in that
    unique set.
    """
    rng = _host_rng()
    row_np, col_np = _np(row), _np(colptr)
    nodes = _np(input_nodes).ravel()
    if return_eids and sort_eids is None:
        raise ValueError(
            "graph_khop_sampler: return_eids=True needs sort_eids")
    eids_np = _np(sort_eids).ravel() if sort_eids is not None else None

    frontier = nodes
    all_src, all_dst, all_eid = [], [], []
    for k in list(sample_sizes):
        neigh, cnt, echosen = _sample_one_hop(row_np, col_np, frontier,
                                              int(k), eids_np, rng)
        dst = np.repeat(frontier, cnt)
        all_src.append(neigh)
        all_dst.append(dst)
        all_eid.append(echosen)
        frontier = np.unique(neigh)

    src = (np.concatenate(all_src) if all_src
           else np.empty((0,), row_np.dtype))
    dst = (np.concatenate(all_dst) if all_dst
           else np.empty((0,), row_np.dtype))
    eid = (np.concatenate(all_eid) if all_eid
           else np.empty((0,), np.int64))

    # unique node set: input nodes first (dedup'd, keeping order), then
    # sampled nodes in first-appearance order
    uniq, order = np.unique(np.concatenate([nodes, src]),
                            return_index=True)
    sample_index = np.concatenate([nodes, src])[np.sort(order)]
    edge_src = _remap_ids(sample_index, src)
    edge_dst = _remap_ids(sample_index, dst)
    reindex_x = _remap_ids(sample_index, nodes)

    out = (_wrap(edge_src), _wrap(edge_dst),
           _wrap(sample_index, row_np.dtype), _wrap(reindex_x))
    if return_eids:
        return out + (_wrap(eid),)
    return out
