"""Fused transformer layers (ref: python/paddle/incubate/nn/layer/).

FusedMultiTransformer is the reference's inference workhorse
(fused_multi_transformer_op.cu: full decoder stack incl. KV cache). Here the
stack is a lax.scan over stacked per-layer weights with the Pallas attention
kernel — the fusion XLA+Pallas equivalent.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...nn import functional as F
from ...nn.layer.layers import Layer
from ...nn import initializer as I
from ...tensor.tensor import Tensor, _run_op
from . import functional


class FusedLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [in_features, out_features] if not transpose_weight
            else [out_features, in_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)
        self.transpose_weight = transpose_weight

    def forward(self, x):
        return functional.fused_linear(x, self.weight, self.bias,
                                       self.transpose_weight)


class FusedRMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None):
        super().__init__()
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.epsilon = epsilon

    def forward(self, x, residual=None):
        return functional.fused_rms_norm(x, self.weight, epsilon=self.epsilon,
                                         residual=residual)


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.0,
                 attn_dropout_rate=0.0, normalize_before=False, **kw):
        super().__init__()
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, embed_dim],
            default_initializer=I.XavierNormal())
        self.qkv_bias = self.create_parameter(
            [3, num_heads, self.head_dim], is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], default_initializer=I.XavierNormal())
        self.linear_bias = self.create_parameter([embed_dim], is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], default_initializer=I.Constant(1.0))
        self.pre_ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate

    def forward(self, x, attn_mask=None, cache=None):
        return functional.fused_multi_head_attention(
            x, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            training=self.training, num_heads=self.num_heads)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 activation="relu", normalize_before=False, **kw):
        super().__init__()
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], default_initializer=I.XavierNormal())
        self.linear1_bias = self.create_parameter([dim_feedforward], is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], default_initializer=I.XavierNormal())
        self.linear2_bias = self.create_parameter([d_model], is_bias=True)
        self.ln1_scale = self.create_parameter(
            [d_model], default_initializer=I.Constant(1.0))
        self.ln1_bias = self.create_parameter([d_model], is_bias=True)
        self.ln2_scale = self.create_parameter(
            [d_model], default_initializer=I.Constant(1.0))
        self.ln2_bias = self.create_parameter([d_model], is_bias=True)
        self.dropout_rate = dropout_rate
        self.activation = activation
        self.normalize_before = normalize_before

    def forward(self, x):
        return functional.fused_feedforward(
            x, self.linear1_weight, self.linear2_weight,
            self.linear1_bias, self.linear2_bias,
            self.ln1_scale, self.ln1_bias, self.ln2_scale, self.ln2_bias,
            dropout1_rate=self.dropout_rate, dropout2_rate=self.dropout_rate,
            activation=self.activation, pre_layer_norm=self.normalize_before,
            training=self.training)


class FusedMultiTransformer(Layer):
    """Decoder stack with per-layer weights stacked for a scanned, fused
    forward + incremental KV-cache decode (ref: fused_multi_transformer_op.cu).
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward, num_layers=1,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.num_layers = num_layers
        self.normalize_before = normalize_before
        self.activation = activation
        L = num_layers
        xavier = I.XavierNormal()

        def mk(shape, init=None):
            return self.create_parameter(shape, default_initializer=init or xavier)

        self.ln_scales = mk([L, embed_dim], I.Constant(1.0))
        self.ln_biases = mk([L, embed_dim], I.Constant(0.0))
        self.qkv_weights = mk([L, embed_dim, 3 * embed_dim])
        self.qkv_biases = mk([L, 3 * embed_dim], I.Constant(0.0))
        self.linear_weights = mk([L, embed_dim, embed_dim])
        self.linear_biases = mk([L, embed_dim], I.Constant(0.0))
        self.ffn_ln_scales = mk([L, embed_dim], I.Constant(1.0))
        self.ffn_ln_biases = mk([L, embed_dim], I.Constant(0.0))
        self.ffn1_weights = mk([L, embed_dim, dim_feedforward])
        self.ffn1_biases = mk([L, dim_feedforward], I.Constant(0.0))
        self.ffn2_weights = mk([L, dim_feedforward, embed_dim])
        self.ffn2_biases = mk([L, embed_dim], I.Constant(0.0))

    def gen_cache(self, batch, max_len, dtype=None):
        """Stacked KV cache for incremental decode (ref: the cache tensors
        fused_multi_transformer_op fills in place): k/v [L, B, max_len, nh,
        hd] + a position scalar."""
        from ... import zeros
        L, nh, hd = self.num_layers, self.num_heads, self.head_dim
        shape = [L, batch, max_len, nh, hd]
        k = zeros(shape, dtype=dtype or "float32")
        v = zeros(shape, dtype=dtype or "float32")
        return {"k": k, "v": v, "pos": 0}

    @staticmethod
    def _block(h, per, nh, hd, act_name, attn_step):
        """One decoder block, shared by the full-forward and cached paths
        (attn_step(q, k, v) -> attn supplies the attention variant)."""
        (ls, lb, qw, qb, lw, lbias, fs_, fb, w1, b1, w2, b2) = per

        def ln(t, s_, b_):
            t32 = t.astype(jnp.float32)
            mu = t32.mean(-1, keepdims=True)
            var = t32.var(-1, keepdims=True)
            return ((t32 - mu) * jax.lax.rsqrt(var + 1e-5)
                    * s_ + b_).astype(t.dtype)

        b_, s_len = h.shape[0], h.shape[1]
        resid = h
        y = ln(h, ls, lb)
        qkv = (y @ qw + qb).reshape(b_, s_len, 3, nh, hd)
        attn = attn_step(qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
        h = resid + attn.reshape(b_, s_len, nh * hd) @ lw + lbias
        resid = h
        y = ln(h, fs_, fb)
        act = (jax.nn.gelu if act_name == "gelu" else jax.nn.relu)
        return resid + act(y @ w1 + b1) @ w2 + b2

    def _cached_forward(self, x, caches, attn_mask=None, time_step=None):
        """Prefill (seq>1) or one decode step (seq==1) against the cache.
        Same carry-resident cache-in-scan pattern as models/llama.py
        llama_decode_step: caches ride the scan CARRY and update in place,
        no per-layer cache copies. Returns (out, new_caches).

        attn_mask: optional bool/additive mask broadcastable to
        [B, nh, seq, max_len] (e.g. padding); time_step overrides the
        cache's position (reference API)."""
        nh, hd = self.num_heads, self.head_dim
        act_name = self.activation
        pos = int(time_step) if time_step is not None else int(caches["pos"])
        s_in = int(x.shape[1])
        max_len = int(caches["k"].shape[2])
        if pos + s_in > max_len:
            raise ValueError(
                f"KV cache overflow: pos {pos} + seq {s_in} > max_len "
                f"{max_len} (dynamic_update_slice would silently clamp)")
        # pos enters as a TRACED operand: every decode step reuses one
        # compiled executable instead of retracing per position
        pos_t = Tensor(np.asarray(pos, np.int32))

        f = self._cached_fn()
        args = (x, caches["k"], caches["v"], pos_t, attn_mask)
        out, new_k, new_v = _run_op(
            "fused_multi_transformer_cached", f,
            args + (self.ln_scales, self.ln_biases, self.qkv_weights,
                    self.qkv_biases, self.linear_weights, self.linear_biases,
                    self.ffn_ln_scales, self.ffn_ln_biases,
                    self.ffn1_weights, self.ffn1_biases, self.ffn2_weights,
                    self.ffn2_biases), {})
        return out, {"k": new_k, "v": new_v, "pos": pos + s_in}

    def _cached_fn(self):
        """The cached-decode kernel, built and jitted ONCE per module:
        jax.jit's shape-keyed cache makes every same-shape decode step reuse
        one compiled executable (a fresh closure per call would retrace)."""
        if getattr(self, "_cached_jit", None) is not None:
            return self._cached_jit
        nh, hd = self.num_heads, self.head_dim
        act_name = self.activation

        def f(xa, kc, vc, pos_a, mask, *ws):
            s_len = xa.shape[1]
            n_layers = kc.shape[0]

            def layer(carry, xs):
                h, kcc, vcc = carry
                per, li = xs
                cell = {}

                def attn_step(q, k, v):
                    zero = jnp.zeros((), jnp.int32)
                    kcc2 = jax.lax.dynamic_update_slice(
                        kcc, k.astype(kcc.dtype)[None],
                        (li, zero, pos_a.astype(jnp.int32), zero, zero))
                    vcc2 = jax.lax.dynamic_update_slice(
                        vcc, v.astype(vcc.dtype)[None],
                        (li, zero, pos_a.astype(jnp.int32), zero, zero))
                    cell["k"], cell["v"] = kcc2, vcc2
                    kl = jax.lax.dynamic_index_in_dim(kcc2, li, 0,
                                                      keepdims=False)
                    vl = jax.lax.dynamic_index_in_dim(vcc2, li, 0,
                                                      keepdims=False)
                    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
                    kh = jnp.swapaxes(kl, 1, 2).astype(jnp.float32)
                    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) \
                        / (hd ** 0.5)
                    kpos = jnp.arange(kl.shape[1])[None, None, None, :]
                    qpos = (pos_a + jnp.arange(s_len))[None, None, :, None]
                    ok = kpos <= qpos
                    logits = jnp.where(ok, logits, -1e30)
                    if mask is not None:
                        if mask.dtype == jnp.bool_:
                            logits = jnp.where(mask, logits, -1e30)
                        else:
                            logits = logits + mask.astype(jnp.float32)
                    probs = jax.nn.softmax(logits, axis=-1)
                    attn = jnp.einsum(
                        "bhqk,bhkd->bhqd", probs,
                        jnp.swapaxes(vl, 1, 2).astype(jnp.float32))
                    return jnp.swapaxes(attn, 1, 2).astype(h.dtype)

                h = FusedMultiTransformer._block(h, per, nh, hd, act_name,
                                                 attn_step)
                return (h, cell["k"], cell["v"]), None

            idxs = jnp.arange(n_layers, dtype=jnp.int32)
            (h, new_k, new_v), _ = jax.lax.scan(
                layer, (xa, kc, vc), (ws, idxs))
            return h, new_k, new_v

        self._cached_jit = jax.jit(f)
        return self._cached_jit

    def forward(self, x, attn_mask=None, caches=None, time_step=None):
        nh, hd = self.num_heads, self.head_dim
        act_name = self.activation
        if caches is not None:
            return self._cached_forward(x, caches, attn_mask=attn_mask,
                                        time_step=time_step)

        def f(xa, mask, *ws):
            def attn_step(q, k, v):
                from ...nn.functional.attention import _xla_sdpa
                from ...ops._common import interpret_mode
                if mask is not None or interpret_mode():
                    return _xla_sdpa(q, k, v, attn_mask=mask, is_causal=True)
                from ...ops.flash_attention import flash_attention_bshd
                return flash_attention_bshd(q, k, v, causal=True)

            def layer(h, per):
                return FusedMultiTransformer._block(
                    h, per, nh, hd, act_name, attn_step), None

            h, _ = jax.lax.scan(layer, xa, ws)
            return h

        return _run_op("fused_multi_transformer", f,
                       (x, attn_mask,
                        self.ln_scales, self.ln_biases, self.qkv_weights,
                        self.qkv_biases, self.linear_weights,
                        self.linear_biases, self.ffn_ln_scales,
                        self.ffn_ln_biases, self.ffn1_weights,
                        self.ffn1_biases, self.ffn2_weights,
                        self.ffn2_biases), {})


class FusedTransformerEncoderLayer(Layer):
    """ref: incubate.nn.FusedTransformerEncoderLayer — one encoder block
    built from the fused attention + feed-forward layers (the reference
    composes fused_multi_head_attention and fused_feedforward ops)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(attn_dropout_rate if attn_dropout_rate
                               is not None else dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask)
        return self.ffn(out)
