"""Fused transformer layers (ref: python/paddle/incubate/nn/layer/).

FusedMultiTransformer is the reference's inference workhorse
(fused_multi_transformer_op.cu: full decoder stack incl. KV cache). Here the
stack is a lax.scan over stacked per-layer weights with the Pallas attention
kernel — the fusion XLA+Pallas equivalent.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...nn import functional as F
from ...nn.layer.layers import Layer
from ...nn import initializer as I
from ...tensor.tensor import Tensor, _run_op
from . import functional


class FusedLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [in_features, out_features] if not transpose_weight
            else [out_features, in_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)
        self.transpose_weight = transpose_weight

    def forward(self, x):
        return functional.fused_linear(x, self.weight, self.bias,
                                       self.transpose_weight)


class FusedRMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None):
        super().__init__()
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.epsilon = epsilon

    def forward(self, x, residual=None):
        return functional.fused_rms_norm(x, self.weight, epsilon=self.epsilon,
                                         residual=residual)


class FusedMultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.0,
                 attn_dropout_rate=0.0, normalize_before=False, **kw):
        super().__init__()
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.qkv_weight = self.create_parameter(
            [3, num_heads, self.head_dim, embed_dim],
            default_initializer=I.XavierNormal())
        self.qkv_bias = self.create_parameter(
            [3, num_heads, self.head_dim], is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], default_initializer=I.XavierNormal())
        self.linear_bias = self.create_parameter([embed_dim], is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], default_initializer=I.Constant(1.0))
        self.pre_ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate

    def forward(self, x, attn_mask=None, cache=None):
        return functional.fused_multi_head_attention(
            x, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            training=self.training, num_heads=self.num_heads)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 activation="relu", normalize_before=False, **kw):
        super().__init__()
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], default_initializer=I.XavierNormal())
        self.linear1_bias = self.create_parameter([dim_feedforward], is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], default_initializer=I.XavierNormal())
        self.linear2_bias = self.create_parameter([d_model], is_bias=True)
        self.ln1_scale = self.create_parameter(
            [d_model], default_initializer=I.Constant(1.0))
        self.ln1_bias = self.create_parameter([d_model], is_bias=True)
        self.ln2_scale = self.create_parameter(
            [d_model], default_initializer=I.Constant(1.0))
        self.ln2_bias = self.create_parameter([d_model], is_bias=True)
        self.dropout_rate = dropout_rate
        self.activation = activation
        self.normalize_before = normalize_before

    def forward(self, x):
        return functional.fused_feedforward(
            x, self.linear1_weight, self.linear2_weight,
            self.linear1_bias, self.linear2_bias,
            self.ln1_scale, self.ln1_bias, self.ln2_scale, self.ln2_bias,
            dropout1_rate=self.dropout_rate, dropout2_rate=self.dropout_rate,
            activation=self.activation, pre_layer_norm=self.normalize_before,
            training=self.training)


class FusedMultiTransformer(Layer):
    """Decoder stack with per-layer weights stacked for a scanned, fused
    forward + incremental KV-cache decode (ref: fused_multi_transformer_op.cu).
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward, num_layers=1,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ring_id=-1, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.num_layers = num_layers
        self.normalize_before = normalize_before
        self.activation = activation
        L = num_layers
        xavier = I.XavierNormal()

        def mk(shape, init=None):
            return self.create_parameter(shape, default_initializer=init or xavier)

        self.ln_scales = mk([L, embed_dim], I.Constant(1.0))
        self.ln_biases = mk([L, embed_dim], I.Constant(0.0))
        self.qkv_weights = mk([L, embed_dim, 3 * embed_dim])
        self.qkv_biases = mk([L, 3 * embed_dim], I.Constant(0.0))
        self.linear_weights = mk([L, embed_dim, embed_dim])
        self.linear_biases = mk([L, embed_dim], I.Constant(0.0))
        self.ffn_ln_scales = mk([L, embed_dim], I.Constant(1.0))
        self.ffn_ln_biases = mk([L, embed_dim], I.Constant(0.0))
        self.ffn1_weights = mk([L, embed_dim, dim_feedforward])
        self.ffn1_biases = mk([L, dim_feedforward], I.Constant(0.0))
        self.ffn2_weights = mk([L, dim_feedforward, embed_dim])
        self.ffn2_biases = mk([L, embed_dim], I.Constant(0.0))

    def forward(self, x, attn_mask=None, caches=None, time_step=None):
        nh, hd = self.num_heads, self.head_dim
        act_name = self.activation

        def f(xa, *ws):
            (ln_s, ln_b, qkv_w, qkv_b, lin_w, lin_b,
             fln_s, fln_b, f1_w, f1_b, f2_w, f2_b) = ws

            def layer(h, per):
                (ls, lb, qw, qb, lw, lbias, fs_, fb, w1, b1, w2, b2) = per
                def ln(t, s_, b_):
                    t32 = t.astype(jnp.float32)
                    mu = t32.mean(-1, keepdims=True)
                    var = t32.var(-1, keepdims=True)
                    return ((t32 - mu) * jax.lax.rsqrt(var + 1e-5)
                            * s_ + b_).astype(t.dtype)
                resid = h
                y = ln(h, ls, lb)
                b_, s_len = y.shape[0], y.shape[1]
                qkv = (y @ qw + qb).reshape(b_, s_len, 3, nh, hd)
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
                from ...nn.functional.attention import _xla_sdpa
                from ...ops._common import interpret_mode
                if interpret_mode():
                    attn = _xla_sdpa(q, k, v, is_causal=True)
                else:
                    from ...ops.flash_attention import flash_attention_bshd
                    attn = flash_attention_bshd(q, k, v, causal=True)
                h = resid + attn.reshape(b_, s_len, nh * hd) @ lw + lbias
                resid = h
                y = ln(h, fs_, fb)
                act = (jax.nn.gelu if act_name == "gelu" else jax.nn.relu)
                h = resid + act(y @ w1 + b1) @ w2 + b2
                return h, None

            h, _ = jax.lax.scan(layer, xa,
                                (ln_s, ln_b, qkv_w, qkv_b, lin_w, lin_b,
                                 fln_s, fln_b, f1_w, f1_b, f2_w, f2_b))
            return h

        return _run_op("fused_multi_transformer", f,
                       (x, self.ln_scales, self.ln_biases, self.qkv_weights,
                        self.qkv_biases, self.linear_weights,
                        self.linear_biases, self.ffn_ln_scales,
                        self.ffn_ln_biases, self.ffn1_weights,
                        self.ffn1_biases, self.ffn2_weights,
                        self.ffn2_biases), {})
