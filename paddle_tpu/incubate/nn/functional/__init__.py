"""Fused-op functional API (ref: python/paddle/incubate/nn/functional/).

Tensor-level wrappers over the Pallas kernels in paddle_tpu.ops — the same
surface the reference exposes for its fused CUDA kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....ops import flash_attention as _fa
from ....ops import rms_norm as _rms
from ....ops import rope as _rope
from ....tensor.tensor import Tensor, _run_op


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True):
    """q/k/v: [B, S, H, D] Tensors (ref: fused_rope_kernel.cu wrapper)."""
    tensors = [t for t in (q, k, v) if t is not None]
    arrays = [None if t is None else t for t in (q, k, v)]

    def f(*args):
        it = iter(args)
        qa = next(it) if q is not None else None
        ka = next(it) if k is not None else None
        va = next(it) if v is not None else None
        extra = {}
        sa = sin._data if isinstance(sin, Tensor) else sin
        ca = cos._data if isinstance(cos, Tensor) else cos
        pid = position_ids._data if isinstance(position_ids, Tensor) else position_ids
        outs = _rope.fused_rotary_position_embedding(
            qa, ka, va, sin=sa, cos=ca, position_ids=pid,
            use_neox_rotary_style=use_neox_rotary_style)
        return tuple(o for o in outs if o is not None)

    outs = _run_op("fused_rope", f, tuple(tensors), {})
    if not isinstance(outs, tuple):
        outs = (outs,)
    it = iter(outs)
    return tuple(next(it) if t is not None else None for t in (q, k, v))


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None):
    """(ref: phi/kernels/fusion/gpu/rms_norm_kernel.cu wrapper).
    Supports the residual-add fusion variant."""
    args = [x, norm_weight]
    has_res = residual is not None

    def f(xa, wa, *rest):
        i = 0
        res = None
        if has_res:
            res = rest[i]; i += 1
        if res is not None:
            xa = xa + res
        out = _rms.fused_rms_norm(xa, wa, epsilon)
        if norm_bias is not None:
            out = out + (norm_bias._data if isinstance(norm_bias, Tensor)
                         else norm_bias)
        return out

    if has_res:
        args.append(residual)
    return _run_op("fused_rms_norm", f, tuple(args), {})


def fused_layer_norm(x, norm_weight, norm_bias, epsilon=1e-5,
                     begin_norm_axis=1, bias=None, residual=None):
    def f(xa, wa, ba, *rest):
        if rest:
            xa = xa + rest[0]
        x32 = xa.astype(jnp.float32)
        mean = x32.mean(-1, keepdims=True)
        var = x32.var(-1, keepdims=True)
        out = (x32 - mean) * jax.lax.rsqrt(var + epsilon)
        return (out * wa.astype(jnp.float32)
                + ba.astype(jnp.float32)).astype(xa.dtype)
    args = (x, norm_weight, norm_bias) + ((residual,) if residual is not None else ())
    return _run_op("fused_layer_norm", f, args, {})


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    from ....nn.functional.common import linear
    if transpose_weight:
        from ....tensor.linalg import t as _t
        weight = _t(weight)
    return linear(x, weight, bias)


def fused_linear_activation(x, y, bias=None, trans_x=False, trans_y=False,
                            activation="gelu"):
    from ....tensor.linalg import matmul
    out = matmul(x, y, transpose_x=trans_x, transpose_y=trans_y)
    if bias is not None:
        out = out + bias
    from ....nn import functional as F
    act = {"gelu": lambda t: F.gelu(t, approximate=True),
           "relu": F.relu, "none": lambda t: t}[activation]
    return act(out)


def swiglu(x, y=None, name=None):
    """silu(x) * y; single-arg form splits last dim in half (ref: swiglu op)."""
    if y is None:
        def f(a):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(a1) * a2
        return _run_op("swiglu", f, (x,), {})
    return _run_op("swiglu", lambda a, b: jax.nn.silu(a) * b, (x, y), {})


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    from ....nn.functional.common import dropout
    return dropout(x, p=p, training=training, mode=mode) + y


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.0, ln_epsilon=1e-5,
                                           training=True):
    from ....nn.functional.common import dropout
    from ....nn.functional.norm import layer_norm
    out = x if bias is None else x + bias
    out = dropout(out, p=dropout_rate, training=training) + residual
    return layer_norm(out, out.shape[-1], ln_scale, ln_bias, ln_epsilon)


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None,
                               ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, num_heads=None, name=None):
    """Fused MHA (ref: fused_attention_op.cu). qkv_weight: [3, H, D, hidden]."""
    from ....nn.functional.attention import scaled_dot_product_attention
    from ....nn.functional.norm import layer_norm
    from ....nn.functional.common import dropout

    residual = x
    if pre_layer_norm:
        x = layer_norm(x, x.shape[-1], pre_ln_scale, pre_ln_bias, pre_ln_epsilon)

    def qkv_f(xa, wa, *b):
        out = jnp.einsum("bsh,tndh->bstnd", xa, wa)
        if b:
            out = out + b[0]
        return out
    args = (x, qkv_weight) + ((qkv_bias,) if qkv_bias is not None else ())
    qkv = _run_op("fused_qkv", qkv_f, args, {})
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    attn = scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                        dropout_p=attn_dropout_rate,
                                        training=training)
    b_, s_ = attn.shape[0], attn.shape[1]
    from ....tensor.manipulation import reshape
    attn = reshape(attn, [b_, s_, -1])
    from ....nn.functional.common import linear
    out = linear(attn, linear_weight, linear_bias)
    out = dropout(out, p=dropout_rate, training=training) + residual
    if not pre_layer_norm:
        out = layer_norm(out, out.shape[-1], ln_scale, ln_bias, ln_epsilon)
    return out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, name=None):
    """(ref: fused_feedforward_op.cu)."""
    from ....nn import functional as F
    from ....nn.functional.norm import layer_norm
    from ....nn.functional.common import dropout, linear
    residual = x
    if pre_layer_norm:
        x = layer_norm(x, x.shape[-1], ln1_scale, ln1_bias, ln1_epsilon)
    act = getattr(F, activation)
    h = dropout(act(linear(x, linear1_weight, linear1_bias)),
                p=dropout1_rate, training=training)
    h = dropout(linear(h, linear2_weight, linear2_bias),
                p=dropout2_rate, training=training) + residual
    if not pre_layer_norm:
        h = layer_norm(h, h.shape[-1], ln2_scale, ln2_bias, ln2_epsilon)
    return h
