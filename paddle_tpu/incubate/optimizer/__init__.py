"""Incubate optimizers (ref: python/paddle/incubate/optimizer/):
LookAhead and ModelAverage wrappers over any inner optimizer."""
from __future__ import annotations

import jax.numpy as jnp


class LookAhead:
    """k steps forward, one step back (Zhang et al. 2019;
    ref: incubate/optimizer/lookahead.py). Wraps an inner optimizer; every k
    inner steps the slow weights interpolate toward the fast ones and the
    fast weights reset to the slow track."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._step_num = 0
        self._slow = {id(p): jnp.array(p._data)
                      for p in inner_optimizer._parameter_list}

    def step(self):
        self.inner_optimizer.step()
        self._step_num += 1
        if self._step_num % self.k == 0:
            for p in self.inner_optimizer._parameter_list:
                slow = self._slow[id(p)]
                slow = slow + self.alpha * (
                    p._data.astype(slow.dtype) - slow)
                self._slow[id(p)] = slow
                p._data = slow.astype(p._data.dtype)

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def state_dict(self):
        return {"inner": self.inner_optimizer.state_dict(),
                "step_num": self._step_num}

    def minimize(self, loss):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage:
    """Running average of parameters applied at eval time
    (ref: incubate/optimizer/modelaverage.py). ``apply()`` swaps averaged
    weights in (a context manager), ``restore()`` puts the live ones back."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError("ModelAverage needs the parameter list")
        self._params = list(parameters)
        self.average_window_rate = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        # two-level sums like the reference: when the recent window fills,
        # it rolls into the old buffer, so the effective average covers
        # [max_average_window, 2*max_average_window) recent steps.
        zeros = lambda p: jnp.zeros_like(p._data.astype(jnp.float32))
        self._sum_new = {id(p): zeros(p) for p in self._params}
        self._sum_old = {id(p): zeros(p) for p in self._params}
        self._cnt_new = 0
        self._cnt_old = 0
        self._num_updates = 0
        self._backup = None

    def _window(self):
        return max(self.min_average_window,
                   min(int(self.average_window_rate * self._num_updates),
                       self.max_average_window))

    def step(self):
        self._num_updates += 1
        for p in self._params:
            self._sum_new[id(p)] = (self._sum_new[id(p)]
                                    + p._data.astype(jnp.float32))
        self._cnt_new += 1
        if self._cnt_new >= self._window():
            self._sum_old = dict(self._sum_new)
            self._cnt_old = self._cnt_new
            zeros = lambda p: jnp.zeros_like(p._data.astype(jnp.float32))
            self._sum_new = {id(p): zeros(p) for p in self._params}
            self._cnt_new = 0

    def _averaged(self, p):
        total = self._sum_new[id(p)] + self._sum_old[id(p)]
        count = max(self._cnt_new + self._cnt_old, 1)
        return (total / count).astype(p._data.dtype)

    def apply(self, executor=None, need_restore=True):
        class _Ctx:
            def __init__(ctx):
                ctx.need_restore = need_restore

            def __enter__(ctx):
                self._backup = {id(p): p._data for p in self._params}
                for p in self._params:
                    p._data = self._averaged(p)
                return ctx

            def __exit__(ctx, *exc):
                if ctx.need_restore:
                    self.restore()
                return False
        return _Ctx()

    def restore(self, executor=None):
        if self._backup is not None:
            for p in self._params:
                p._data = self._backup[id(p)]
            self._backup = None
