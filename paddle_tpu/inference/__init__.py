"""Inference API (ref: paddle/fluid/inference/ AnalysisPredictor,
 python/paddle/inference/).

The reference's predictor runs analysis passes (op fusion, TensorRT subgraphs)
over a saved program, then executes with zero-copy input/output handles.  The
TPU-native analog: load the StableHLO artifact saved by ``jit.save`` /
``static.save_inference_model`` — XLA performs the fusion/layout work the
analysis passes did — and run it on the target device.  The handle-based API
(get_input_handle / copy_from_cpu / run / get_output_handle) is preserved.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"      # parity alias; maps to the accelerator
    TPU = "tpu"
    XPU = "xpu"


class Config:
    """ref: paddle_infer.Config. Device/memory knobs that map to XLA are
    honored; CUDA-specific ones are accepted and ignored."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.model_prefix = prog_file
        self.params_file = params_file
        self._device = "tpu" if any(
            d.platform == "tpu" for d in jax.devices()) else "cpu"
        self._device_id = 0
        self._precision = PrecisionType.Float32
        self._profile = False
        self._glog = True

    # model path accessors (ref: Config::SetModel / model_dir / prog_file)
    def set_model(self, prog_or_dir: str, params_file: Optional[str] = None):
        if os.path.isdir(prog_or_dir):
            self.model_prefix = os.path.join(prog_or_dir, "model")
        else:
            p = prog_or_dir
            if p.endswith(".pdmodel"):
                p = p[:-len(".pdmodel")]
            self.model_prefix = p
        self.params_file = params_file

    def set_prog_file(self, path: str):
        self.set_model(path, params_file=self.params_file)

    def set_params_file(self, path: str):
        self.params_file = path

    def prog_file(self):
        return (self.model_prefix or "") + ".pdmodel"

    # device selection
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=PrecisionType.Float32):
        self._device_id = device_id
        self._precision = precision

    def disable_gpu(self):
        self._device = "cpu"

    def enable_xpu(self, *a, **k):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_memory_optim(self, flag=True):
        pass  # XLA's buffer assignment already does this

    def switch_ir_optim(self, flag=True):
        pass  # XLA fusion replaces IR passes

    def enable_tensorrt_engine(self, *a, **k):
        pass  # no TRT on TPU; XLA compiles the whole graph

    def enable_profile(self):
        """Per-run host+device timing via paddle.profiler (real wiring:
        Predictor.run brackets execution with RecordEvent)."""
        self._profile = True

    def disable_glog_info(self):
        self._glog = False

    def glog_info_disabled(self):
        return not self._glog

    def use_gpu(self):
        return False  # device is tpu/cpu here, never CUDA

    def gpu_device_id(self):
        return self._device_id

    def model_dir(self):
        return os.path.dirname(self.model_prefix or "")

    def summary(self) -> str:
        """ref: Config::Summary — a human-readable option table."""
        rows = [
            ("model_prefix", self.model_prefix),
            ("params_file", self.params_file),
            ("device", f"{self._device}:{self._device_id}"),
            ("precision", self._precision),
            ("profile", self._profile),
            ("backend", "XLA (fusion/memory passes in the compiler)"),
        ]
        w = max(len(k) for k, _ in rows)
        return "\n".join(f"{k:<{w}}  {v}" for k, v in rows)


class Tensor_:
    """I/O handle (ref: paddle_infer.Tensor): name + staged host array."""

    def __init__(self, name: str, shape=None, dtype=None):
        self.name = name
        self._shape = shape
        self._dtype = dtype
        self._value = None

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = np.ascontiguousarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._value)

    def shape(self):
        v = self._value
        return list(v.shape) if v is not None else list(self._shape or [])

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)
        else:
            self._shape = list(shape)


class Predictor:
    """ref: AnalysisPredictor via the handle API."""

    def __init__(self, config: Config, _shared_model=None):
        from ..static import load_inference_model
        self._config = config
        if _shared_model is not None:
            self._model = _shared_model
        else:
            if config.model_prefix is None:
                raise ValueError("Config needs a model path prefix")
            self._model = load_inference_model(config.model_prefix)
        self._inputs: Dict[str, Tensor_] = {
            n: Tensor_(n) for n in self._model.feed_names}
        self._outputs: List[np.ndarray] = []
        self._out_names = [f"fetch_{i}"
                           for i in range(self._model.meta["num_fetch"])]

    def clone(self):
        """A predictor over the SAME loaded/compiled model with its own I/O
        handles (ref: AnalysisPredictor::Clone — per-thread predictors share
        weights; here they also share XLA executables)."""
        return Predictor(self._config, _shared_model=self._model)

    def get_input_names(self) -> List[str]:
        return list(self._inputs)

    def get_input_handle(self, name: str) -> Tensor_:
        return self._inputs[name]

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        if inputs is not None:
            for h, a in zip(self._inputs.values(), inputs):
                h.copy_from_cpu(np.asarray(a))
        feeds = {n: h._value for n, h in self._inputs.items()}
        missing = [n for n, v in feeds.items() if v is None]
        if missing:
            raise RuntimeError(f"inputs not set: {missing}")
        import contextlib
        if self._config._profile:
            from ..profiler import RecordEvent
            span = RecordEvent("inference::Predictor::run")
        else:
            span = contextlib.nullcontext()
        with span:
            self._outputs = self._model.run(feeds)
        if inputs is not None:
            return [np.asarray(o) for o in self._outputs]
        return None

    def get_output_names(self) -> List[str]:
        return list(self._out_names)

    def get_output_handle(self, name: str) -> Tensor_:
        idx = self._out_names.index(name)
        h = Tensor_(name)
        h._value = self._outputs[idx]
        return h


    def clear_intermediate_tensor(self):
        pass  # XLA frees intermediates after each executable run

    def try_shrink_memory(self):
        pass  # device arena is PJRT's


class PredictorPool:
    """N predictors sharing one loaded model (ref: services run one
    predictor per worker thread; paddle_infer.PredictorPool)."""

    def __init__(self, config: Config, size: int):
        if size < 1:
            raise ValueError(f"PredictorPool size must be >= 1, got {size}")
        first = Predictor(config)
        self._preds = [first] + [first.clone() for _ in range(size - 1)]

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def get_version() -> str:
    from .. import __version__
    return __version__


def get_num_bytes_of_data_type(dtype) -> int:
    return np.dtype(getattr(dtype, "value", dtype)).itemsize


__all__ = ["Config", "Predictor", "PredictorPool", "create_predictor",
           "get_version", "get_num_bytes_of_data_type", "PrecisionType",
           "PlaceType"]


# --- continuous-batching serving engine (paged KV cache) -------------------
from .kv_cache import BlockPool, BlockPoolError, PrefixCache, pad_table  # noqa: E402
from .engine import (Admission, AdmissionController, InferenceEngine,  # noqa: E402
                     PoisonError, Request, ServeConfig)
from .journal import (EngineJournal, JournalCompatError,  # noqa: E402
                      read_journal)
from .fleet import FleetRouter  # noqa: E402

__all__ += ["BlockPool", "BlockPoolError", "PrefixCache", "pad_table",
            "InferenceEngine", "Request", "ServeConfig", "Admission",
            "AdmissionController", "PoisonError", "EngineJournal",
            "JournalCompatError", "read_journal", "FleetRouter"]
