"""Inference API (ref: paddle/fluid/inference/ AnalysisPredictor,
 python/paddle/inference/).

The reference's predictor runs analysis passes (op fusion, TensorRT subgraphs)
over a saved program, then executes with zero-copy input/output handles.  The
TPU-native analog: load the StableHLO artifact saved by ``jit.save`` /
``static.save_inference_model`` — XLA performs the fusion/layout work the
analysis passes did — and run it on the target device.  The handle-based API
(get_input_handle / copy_from_cpu / run / get_output_handle) is preserved.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"      # parity alias; maps to the accelerator
    TPU = "tpu"
    XPU = "xpu"


class Config:
    """ref: paddle_infer.Config. Device/memory knobs that map to XLA are
    honored; CUDA-specific ones are accepted and ignored."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.model_prefix = prog_file
        self.params_file = params_file
        self._device = "tpu" if any(
            d.platform == "tpu" for d in jax.devices()) else "cpu"
        self._device_id = 0
        self._precision = PrecisionType.Float32

    # device selection
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=PrecisionType.Float32):
        self._device_id = device_id
        self._precision = precision

    def disable_gpu(self):
        self._device = "cpu"

    def enable_xpu(self, *a, **k):
        pass

    def set_cpu_math_library_num_threads(self, n):
        pass

    def enable_memory_optim(self, flag=True):
        pass  # XLA's buffer assignment already does this

    def switch_ir_optim(self, flag=True):
        pass  # XLA fusion replaces IR passes

    def enable_tensorrt_engine(self, *a, **k):
        pass  # no TRT on TPU; XLA compiles the whole graph

    def model_dir(self):
        return os.path.dirname(self.model_prefix or "")


class Tensor_:
    """I/O handle (ref: paddle_infer.Tensor): name + staged host array."""

    def __init__(self, name: str, shape=None, dtype=None):
        self.name = name
        self._shape = shape
        self._dtype = dtype
        self._value = None

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = np.ascontiguousarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._value)

    def shape(self):
        v = self._value
        return list(v.shape) if v is not None else list(self._shape or [])

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)
        else:
            self._shape = list(shape)


class Predictor:
    """ref: AnalysisPredictor via the handle API."""

    def __init__(self, config: Config):
        from ..static import load_inference_model
        if config.model_prefix is None:
            raise ValueError("Config needs a model path prefix")
        self._model = load_inference_model(config.model_prefix)
        self._inputs: Dict[str, Tensor_] = {
            n: Tensor_(n) for n in self._model.feed_names}
        self._outputs: List[np.ndarray] = []
        self._out_names = [f"fetch_{i}"
                           for i in range(self._model.meta["num_fetch"])]

    def get_input_names(self) -> List[str]:
        return list(self._inputs)

    def get_input_handle(self, name: str) -> Tensor_:
        return self._inputs[name]

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        if inputs is not None:
            for h, a in zip(self._inputs.values(), inputs):
                h.copy_from_cpu(np.asarray(a))
        feeds = {n: h._value for n, h in self._inputs.items()}
        missing = [n for n, v in feeds.items() if v is None]
        if missing:
            raise RuntimeError(f"inputs not set: {missing}")
        self._outputs = self._model.run(feeds)
        if inputs is not None:
            return [np.asarray(o) for o in self._outputs]
        return None

    def get_output_names(self) -> List[str]:
        return list(self._out_names)

    def get_output_handle(self, name: str) -> Tensor_:
        idx = self._out_names.index(name)
        h = Tensor_(name)
        h._value = self._outputs[idx]
        return h


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType",
           "PlaceType"]
