"""Continuous-batching serving engine (iteration-level scheduling).

Orca-style iteration-level scheduling (Yu et al., OSDI '22) over the
vLLM paged KV cache (Kwon et al., SOSP '23), restated for TPU static
shapes: every device program in the serving hot path comes from ONE
compiled step family per bucketed shape —

  - ``paged_decode_step`` at batch buckets (1, 2, 4, ..., max_batch):
    one token for every RUNNING sequence through the fused
    paged-attention update kernel (ops/paged_attention.py);
  - ``paged_prefill_chunk`` at the fixed chunk bucket: one slice of
    ONE admitted prompt, interleaved with the decode batches so long
    prompts never head-of-line-block token generation.

Recompiles are therefore bounded by ``len(decode_buckets) + 1`` and
counted (``serve.compile.*`` counters + StepMetrics.record_compile).

Scheduling per ``step()`` iteration:
  1. admit waiting requests while the free-block budget covers their
     prompt (plus one decode block of headroom);
  2. run one prefill chunk for the oldest admitted prompt, allocating
     its blocks lazily per chunk;
  3. run one decode batch over all RUNNING sequences, allocating each
     sequence's next block as it crosses a block boundary and
     PREEMPTING-BY-EVICTION (youngest RUNNING sequence back to the
     waiting queue, blocks freed, recompute-on-readmission) when the
     pool runs dry.

Telemetry: queue depth, batch occupancy, block-pool utilization and
prefill-vs-decode time share per iteration through StepMetrics, with
comm_span/counter markers on every scheduling event.

Overload + fault contract (PR 14):

  - ``submit()`` returns an :class:`Admission` decision instead of
    queueing unboundedly: a bounded waiting queue, a token-bucket rate
    limit and a free-block-aware overcommit estimate each produce a
    deterministic ``rejected`` outcome with a cause.
  - Requests carry optional TTFT/total deadlines and a priority; the
    scheduler sheds queued requests whose deadline has already passed
    (engine-clock arithmetic only, so shedding replays bit-identically)
    and evicts lowest-priority-first under pool pressure, shrinking a
    prefill chunk's live span (same compiled shape) before evicting.
  - A request whose prefill raises, or whose prefill/decode logits go
    non-finite, is QUARANTINED: blocks released, marked failed with a
    cause, the decode batch re-driven without it — one poisoned request
    never takes down the engine.
  - With a journal path, every accepted request and emitted token is
    appended to a crash-recoverable JSONL journal (inference/journal.py);
    a fresh engine's :meth:`InferenceEngine.recover` re-drives to
    bit-identical token streams. ``faults.py`` points ``serve.admit.*``/
    ``serve.prefill.*``/``serve.decode.*``/``serve.swap.*`` let the
    crash-matrix test kill the engine at every stage.

Every request the engine ever saw ends in exactly one terminal state —
finished, rejected, shed, or failed — with a cause (:meth:`outcomes`);
nothing is silently dropped.
"""
from __future__ import annotations

import dataclasses
import itertools
import os
import signal
import threading
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import envs
from ..testing import faults
from ..models.llama import (LlamaConfig, ParallelConfig, _freeze_config,
                            _jitted_paged_decode,
                            _jitted_paged_decode_quant,
                            _jitted_paged_decode_quant_tp,
                            _jitted_paged_decode_tp,
                            _jitted_paged_prefill,
                            _jitted_paged_prefill_quant,
                            _jitted_paged_prefill_quant_tp,
                            _jitted_paged_prefill_tp,
                            _jitted_paged_verify,
                            _jitted_paged_verify_quant,
                            _jitted_paged_verify_quant_tp,
                            _jitted_paged_verify_tp, init_paged_kv_pool,
                            init_paged_kv_scales, make_draft_model,
                            make_mesh, param_pspecs)
from ..observability.flight_recorder import (FlightRecorder,
                                             flight_recorder_enabled)
from ..observability.histogram import LogHistogram
from ..observability.registry import MetricsRegistry
from ..observability.metrics import StepMetrics
from ..observability.request_trace import RequestTracer
from ..observability.trace import comm_span, record_counter
from .journal import EngineJournal, JournalCompatError, read_journal
from .kv_cache import (BlockPool, PrefixCache, pad_table,
                       pool_bytes_per_rank)

ENV_TRACE_REQUESTS = "PADDLE_TPU_TRACE_REQUESTS"
ENV_SERVE_MAX_QUEUE = "PADDLE_TPU_SERVE_MAX_QUEUE"
ENV_SERVE_RATE = "PADDLE_TPU_SERVE_RATE"
ENV_SERVE_BURST = "PADDLE_TPU_SERVE_BURST"
ENV_SERVE_OVERCOMMIT = "PADDLE_TPU_SERVE_OVERCOMMIT"
ENV_SERVE_NAN_CHECK = "PADDLE_TPU_SERVE_NAN_CHECK"
ENV_SERVE_JOURNAL = "PADDLE_TPU_SERVE_JOURNAL"
ENV_SERVE_JOURNAL_FSYNC = "PADDLE_TPU_SERVE_JOURNAL_FSYNC"
ENV_SERVE_PREFIX_CACHE = "PADDLE_TPU_SERVE_PREFIX_CACHE"
ENV_SERVE_KV_DTYPE = "PADDLE_TPU_SERVE_KV_DTYPE"
ENV_SERVE_SPEC = "PADDLE_TPU_SERVE_SPEC"
ENV_SERVE_SPEC_K = "PADDLE_TPU_SERVE_SPEC_K"
ENV_SERVE_MP = "PADDLE_TPU_SERVE_MP"

WAITING, PREFILL, RUNNING, FINISHED = "waiting", "prefill", "running", \
    "finished"
SHED, FAILED = "shed", "failed"


class PoisonError(RuntimeError):
    """A poisoned per-request computation, attributable to ``rid``.
    Raised by the engine's own non-finite logit screens, and usable from
    a fault-injection corrupt callable to simulate a request whose
    device computation raises (``PoisonError(ctx['rids'][0])``)."""

    def __init__(self, rid: int, cause: str = "poisoned request"):
        super().__init__(f"request {rid}: {cause}")
        self.rid = rid
        self.cause = cause


@dataclasses.dataclass
class Request:
    """One generation request. ``arrival`` is seconds from engine start
    (wall mode) or the iteration index (deterministic replay mode).
    ``ttft_deadline``/``deadline`` are engine-clock spans from arrival
    (first token / full completion); a queued request past its deadline
    is shed. Higher ``priority`` survives eviction longer."""
    prompt: Sequence[int]
    max_new_tokens: int = 16
    request_id: Optional[int] = None
    eos_id: Optional[int] = None
    arrival: float = 0.0
    priority: int = 0
    ttft_deadline: Optional[float] = None
    deadline: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class Admission:
    """The ``submit()`` outcome: accepted into the bounded queue, or
    rejected with a deterministic cause (``queue_full`` | ``overcommit``
    | ``rate_limit``)."""
    accepted: bool
    request_id: int
    cause: Optional[str] = None


class _TokenBucket:
    """``rate`` admissions per engine-clock unit, capacity ``burst``.
    Refill arithmetic uses the ENGINE clock (iteration index in
    deterministic replay), never wall time, so admission decisions
    replay bit-identically."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self._level = float(burst)
        self._at = 0.0

    def take(self, now: float) -> bool:
        if now > self._at:
            self._level = min(self.burst,
                              self._level + (now - self._at) * self.rate)
            self._at = now
        if self._level < 1.0:
            return False
        self._level -= 1.0
        return True


class AdmissionController:
    """Explicit admit/reject decision at ``submit()``.

    Three independent valves, checked in order (first hit wins):
    ``queue_full`` (bounded waiting queue), ``overcommit`` (the worst-
    case block demand of everything queued+active plus this request
    exceeds ``overcommit`` x the usable pool — a free-block-aware
    estimate, since admitted work is never silently dropped) and
    ``rate_limit`` (token bucket; checked last so rejected-anyway
    requests do not drain the bucket)."""

    def __init__(self, max_queue: int, rate: Optional[float],
                 burst: float, overcommit: float):
        self.max_queue = int(max_queue)
        self.overcommit = float(overcommit)
        self.bucket = _TokenBucket(rate, burst) if rate else None

    def decide(self, queue_len: int, demand_blocks: int,
               worst_blocks: int, usable_blocks: int,
               now: float) -> Optional[str]:
        """None to accept, else the rejection cause."""
        if queue_len >= self.max_queue:
            return "queue_full"
        if demand_blocks + worst_blocks > self.overcommit * usable_blocks:
            return "overcommit"
        if self.bucket is not None and not self.bucket.take(now):
            return "rate_limit"
        return None


@dataclasses.dataclass
class ServeConfig:
    block_size: int = 128
    num_blocks: int = 64          # includes the reserved null block 0
    max_batch: int = 8
    prefill_chunk: int = 64
    max_seq_len: int = 1024       # bounds the block-table width
    decode_buckets: Optional[Tuple[int, ...]] = None
    # overload valves (PR 14); None defers to the PADDLE_TPU_SERVE_*
    # knob, which in turn falls back to the documented default
    max_queue: Optional[int] = None       # default 4 x max_batch
    rate_limit: Optional[float] = None    # admissions/clock-unit; 0=off
    burst: Optional[int] = None           # default max(2, max_batch)
    overcommit: Optional[float] = None    # default 4.0 x usable blocks
    nan_check: Optional[bool] = None      # default True
    # PR 16 capacity features; None defers to the knob (both default
    # to the legacy behavior: no sharing, model-dtype fp KV)
    prefix_cache: Optional[bool] = None   # COW shared prefix blocks
    kv_dtype: Optional[str] = None        # "auto" (model dtype) | "int8"
    speculative: Optional[bool] = None    # draft + batched verification
    draft_k: Optional[int] = None         # proposals/seq/iteration (>=1)
    # tensor-parallel serving (PR 19): model-parallel degree of the
    # engine's mesh; weights slice per param_pspecs, KV pools shard by
    # kv-head. None defers to PADDLE_TPU_SERVE_MP (default 1 = the
    # single-device path, bit-identical to pre-PR-19).
    mp: Optional[int] = None

    def __post_init__(self):
        if self.decode_buckets is None:
            b, buckets = 1, []
            while b < self.max_batch:
                buckets.append(b)
                b *= 2
            self.decode_buckets = tuple(buckets) + (self.max_batch,)
        self.decode_buckets = tuple(sorted(set(self.decode_buckets)))
        if self.decode_buckets[-1] != self.max_batch:
            raise ValueError("largest decode bucket must equal max_batch")

    @property
    def max_nb(self) -> int:
        return -(-self.max_seq_len // self.block_size)


class _Seq:
    """Scheduler-side sequence state. Invariant while RUNNING:
    n_cached == len(tokens) - 1, and the next decode feeds tokens[-1]
    at position n_cached."""

    def __init__(self, req: Request, now: float):
        self.req = req
        self.tokens: List[int] = [int(t) for t in req.prompt]
        self.n_prompt = len(self.tokens)
        self.n_cached = 0
        self.blocks: List[int] = []
        self.state = WAITING
        self.arrival = now
        self.order = 0                 # submission sequence number
        self.first_token_t: Optional[float] = None
        self.token_times: List[float] = []
        self.n_preempted = 0
        self.fail_cause: Optional[str] = None   # shed/quarantine cause
        self.recovered = False                  # rebuilt from a journal
        # tokens whose KV the DRAFT pools hold; always <= n_cached after
        # a verify (rejected lookahead KV is simply re-proposed over)
        self.draft_pos = 0

    @property
    def generated(self) -> List[int]:
        return self.tokens[self.n_prompt:]

    @property
    def prefill_target(self) -> int:
        # fresh prompts cache every prompt token and sample from the
        # final chunk's logits; a preempted sequence re-caches all but
        # its newest (never-fed) token and resumes decoding instead
        return len(self.tokens) - (1 if self.generated else 0)

    def done(self) -> bool:
        g = self.generated
        return (len(g) >= self.req.max_new_tokens
                or (self.req.eos_id is not None and g
                    and g[-1] == self.req.eos_id))


class InferenceEngine:
    """Continuous-batching engine over a paged KV cache.

    >>> eng = InferenceEngine(params, config, ServeConfig())
    >>> stats = eng.run([Request(prompt, max_new_tokens=32), ...])

    Greedy decoding; one engine owns its device pools, so drive it from
    a single thread."""

    def __init__(self, params: Dict[str, Any], config: LlamaConfig,
                 serve: Optional[ServeConfig] = None,
                 telemetry: Optional[StepMetrics] = None,
                 record_events: bool = False,
                 trace_requests: Optional[bool] = None,
                 flight_recorder: Optional[bool] = None,
                 journal: Optional[str] = None,
                 draft_params: Optional[Dict[str, Any]] = None,
                 draft_config: Optional[LlamaConfig] = None):
        self.params = params
        self.config = config
        self.serve = serve or ServeConfig()
        # tensor-parallel serving (PR 19): mp > 1 runs every jitted step
        # family inside an ('mp',)-sharded mesh — weights sliced per
        # param_pspecs, KV/scale pools sharded by kv-head — while the
        # host-side scheduler (admission, shedding, quarantine, journal,
        # BlockPool, PrefixCache) stays rank-agnostic: one process
        # drives all ranks with rank-replicated block tables. Streams
        # stay token-identical to mp=1 (PARITY.md PR 19).
        self.mp = int(self.serve.mp if self.serve.mp is not None
                      else envs.get(ENV_SERVE_MP))
        if self.mp < 1:
            raise ValueError(f"ServeConfig.mp must be >= 1, got {self.mp}")
        self.mesh = None
        self.pool = BlockPool(self.serve.num_blocks, self.serve.block_size)
        # KV storage dtype: "auto" keeps the model dtype (the pre-PR-16
        # path, bit-identical); "int8" halves pool bytes with per-column
        # scale pools dequantized inside the paged kernels
        self.kv_dtype = (self.serve.kv_dtype
                         if self.serve.kv_dtype is not None
                         else envs.get(ENV_SERVE_KV_DTYPE))
        if self.kv_dtype not in ("auto", "int8"):
            raise ValueError(
                f"ServeConfig.kv_dtype must be 'auto' or 'int8', "
                f"got {self.kv_dtype!r}")
        self.k_pool, self.v_pool = init_paged_kv_pool(
            config, self.serve.num_blocks, self.serve.block_size,
            kv_dtype=self.kv_dtype)
        self.k_scale = self.v_scale = None
        if self.kv_dtype == "int8":
            self.k_scale, self.v_scale = init_paged_kv_scales(
                config, self.serve.num_blocks, self.serve.block_size)
        # COW prefix cache: full prompt blocks stay indexed after
        # release and later identical prompts share them ref-counted
        prefix_on = (self.serve.prefix_cache
                     if self.serve.prefix_cache is not None
                     else envs.get(ENV_SERVE_PREFIX_CACHE))
        self.cache: Optional[PrefixCache] = \
            PrefixCache(self.pool) if prefix_on else None
        self._cow_copies = 0
        # speculative decoding (PR 18): a draft model proposes up to K
        # tokens per sequence per iteration and ONE batched verify pass
        # scores all K+1 positions. Emitted tokens are always the BASE
        # model's greedy argmax, so streams are bit-identical to
        # sequential decode regardless of draft quality (PARITY.md) —
        # the draft only moves latency.
        spec = (self.serve.speculative
                if self.serve.speculative is not None
                else envs.get(ENV_SERVE_SPEC))
        self.speculative = bool(spec)
        self.draft_k = int(self.serve.draft_k
                           if self.serve.draft_k is not None
                           else envs.get(ENV_SERVE_SPEC_K))
        if self.draft_k < 1:
            raise ValueError(f"draft_k must be >= 1, got {self.draft_k}")
        self.draft_params: Optional[Dict[str, Any]] = None
        self.draft_config: Optional[LlamaConfig] = None
        self._draft_frozen: Optional[Tuple] = None
        self.k_draft = self.v_draft = None
        self._spec_proposed = 0
        self._spec_accepted = 0
        if self.speculative:
            if draft_params is None:
                # default draft: the base model truncated to its first
                # layer, sharing embedding/head weights by reference
                draft_params, draft_config = make_draft_model(params,
                                                              config)
            elif draft_config is None:
                raise ValueError("draft_params given without draft_config")
            self.draft_params = draft_params
            self.draft_config = draft_config
            self._draft_frozen = _freeze_config(draft_config)
            # the draft pools mirror the base pool's block geometry (one
            # shared block table per sequence) but always store the
            # model dtype: draft KV only shapes proposals, never output
            # bytes, so int8 buys nothing there
            self.k_draft, self.v_draft = init_paged_kv_pool(
                draft_config, self.serve.num_blocks, self.serve.block_size)
        if self.mp > 1:
            self._shard_tp()
        self.metrics = telemetry
        self.record_events = record_events
        # request-lifecycle tracing is measurement-only: spans are recorded
        # from timestamps the scheduler already crosses, never consulted by
        # it, so tokens are bit-identical with tracing on or off
        if trace_requests is None:
            trace_requests = envs.get(ENV_TRACE_REQUESTS)
        self.tracer: Optional[RequestTracer] = \
            RequestTracer() if trace_requests else None
        self.recorder: Optional[FlightRecorder] = (
            FlightRecorder(source="engine")
            if flight_recorder_enabled(flight_recorder) else None)
        # streaming SLO histograms, always on (one list increment per
        # token); values are in the ENGINE clock — seconds in wall mode,
        # iterations in deterministic mode — matching stats()
        self.slo: Dict[str, LogHistogram] = {
            "ttft": LogHistogram(), "tpot": LogHistogram(),
            "queue_wait": LogHistogram()}
        self.events: List[Tuple] = []
        self.waiting: List[_Seq] = []
        self.active: List[_Seq] = []      # PREFILL + RUNNING, FCFS order
        self.finished: List[_Seq] = []
        self.rejected: List[Tuple[Request, str]] = []
        self.shed: List[_Seq] = []
        self.failed: List[_Seq] = []
        self.iteration = 0
        self.preemptions = 0
        self._last_tokens = 0
        self._redrives = 0
        self._recovered = 0
        self._jtoks: List[Tuple[int, int]] = []  # this iteration's tokens
        # unified exposition (PR 15): the SLO histograms register by
        # reference, scheduler gauges as render-time callbacks; the
        # registration order IS the metrics_snapshot() key order
        self.registry = MetricsRegistry(prefix="paddle_tpu_serve")
        self._register_metrics()
        # admission valves: explicit ServeConfig fields win, then the
        # PADDLE_TPU_SERVE_* knobs, then the documented defaults
        sv = self.serve
        max_queue = (sv.max_queue if sv.max_queue is not None
                     else envs.get(ENV_SERVE_MAX_QUEUE) or 4 * sv.max_batch)
        rate = (sv.rate_limit if sv.rate_limit is not None
                else envs.get(ENV_SERVE_RATE))
        burst = (sv.burst if sv.burst is not None
                 else envs.get(ENV_SERVE_BURST) or max(2, sv.max_batch))
        overcommit = (sv.overcommit if sv.overcommit is not None
                      else envs.get(ENV_SERVE_OVERCOMMIT))
        self.admission = AdmissionController(max_queue, rate, burst,
                                             overcommit)
        self._nan_check = (sv.nan_check if sv.nan_check is not None
                           else envs.get(ENV_SERVE_NAN_CHECK))
        # crash-recoverable request/token journal (inference/journal.py)
        self.journal_path = (journal if journal is not None
                             else envs.get(ENV_SERVE_JOURNAL)) or None
        self._journal: Optional[EngineJournal] = None
        if self.journal_path:
            self._journal = EngineJournal(
                self.journal_path,
                fsync=envs.get(ENV_SERVE_JOURNAL_FSYNC),
                meta=self._journal_meta())
        self._rid = itertools.count()
        self._seqno = itertools.count()
        self._frozen = _freeze_config(config)
        self._compiled: Dict[Tuple, float] = {}
        self._clock = 0.0
        # preemption + live weight push (PR 13)
        self._preempt = threading.Event()
        self._was_preempted = False
        self._signum: Optional[int] = None
        self._prev_handler: Any = None
        self._pending_swap: Optional[Tuple[Any, int]] = None
        self.swaps = 0
        self.last_swap: Optional[Dict[str, Any]] = None
        # drain mode (PR 20): submit() rejects with cause 'draining'
        # while existing work runs to completion (drain() / the fleet
        # router's rolling swap both flip this)
        self._draining = False

    # jitted step families, keyed (kind, quant): the mp-sharded twins
    # are drop-in — same argument lists, same output tuples — so every
    # scheduler call site dispatches through _step_fn and nothing else
    # about the engine changes with mp.
    _STEP_BUILDERS = {
        ("prefill", False): (_jitted_paged_prefill,
                             _jitted_paged_prefill_tp),
        ("prefill", True): (_jitted_paged_prefill_quant,
                            _jitted_paged_prefill_quant_tp),
        ("decode", False): (_jitted_paged_decode, _jitted_paged_decode_tp),
        ("decode", True): (_jitted_paged_decode_quant,
                           _jitted_paged_decode_quant_tp),
        ("verify", False): (_jitted_paged_verify, _jitted_paged_verify_tp),
        ("verify", True): (_jitted_paged_verify_quant,
                           _jitted_paged_verify_quant_tp),
    }

    def _step_fn(self, kind: str, frozen, quant: bool = False):
        plain, tp = self._STEP_BUILDERS[(kind, bool(quant))]
        return tp(frozen, self.mesh) if self.mp > 1 else plain(frozen)

    def _shard_tp(self) -> None:
        """Build the serving mesh and place weights + pools for mp > 1.

        Weight slicing follows ``param_pspecs`` over 'mp' alone
        (column-parallel q/k/v/gate/up, row-parallel o/down, vocab-
        parallel embed + lm_head); every KV/scale pool — fp16, int8 and
        draft — shards its kv-head-major axis 2. Rejects geometries the
        contiguous-head slicing cannot express (see PARITY.md PR 19)."""
        c, mp = self.config, self.mp
        for dim, name in ((c.num_attention_heads, "num_attention_heads"),
                          (c.num_key_value_heads, "num_key_value_heads"),
                          (c.vocab_size, "vocab_size"),
                          (c.intermediate_size, "intermediate_size")):
            if dim % mp:
                raise ValueError(
                    f"ServeConfig.mp={mp} needs {name} % mp == 0 "
                    f"(got {dim}): heads/vocab/ffn slice contiguously "
                    f"across ranks")
        ndev = len(jax.devices())
        if ndev < mp:
            raise ValueError(f"ServeConfig.mp={mp} needs {mp} devices, "
                             f"have {ndev}")
        if "qkv_proj" in self.params.get("layers", {}):
            raise ValueError(
                "tensor-parallel serving needs split q/k/v projections; "
                "fused qkv_proj weights interleave heads and cannot "
                "slice contiguously over 'mp'")
        for tree in (self.params, self.draft_params or {}):
            for leaf in jax.tree_util.tree_leaves(
                    tree, is_leaf=lambda x: isinstance(x, dict) and
                    ("w" in x or "wT" in x)):
                if isinstance(leaf, dict):
                    raise ValueError(
                        "tensor-parallel serving takes plain weight "
                        "arrays; int8/transposed weight dicts don't "
                        "carry the param_pspecs tree")
        self.mesh = make_mesh(ParallelConfig(mp=mp))

        def put(tree, cfg):
            specs = param_pspecs(cfg, ParallelConfig(mp=mp))
            shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s), specs,
                is_leaf=lambda x: isinstance(x, P))
            return jax.device_put(tree, shardings)

        self.params = put(self.params, c)
        pool_sh = NamedSharding(self.mesh, P(None, None, "mp", None))
        self.k_pool = jax.device_put(self.k_pool, pool_sh)
        self.v_pool = jax.device_put(self.v_pool, pool_sh)
        if self.k_scale is not None:
            self.k_scale = jax.device_put(self.k_scale, pool_sh)
            self.v_scale = jax.device_put(self.v_scale, pool_sh)
        if self.speculative:
            self.draft_params = put(self.draft_params, self.draft_config)
            self.k_draft = jax.device_put(self.k_draft, pool_sh)
            self.v_draft = jax.device_put(self.v_draft, pool_sh)

    def _register_metrics(self) -> None:
        """Register every engine metric into the unified registry: the
        live SLO histograms by reference (zero double bookkeeping) and
        the scheduler gauges as callbacks read at render time — all
        host-side ``len()``s and counters, so scraping never touches the
        device."""
        r = self.registry
        r.summary("ttft_seconds", hist=self.slo["ttft"],
                  help="time to first token (engine clock)")
        r.summary("tpot_seconds", hist=self.slo["tpot"],
                  help="time per output token (engine clock)")
        r.summary("queue_wait_seconds", hist=self.slo["queue_wait"],
                  help="submit-to-first-schedule wait (engine clock)")
        r.gauge("queue_depth", fn=lambda: len(self.waiting),
                help="requests admitted but not yet scheduled")
        r.gauge("running", fn=lambda: sum(1 for s in self.active
                                          if s.state == RUNNING),
                help="sequences in decode")
        r.gauge("prefilling", fn=lambda: sum(1 for s in self.active
                                             if s.state == PREFILL),
                help="sequences in chunked prefill")
        r.gauge("batch_capacity", fn=lambda: self.serve.max_batch,
                help="configured max decode batch")
        r.gauge("pool_utilization", fn=lambda: self.pool.utilization,
                help="fraction of KV blocks in use")
        r.gauge("iterations", fn=lambda: self.iteration,
                help="scheduler iterations run")
        r.gauge("preemptions", fn=lambda: self.preemptions,
                help="sequences evicted for memory pressure")
        r.gauge("finished_requests", fn=lambda: len(self.finished),
                help="requests completed")
        r.gauge("rejected_requests", fn=lambda: len(self.rejected),
                help="requests refused at admission")
        r.gauge("shed_requests", fn=lambda: len(self.shed),
                help="requests shed past their deadline")
        r.gauge("failed_requests", fn=lambda: len(self.failed),
                help="requests quarantined or failed")
        r.gauge("decode_redrives", fn=lambda: self._redrives,
                help="decode steps re-driven during journal recovery")
        r.gauge("generated_tokens",
                fn=lambda: sum(len(s.generated) for s in self.finished),
                help="tokens generated by finished requests")
        # PR 16 capacity gauges, only when the cache is live: the
        # default exposition stays byte-compatible with the pre-PR-15
        # legacy dict (pinned by the metrics-registry golden test)
        if self.cache is not None:
            r.gauge("prefix_cache_hits", fn=lambda: self.cache.hits,
                    help="admissions served a shared prefix from the "
                         "cache")
            r.gauge("prefix_cache_hit_tokens",
                    fn=lambda: self.cache.hit_tokens,
                    help="prompt tokens whose prefill was skipped via "
                         "cache")
            r.gauge("prefix_cached_blocks",
                    fn=lambda: self.pool.cached_blocks,
                    help="parked prefix-cache blocks (zero refs, "
                         "reclaimable)")
            r.gauge("cow_copies", fn=lambda: self._cow_copies,
                    help="shared blocks copied on write")
        # PR 18 speculative-decode gauges, only when speculation is live
        if self.speculative:
            r.gauge("spec_proposed_tokens", fn=lambda: self._spec_proposed,
                    help="draft tokens proposed for verification")
            r.gauge("spec_accepted_tokens", fn=lambda: self._spec_accepted,
                    help="draft tokens the base model accepted")
            r.gauge("spec_accept_rate",
                    fn=lambda: (self._spec_accepted / self._spec_proposed
                                if self._spec_proposed else 0.0),
                    help="accepted / proposed draft tokens")

    # -- bookkeeping --------------------------------------------------------

    def _journal_meta(self) -> Dict[str, Any]:
        """Audit-only open-record fields: which capacity features were
        live. Cache STATE is derived (bytes are a pure function of the
        token prefix), so recovery never needs it journaled."""
        return {"kv_dtype": self.kv_dtype,
                "prefix_cache": self.cache is not None,
                "speculative": self.speculative,
                "mp": self.mp}

    def _event(self, *ev):
        if self.record_events:
            self.events.append((self.iteration,) + tuple(ev))

    def _alloc_for(self, seq: _Seq, n_tokens: int) -> bool:
        """Grow ``seq`` to cover ``n_tokens`` cached tokens; False (and
        no change) when the pool is dry."""
        need = self.pool.blocks_for(n_tokens) - len(seq.blocks)
        if need <= 0:
            return True
        got = self.pool.alloc(need)
        if got is None:
            return False
        seq.blocks.extend(got)
        record_counter("serve.blocks_alloc", need)
        return True

    def _release(self, seq: _Seq):
        if seq.blocks:
            record_counter("serve.blocks_free", len(seq.blocks))
            self.pool.free(seq.blocks)
            seq.blocks = []

    def _cow_span(self, seq: _Seq, start: int, n_tokens: int) -> bool:
        """Copy-on-write guard: make every block covering positions
        [start, start+n) privately writable before the device writes.
        With sharing on, scheduler writes land past the hit span by
        construction (hits are block-aligned and capped below
        prefill_target; registration covers only full immutable
        blocks), so this is a defensive invariant — but it is THE
        contract that keeps shared bytes immutable: a block with other
        readers is copied (device blit + table swap), a registered
        ref-1 block has its index entry invalidated instead. False if
        the pool cannot supply a copy block (caller evicts/stalls)."""
        if self.cache is None or n_tokens < 1:
            return True
        bs = self.pool.block_size
        for bi in range(start // bs, (start + n_tokens - 1) // bs + 1):
            if bi >= len(seq.blocks):
                continue
            b = seq.blocks[bi]
            if self.pool.ref_count(b) > 1:
                got = self.pool.alloc(1)
                if got is None:
                    return False
                nb = got[0]
                # device-side blit of the shared block's slabs (host
                # decision, one copy — never a cache reshape/compact)
                self.k_pool = self.k_pool.at[:, nb].set(self.k_pool[:, b])
                self.v_pool = self.v_pool.at[:, nb].set(self.v_pool[:, b])
                if self.k_scale is not None:
                    self.k_scale = self.k_scale.at[:, nb].set(
                        self.k_scale[:, b])
                    self.v_scale = self.v_scale.at[:, nb].set(
                        self.v_scale[:, b])
                if self.k_draft is not None:
                    # draft pools share the block table, so the draft's
                    # slab must move with the base's copy
                    self.k_draft = self.k_draft.at[:, nb].set(
                        self.k_draft[:, b])
                    self.v_draft = self.v_draft.at[:, nb].set(
                        self.v_draft[:, b])
                self.pool.free([b])
                seq.blocks[bi] = nb
                self._cow_copies += 1
                record_counter("serve.cow_copy")
                self._event("cow_copy", seq.req.request_id, b, nb)
            elif self.pool.is_registered(b):
                # sole owner, but the index still maps a prefix to this
                # block: writing would corrupt future hits' bytes —
                # forget the entry, keep the block private
                self.cache.invalidate_block(b)
        return True

    def _evict_one(self, protect: Optional[_Seq] = None) -> bool:
        """Preempt the lowest-priority, then YOUNGEST running sequence:
        free its blocks and push it to the FRONT of the waiting queue for
        recompute-style readmission (its generated tokens are kept; the
        KV prefix is re-prefilled)."""
        victims = [s for s in self.active
                   if s.state == RUNNING and s is not protect]
        if not victims:
            return False

        def restorable(s: _Seq) -> int:
            # ref-count-aware tiebreak (PR 16): blocks that back prefix-
            # cache entries survive this sequence's eviction (they park
            # or stay shared), so readmission re-hits them — evicting
            # the most-cached victim costs the least recompute. Zero
            # for every sequence when the cache is off.
            if self.cache is None:
                return 0
            return sum(1 for b in s.blocks if self.pool.is_registered(b))

        # lowest priority goes first; then the victim whose prefix is
        # best covered by the cache (cheapest to restore); within that,
        # ties on arrival (e.g. a burst submitted at the same instant)
        # break toward the latest-submitted sequence, deterministically
        victim = max(victims,
                     key=lambda s: (-s.req.priority, restorable(s),
                                    s.arrival, s.order))
        self.active.remove(victim)
        self._release(victim)
        victim.state = WAITING
        victim.n_cached = 0
        victim.draft_pos = 0
        victim.n_preempted += 1
        self.waiting.insert(0, victim)
        self.preemptions += 1
        record_counter("serve.preempt")
        self._event("evict", victim.req.request_id)
        if self.tracer is not None:
            self.tracer.evict(victim.req.request_id, time.perf_counter(),
                              victim.n_preempted)
        if self.recorder is not None:
            self.recorder.note_eviction(self.iteration)
        return True

    def _finish_seq(self, seq: _Seq, t: float):
        seq.state = FINISHED
        if seq in self.active:
            self.active.remove(seq)
        self._release(seq)
        self.finished.append(seq)
        record_counter("serve.finish")
        if self.tracer is not None:
            self.tracer.finish(seq.req.request_id, t, len(seq.generated))

    def _shed_seq(self, seq: _Seq, cause: str):
        """Terminal shed of a QUEUED sequence (deadline already missed)."""
        self._release(seq)
        seq.state = SHED
        seq.fail_cause = cause
        self.shed.append(seq)
        record_counter("serve.shed")
        self._event("shed", seq.req.request_id, cause)
        if self.tracer is not None:
            self.tracer.shed(seq.req.request_id, time.perf_counter(),
                             cause)
        if self.recorder is not None:
            self.recorder.record({"iteration": self.iteration,
                                  "event": "shed",
                                  "rid": seq.req.request_id,
                                  "cause": cause})
        if self._journal is not None:
            self._journal.shed(seq.req.request_id, cause)

    def _shed_expired(self):
        """Deadline-based load shedding over the waiting queue: a queued
        request past its TTFT or total deadline can no longer meet it —
        shed it now instead of burning pool blocks on a dead request.
        Pure engine-clock arithmetic, so replays shed identically."""
        if not self.waiting:
            return
        kept = []
        for seq in self.waiting:
            r, waited = seq.req, self._clock - seq.arrival
            if r.deadline is not None and waited > r.deadline:
                self._shed_seq(seq, "deadline")
            elif (r.ttft_deadline is not None and not seq.generated
                    and waited > r.ttft_deadline):
                self._shed_seq(seq, "ttft_deadline")
            else:
                kept.append(seq)
        self.waiting = kept

    def _quarantine(self, seq: _Seq, cause: str):
        """Poisoned request: release its blocks, mark it failed with the
        cause, keep serving everyone else."""
        if seq in self.active:
            self.active.remove(seq)
        self._release(seq)
        seq.state = FAILED
        seq.fail_cause = cause
        self.failed.append(seq)
        record_counter("serve.quarantine")
        self._event("quarantine", seq.req.request_id, cause)
        if self.tracer is not None:
            self.tracer.quarantine(seq.req.request_id,
                                   time.perf_counter(), cause)
        if self.recorder is not None:
            self.recorder.record({"iteration": self.iteration,
                                  "event": "quarantine",
                                  "rid": seq.req.request_id,
                                  "cause": cause})
        if self._journal is not None:
            self._journal.failed(seq.req.request_id, cause)

    def _pools_alive(self) -> bool:
        """False when an exception killed a kernel AFTER its donated
        k/v pool buffers were invalidated — unrecoverable in-process
        (the journal recovery path owns that failure mode)."""
        pools = [self.k_pool, self.v_pool]
        if self.k_scale is not None:
            pools += [self.k_scale, self.v_scale]
        if self.k_draft is not None:
            pools += [self.k_draft, self.v_draft]
        for pool in pools:
            deleted = getattr(pool, "is_deleted", None)
            if deleted is not None and deleted():
                return False
        return True

    def _mark_compiled(self, kind: str, key, t_call: float):
        if (kind, key) not in self._compiled:
            self._compiled[(kind, key)] = t_call
            record_counter(f"serve.compile.{kind}")
            if self.metrics is not None:
                self.metrics.record_compile(compile_s=t_call)
            if self.recorder is not None:
                self.recorder.record_compile(f"{kind}_{key}", t_call)

    # -- public API ---------------------------------------------------------

    def _demand_and_shared(self, req: Optional[Request]
                           ) -> Tuple[int, int]:
        """Worst-case block demand of everything queued + active, and
        the new request's estimated prefix-shared blocks.

        With the prefix cache on (PR 16), shared prefix blocks are
        free-by-construction — N requests over one cached prefix cost
        its blocks ONCE — so each request's worst case shrinks by its
        expected hit length. Queued-but-unprefilled prompts count too
        (``pending`` keys), so a same-instant burst of identical
        prompts is admitted against one copy of the shared span, which
        is exactly the ROADMAP's "admission estimate could subtract
        shared blocks" item. Cache off: identical to the PR-14 sum."""
        demand = 0
        cache = self.cache
        pending: set = set()
        for s in itertools.chain(self.waiting, self.active):
            # speculative lookahead needs no extra headroom here: the
            # per-iteration cap t_cap <= max_new - generated keeps every
            # allocation within blocks_for(prompt + max_new), the same
            # worst case sequential decode plans for
            worst = self.pool.blocks_for(
                len(s.req.prompt) + s.req.max_new_tokens)
            if cache is not None:
                limit = (len(s.req.prompt) - 1) // self.pool.block_size
                shared = cache.match_len(s.req.prompt, limit, pending)
                worst -= min(shared, worst - 1)
                pending.update(cache.prospective_keys(s.req.prompt,
                                                      limit))
            demand += worst
        new_shared = 0
        if req is not None and cache is not None:
            limit = (len(req.prompt) - 1) // self.pool.block_size
            new_shared = cache.match_len(req.prompt, limit, pending)
        return demand, new_shared

    def _demand_blocks(self) -> int:
        """Worst-case block demand of everything queued + active."""
        return self._demand_and_shared(None)[0]

    def submit(self, req: Request) -> Admission:
        """Admit ``req`` into the bounded queue or reject it with a
        deterministic cause. Malformed requests (can never be served at
        any load) still raise ValueError; overload is an Admission
        outcome, not an exception."""
        if req.request_id is None:
            req.request_id = next(self._rid)
        worst = len(req.prompt) + req.max_new_tokens
        if worst > self.serve.max_seq_len:
            raise ValueError(
                f"request {req.request_id}: prompt+max_new_tokens {worst} "
                f"exceeds max_seq_len {self.serve.max_seq_len}")
        if self.pool.blocks_for(worst) > self.serve.num_blocks - 1:
            raise ValueError(
                f"request {req.request_id} can never fit the pool "
                f"({worst} tokens > {self.serve.num_blocks - 1} blocks)")
        if not len(req.prompt):
            raise ValueError(f"request {req.request_id}: empty prompt")
        faults.inject("serve.admit.before", rid=req.request_id)
        if self._draining:
            # a draining engine admits nothing new — checked before the
            # admission valves so draining never spends bucket tokens
            cause = "draining"
        else:
            demand, new_shared = self._demand_and_shared(req)
            worst_blocks = max(self.pool.blocks_for(worst) - new_shared, 1)
            cause = self.admission.decide(
                queue_len=len(self.waiting),
                demand_blocks=demand,
                worst_blocks=worst_blocks,
                usable_blocks=self.serve.num_blocks - 1,
                now=self._clock)
        if cause is not None:
            self.rejected.append((req, cause))
            record_counter("serve.reject")
            self._event("reject", req.request_id, cause)
            if self.tracer is not None:
                self.tracer.reject(req.request_id, time.perf_counter(),
                                   cause)
            if self.recorder is not None:
                self.recorder.record({"iteration": self.iteration,
                                      "event": "reject",
                                      "rid": req.request_id,
                                      "cause": cause})
            if self._journal is not None:
                self._journal.reject(req.request_id, cause)
            return Admission(False, req.request_id, cause)
        seq = _Seq(req, self._clock)
        seq.order = next(self._seqno)
        self.waiting.append(seq)
        self._event("submit", req.request_id)
        if self.tracer is not None:
            self.tracer.submit(req.request_id, time.perf_counter())
        if self._journal is not None:
            self._journal.submit(req)
        faults.inject("serve.admit.after", rid=req.request_id)
        return Admission(True, req.request_id)

    def adopt(self, req: Request,
              generated: Sequence[int] = ()) -> None:
        """Adopt an already-ACCEPTED request migrated from another
        engine (fleet journal migration, PR 20): enqueue it BYPASSING
        the admission valves — accepted work is never re-rejected —
        with its already-emitted tokens attached, exactly as
        ``recover()`` rebuilds an unfinished rid. Greedy decode is
        deterministic in (prompt + history), so the continuation stream
        is bit-identical to the donor's would-have-been stream. The
        request is re-journaled on THIS engine (submit + inherited
        tokens), so a second crash recovers from this journal alone,
        without the dead donor's file."""
        if req.request_id is None:
            req.request_id = next(self._rid)
        seq = _Seq(req, self._clock)
        seq.order = next(self._seqno)
        seq.tokens.extend(int(t) for t in generated)
        seq.recovered = True
        if seq.generated:
            # its first token predates this engine: never re-measure TTFT
            seq.first_token_t = seq.arrival
        if self._journal is not None:
            self._journal.submit(req)
            if seq.generated:
                self._journal.tokens(
                    self.iteration,
                    [(req.request_id, t) for t in seq.generated])
        if seq.done():
            # the donor emitted its last token but never journaled the
            # finish mark: already complete, no re-drive
            seq.state = FINISHED
            self.finished.append(seq)
            if self._journal is not None:
                self._journal.finish(req.request_id)
        else:
            self.waiting.append(seq)
        self._recovered += 1
        record_counter("serve.adopt")
        self._event("adopt", req.request_id, len(seq.generated))

    def load_signal(self) -> Tuple[float, float, float]:
        """Composite load for fleet routing (PR 20), host-side and
        cheap: (queue depth + in-flight, -available blocks, streaming
        TTFT p99). Every component is a pure function of scheduler
        state and the engine clock, so identical replays expose
        identical load and routing stays deterministic."""
        p99 = self.slo["ttft"].percentile(99)
        return (float(len(self.waiting) + len(self.active)),
                -float(self.pool.available_blocks),
                float(p99 if p99 is not None else 0.0))

    def step(self) -> List[_Seq]:
        """One scheduler iteration: admit, one prefill chunk, one decode
        batch. Returns sequences that finished this iteration."""
        # the gap between step() calls is the engine's safe boundary: the
        # previous decode already synced its tokens to the host, nothing
        # is in flight — scheduled weight swaps land exactly here
        if self._pending_swap is not None \
                and self.iteration + 1 >= self._pending_swap[1]:
            source, _ = self._pending_swap
            self._pending_swap = None
            self._apply_swap(source)
        self.iteration += 1
        self._last_tokens = 0
        self._jtoks = []
        t_iter = time.perf_counter()
        if faults.fires("serve.preempt_storm"):
            # injected pool-pressure fault: forcibly evict the youngest
            # running sequence, as if a burst had stolen its blocks
            self._evict_one()
        self._shed_expired()
        self._admit()
        t_adm = time.perf_counter()
        done: List[_Seq] = []
        ran_prefill = self._prefill_chunk(done)
        t_pre = time.perf_counter()
        done += self._decode_batch()
        t_dec = time.perf_counter()
        for seq in done:
            self._event("finish", seq.req.request_id, len(seq.generated))
        if self._journal is not None:
            # one tokens record per iteration; finish marks AFTER it so
            # a torn tail can lose a finish mark but never a finished
            # request's tokens (recover() re-derives the mark)
            self._journal.tokens(self.iteration, self._jtoks)
            for seq in done:
                self._journal.finish(seq.req.request_id)
        if self.tracer is not None:
            self.tracer.phase("admit", t_iter, t_adm, self.iteration)
            if ran_prefill:
                self.tracer.phase("prefill", t_adm, t_pre, self.iteration)
            self.tracer.phase("decode", t_pre, t_dec, self.iteration)
        if self.metrics is not None or self.recorder is not None:
            n_run = n_pre = 0
            for s in self.active:
                if s.state == RUNNING:
                    n_run += 1
                elif s.state == PREFILL:
                    n_pre += 1
            fields = dict(
                step_time_s=t_dec - t_iter,
                tokens=self._last_tokens,
                queue_depth=len(self.waiting),
                n_running=n_run,
                n_prefill=n_pre,
                batch_occupancy=n_run / self.serve.max_batch,
                pool_utilization=self.pool.utilization,
                prefill_ms=(t_pre - t_adm) * 1e3 if ran_prefill else 0.0,
                decode_ms=(t_dec - t_pre) * 1e3,
            )
            if self.metrics is not None:
                self.metrics.step(**fields)
            if self.recorder is not None:
                self.recorder.record(
                    {"iteration": self.iteration, **fields})
                self.recorder.check_step_time(t_dec - t_iter)
        return done

    def idle(self) -> bool:
        return not self.waiting and not self.active

    # -- scheduler phases ---------------------------------------------------

    def _admit(self):
        while self.waiting and len(self.active) < self.serve.max_batch:
            seq = self.waiting[0]
            # prefix-cache hit (PR 16): the longest chain of cached
            # full blocks prefixing this prompt, capped one token short
            # of prefill_target so the final chunk always has a live
            # token to produce the sampling logits. Hit blocks are
            # shared ref-counted (COW), never re-prefilled.
            hit: List[int] = []
            if self.cache is not None and not seq.blocks:
                limit = (seq.prefill_target - 1) // self.pool.block_size
                hit = self.cache.match(seq.tokens, limit)
            need = self.pool.blocks_for(seq.prefill_target) + 1 - len(hit)
            if not self.pool.can_alloc(need):
                break
            self.waiting.pop(0)
            seq.state = PREFILL
            if hit:
                self.pool.acquire(hit)
                seq.blocks = list(hit)
                seq.n_cached = len(hit) * self.pool.block_size
                record_counter("serve.prefix_hit")
                record_counter("serve.prefix_hit_tokens", seq.n_cached)
                self._event("prefix_hit", seq.req.request_id, len(hit))
            else:
                seq.n_cached = 0
            self.active.append(seq)
            record_counter("serve.admit")
            self._event("admit", seq.req.request_id)
            if not seq.generated:
                # first admission: queue wait from submit to here (a
                # readmitted sequence's renewed wait shows in its trace
                # requeue span, not the SLO histogram)
                self.slo["queue_wait"].record(self._clock - seq.arrival)
            if self.tracer is not None:
                self.tracer.admit(seq.req.request_id, time.perf_counter(),
                                  seq.n_preempted)

    def _prefill_chunk(self, done_out: Optional[List[_Seq]] = None) -> bool:
        seq = next((s for s in self.active if s.state == PREFILL), None)
        if seq is None:
            return False
        rid = seq.req.request_id
        faults.inject("serve.prefill.before", rid=rid)
        c = self.serve.prefill_chunk
        n_live = min(c, seq.prefill_target - seq.n_cached)
        # graceful degradation: under pool pressure, shrink this chunk's
        # LIVE span to the headroom the pool still has (n_live is data,
        # not shape — same compiled step) before resorting to eviction.
        # available_blocks counts parked cache blocks: alloc() reclaims
        # them LRU-oldest after the free list, so caching never shrinks
        # a chunk a cache-off engine could run whole
        headroom = ((len(seq.blocks) + self.pool.available_blocks)
                    * self.pool.block_size - seq.n_cached)
        if 1 <= headroom < n_live:
            n_live = headroom
            record_counter("serve.prefill_shrink")
            self._event("prefill_shrink", rid, n_live)
        if not (self._alloc_for(seq, seq.n_cached + n_live)
                and self._cow_span(seq, seq.n_cached, n_live)):
            # pool dry mid-prompt: steal from the youngest decoder; if
            # there is none, stall — decode progress will free blocks
            if not (self._evict_one(protect=seq)
                    and self._alloc_for(seq, seq.n_cached + n_live)
                    and self._cow_span(seq, seq.n_cached, n_live)):
                return False
        ids = np.zeros((c,), np.int32)
        ids[:n_live] = seq.tokens[seq.n_cached:seq.n_cached + n_live]
        table = pad_table(seq.blocks, self.serve.max_nb)
        key = ("prefill", c)
        t0 = time.perf_counter()
        try:
            faults.inject("serve.prefill.poison", rid=rid)
            with comm_span("serve.prefill",
                           nbytes=int(n_live) * 4,
                           site="serve.prefill"):
                if self.k_scale is None:
                    fn = self._step_fn("prefill", self._frozen)
                    logits, self.k_pool, self.v_pool = fn(
                        self.params, self.k_pool, self.v_pool,
                        jnp.asarray(table), np.int32(seq.n_cached),
                        jnp.asarray(ids), np.int32(n_live))
                else:
                    fn = self._step_fn("prefill", self._frozen, quant=True)
                    (logits, self.k_pool, self.v_pool, self.k_scale,
                     self.v_scale) = fn(
                        self.params, self.k_pool, self.v_pool,
                        self.k_scale, self.v_scale,
                        jnp.asarray(table), np.int32(seq.n_cached),
                        jnp.asarray(ids), np.int32(n_live))
                logits = np.asarray(logits)  # noqa: PTA006 -- deliberate sync so prefill phase timing is honest
            faults.inject("serve.prefill.logits", rid=rid, logits=logits)
            if self._nan_check and not bool(np.isfinite(logits).all()):
                raise PoisonError(rid, "non-finite prefill logits")
        except Exception as e:  # noqa: BLE001 -- quarantine boundary
            if not self._pools_alive():
                raise  # donated pools died mid-kernel: journal recovery
            # a prefill chunk touches exactly one request, so ANY
            # failure here is attributable: quarantine it, keep serving
            cause = (e.cause if isinstance(e, PoisonError)
                     else f"prefill: {e!r}")
            self._quarantine(seq, cause)
            return True
        t1 = time.perf_counter()
        self._mark_compiled(*key, t1 - t0)
        if self.tracer is not None:
            self.tracer.prefill_chunk(
                rid, t0, t1, int(n_live),
                recompute=bool(seq.generated))
        seq.n_cached += n_live
        if seq.n_cached == seq.prefill_target:
            if self.cache is not None:
                # register the prompt's FULL blocks — wholly below
                # prefill_target, so their bytes are immutable from here
                # on (decode writes land at >= prefill_target). A
                # quarantined prefill never reaches this line.
                n_reg = seq.prefill_target // self.pool.block_size
                if n_reg:
                    added = self.cache.register(seq.tokens, seq.blocks,
                                                n_reg)
                    if added:
                        self._event("prefix_register", rid, added)
            if not seq.generated:
                # fresh prompt: the final chunk's logits sample the
                # first new token (greedy)
                seq.tokens.append(int(logits.argmax(-1)))
                seq.first_token_t = self._now()
                seq.token_times.append(seq.first_token_t)
                self._last_tokens += 1
                self._jtoks.append((rid, seq.tokens[-1]))
                self.slo["ttft"].record(seq.first_token_t - seq.arrival)
            if seq.done():
                # eos/max_new on the very first token: finish here so
                # "done() implies finished" holds at every iteration
                # boundary (recover() relies on the invariant)
                self._finish_seq(seq, time.perf_counter())
                if done_out is not None:
                    done_out.append(seq)
            else:
                seq.state = RUNNING
                if self.speculative:
                    # bring the draft's cache up to n_cached before the
                    # first decode iteration touches this row; covers
                    # fresh, readmitted, recovered and prefix-hit
                    # sequences uniformly (the draft re-prefills shared
                    # blocks with identical bytes — pure function of
                    # the token prefix)
                    self._draft_prefill(seq)
        faults.inject("serve.prefill.after", rid=rid)
        return True

    def _draft_prefill(self, seq: _Seq):
        """Chunked prefill of ``seq``'s prompt through the DRAFT model
        into the draft pools (shared block table). Draft state is fully
        derived — never journaled, never recovered — so a crash here
        costs nothing but the re-prefill on readmission."""
        c = self.serve.prefill_chunk
        fn = self._step_fn("prefill", self._draft_frozen)
        table = jnp.asarray(pad_table(seq.blocks, self.serve.max_nb))
        start, target = 0, seq.n_cached
        t0 = time.perf_counter()
        while start < target:
            n_live = min(c, target - start)
            ids = np.zeros((c,), np.int32)
            ids[:n_live] = seq.tokens[start:start + n_live]
            _, self.k_draft, self.v_draft = fn(
                self.draft_params, self.k_draft, self.v_draft,
                table, np.int32(start), jnp.asarray(ids),
                np.int32(n_live))
            start += n_live
        t1 = time.perf_counter()
        self._mark_compiled("draft_prefill", c, t1 - t0)
        seq.draft_pos = target
        if self.tracer is not None:
            self.tracer.phase("draft", t0, t1, self.iteration)

    def _decode_batch(self) -> List[_Seq]:
        if self.speculative:
            return self._decode_spec_batch()
        # grow each row across its block boundary, evicting youngest-
        # first when the pool runs dry (an evicted row drops out of the
        # batch by losing RUNNING state); with nothing evictable the row
        # stalls an iteration instead — finishing rows free its blocks
        ready: List[_Seq] = []
        for seq in [s for s in self.active if s.state == RUNNING]:
            if seq.state != RUNNING:
                continue
            ok = (self._alloc_for(seq, seq.n_cached + 1)
                  and self._cow_span(seq, seq.n_cached, 1))
            while not ok and self._evict_one(protect=seq):
                ok = (self._alloc_for(seq, seq.n_cached + 1)
                      and self._cow_span(seq, seq.n_cached, 1))
            if ok:
                ready.append(seq)
            else:
                record_counter("serve.decode_stall")
        rows = [s for s in ready if s.state == RUNNING]
        if not rows:
            return []
        faults.inject("serve.decode.before",
                      rids=[s.req.request_id for s in rows])
        logits = None
        # re-drive loop: a PoisonError attributable to one row drops that
        # row (quarantined) and re-runs the batch without it; rows are
        # independent (disjoint blocks, per-row tables), so survivors'
        # tokens are bit-identical to a batch that never held the poison
        while rows:
            rids = [s.req.request_id for s in rows]
            bucket = next(b for b in self.serve.decode_buckets
                          if b >= len(rows))
            toks = np.zeros((bucket,), np.int32)
            positions = np.zeros((bucket,), np.int32)
            tables = np.zeros((bucket, self.serve.max_nb), np.int32)
            for i, seq in enumerate(rows):
                toks[i] = seq.tokens[-1]
                positions[i] = seq.n_cached
                tables[i] = pad_table(seq.blocks, self.serve.max_nb)
            key = ("decode", bucket)
            t0 = time.perf_counter()
            try:
                faults.inject("serve.decode.poison", rids=rids)
                with comm_span("serve.decode", nbytes=bucket * 4,
                               site="serve.decode"):
                    if self.k_scale is None:
                        fn = self._step_fn("decode", self._frozen)
                        logits, self.k_pool, self.v_pool = fn(
                            self.params, self.k_pool, self.v_pool,
                            jnp.asarray(tables), jnp.asarray(positions),
                            jnp.asarray(toks))
                    else:
                        fn = self._step_fn("decode", self._frozen,
                                           quant=True)
                        (logits, self.k_pool, self.v_pool, self.k_scale,
                         self.v_scale) = fn(
                            self.params, self.k_pool, self.v_pool,
                            self.k_scale, self.v_scale,
                            jnp.asarray(tables), jnp.asarray(positions),
                            jnp.asarray(toks))
                    logits = np.asarray(logits)  # noqa: PTA006 -- step boundary: sampled tokens must reach the scheduler
                faults.inject("serve.decode.logits", rids=rids,
                              logits=logits)
            except PoisonError as e:
                if not self._pools_alive():
                    raise  # donated pools died mid-kernel: journal path
                bad = next((s for s in rows
                            if s.req.request_id == e.rid), None)
                if bad is None:
                    raise  # not attributable to this batch
                self._quarantine(bad, e.cause)
                rows = [s for s in rows if s is not bad]
                self._redrives += 1
                record_counter("serve.decode_redrive")
                continue
            break
        if not rows:
            return []
        t1 = time.perf_counter()
        self._mark_compiled(*key, t1 - t0)
        next_tok = logits.argmax(-1)
        live = list(enumerate(rows))
        if self._nan_check:
            # per-row screen: quarantine rows whose logits went
            # non-finite; the survivors' already-computed argmax stands
            # (rows are independent)
            finite = np.isfinite(
                logits[:len(rows)].reshape(len(rows), -1)).all(axis=1)
            if not bool(finite.all()):
                for i, seq in [p for p in live if not finite[p[0]]]:
                    self._quarantine(seq, "non-finite decode logits")
                live = [p for p in live if finite[p[0]]]
        if self.tracer is not None:
            self.tracer.decode([s.req.request_id for _, s in live],
                               t0, t1, self.iteration)
        self._last_tokens += len(live)
        done = []
        now = self._now()
        for i, seq in live:
            seq.n_cached += 1
            seq.tokens.append(int(next_tok[i]))
            self._jtoks.append((seq.req.request_id, seq.tokens[-1]))
            if seq.first_token_t is None:
                seq.first_token_t = now
                self.slo["ttft"].record(now - seq.arrival)
            elif seq.token_times:
                self.slo["tpot"].record(now - seq.token_times[-1])
            seq.token_times.append(now)
            if seq.done():
                self._finish_seq(seq, t1)
                done.append(seq)
        faults.inject("serve.decode.after",
                      rids=[s.req.request_id for _, s in live])
        return done

    def _decode_spec_batch(self) -> List[_Seq]:
        """Speculative decode iteration: up to K host-chained DRAFT
        steps propose lookahead tokens per RUNNING row, then ONE batched
        base-model verification pass scores all K+1 positions through
        the multi-token paged read and commits only the accepted
        prefix's KV (ops/paged_attention paged_verify_commit*).

        Determinism contract: every emitted token is the BASE model's
        own greedy argmax at its position — the draft only chooses how
        many positions one iteration can confirm — so the stream is
        bit-identical to sequential decode (PARITY.md) and the journal
        only ever sees verified tokens."""
        K = self.draft_k
        # per-row lookahead cap: never past max_new (admission's worst-
        # case bound) or the table width; floor 1 means the degenerate
        # row still advances one token — the verify path IS the decode
        # path, one uniform program family
        ready: List[_Seq] = []
        caps: Dict[int, int] = {}
        for seq in [s for s in self.active if s.state == RUNNING]:
            if seq.state != RUNNING:
                continue
            remaining = seq.req.max_new_tokens - len(seq.generated)
            t_cap = max(1, min(K + 1, remaining,
                               self.serve.max_seq_len - seq.n_cached))
            ok = (self._alloc_for(seq, seq.n_cached + t_cap)
                  and self._cow_span(seq, seq.n_cached, t_cap))
            # shrink the lookahead before evicting anyone: in-flight
            # draft tokens are free to drop (they cost accept-rate,
            # never correctness)
            while not ok and t_cap > 1:
                t_cap -= 1
                record_counter("serve.spec_shrink")
                ok = (self._alloc_for(seq, seq.n_cached + t_cap)
                      and self._cow_span(seq, seq.n_cached, t_cap))
            while not ok and self._evict_one(protect=seq):
                t_cap = 1
                ok = (self._alloc_for(seq, seq.n_cached + 1)
                      and self._cow_span(seq, seq.n_cached, 1))
            if ok:
                ready.append(seq)
                caps[seq.req.request_id] = t_cap
            else:
                record_counter("serve.decode_stall")
        rows = [s for s in ready if s.state == RUNNING]
        if not rows:
            return []
        faults.inject("serve.decode.before",
                      rids=[s.req.request_id for s in rows])
        # -- draft phase: K batched single-token steps, host-chained.
        # Each step feeds one token per still-proposing row; rows past
        # their window become padding rows (null table -> block-0
        # scribble, the established convention). The first proposing
        # step for a row feeds tokens[-1] — identical to what verify
        # feeds as fed[:, 0] — so catch-up and proposal steps are the
        # same compiled program.
        t0d = time.perf_counter()
        proposals: Dict[int, List[int]] = {}
        last_out: Dict[int, int] = {}
        drafted = False
        bucket = next(b for b in self.serve.decode_buckets
                      if b >= len(rows))
        for _ in range(K):
            toks = np.zeros((bucket,), np.int32)
            positions = np.zeros((bucket,), np.int32)
            tables = np.zeros((bucket, self.serve.max_nb), np.int32)
            stepping = []
            for i, seq in enumerate(rows):
                rid = seq.req.request_id
                if seq.draft_pos >= seq.n_cached + caps[rid] - 1:
                    continue  # window proposed through: padding row
                p = seq.draft_pos
                toks[i] = (seq.tokens[p] if p < len(seq.tokens)
                           else last_out[rid])
                positions[i] = p
                tables[i] = pad_table(seq.blocks, self.serve.max_nb)
                stepping.append((i, seq))
            if not stepping:
                break
            td0 = time.perf_counter()
            fn = self._step_fn("decode", self._draft_frozen)
            dl, self.k_draft, self.v_draft = fn(
                self.draft_params, self.k_draft, self.v_draft,
                jnp.asarray(tables), jnp.asarray(positions),
                jnp.asarray(toks))
            dl = np.asarray(dl)  # noqa: PTA006 -- host-chained: each draft argmax feeds the next draft step
            self._mark_compiled("draft", bucket,
                                time.perf_counter() - td0)
            drafted = True
            nxt = dl.argmax(-1)
            for i, seq in stepping:
                rid = seq.req.request_id
                seq.draft_pos += 1
                last_out[rid] = int(nxt[i])
                if seq.draft_pos > seq.n_cached:
                    proposals.setdefault(rid, []).append(int(nxt[i]))
        t1d = time.perf_counter()
        if drafted and self.tracer is not None:
            self.tracer.phase("draft", t0d, t1d, self.iteration)
        # -- verify phase: one batched K+1-position base pass; the
        # re-drive loop mirrors sequential decode's (rows independent)
        T = K + 1
        out = clen = fin = None
        key = None
        while rows:
            rids = [s.req.request_id for s in rows]
            bucket = next(b for b in self.serve.decode_buckets
                          if b >= len(rows))
            fed = np.zeros((bucket, T), np.int32)
            qstart = np.zeros((bucket,), np.int32)
            t_live = np.zeros((bucket,), np.int32)
            tables = np.zeros((bucket, self.serve.max_nb), np.int32)
            for i, seq in enumerate(rows):
                rid = seq.req.request_id
                props = proposals.get(rid, [])[:caps[rid] - 1]
                fed[i, 0] = seq.tokens[-1]
                fed[i, 1:1 + len(props)] = props
                qstart[i] = seq.n_cached
                t_live[i] = 1 + len(props)
                tables[i] = pad_table(seq.blocks, self.serve.max_nb)
            key = ("verify", bucket)
            t0 = time.perf_counter()
            try:
                faults.inject("serve.decode.poison", rids=rids)
                with comm_span("serve.verify", nbytes=bucket * T * 4,
                               site="serve.verify"):
                    if self.k_scale is None:
                        fn = self._step_fn("verify", self._frozen)
                        (out, clen, fin, self.k_pool,
                         self.v_pool) = fn(
                            self.params, self.k_pool, self.v_pool,
                            jnp.asarray(tables), jnp.asarray(qstart),
                            jnp.asarray(t_live), jnp.asarray(fed))
                    else:
                        fn = self._step_fn("verify", self._frozen,
                                           quant=True)
                        (out, clen, fin, self.k_pool, self.v_pool,
                         self.k_scale, self.v_scale) = fn(
                            self.params, self.k_pool, self.v_pool,
                            self.k_scale, self.v_scale,
                            jnp.asarray(tables), jnp.asarray(qstart),
                            jnp.asarray(t_live), jnp.asarray(fed))
                    out = np.asarray(out)  # noqa: PTA006 -- step boundary: verified tokens must reach the scheduler
                    clen = np.asarray(clen)  # noqa: PTA006 -- accept lengths gate the host-side commit loop
                    fin = np.asarray(fin)  # noqa: PTA006 -- per-row finite screen read at the step boundary
                faults.inject("serve.decode.logits", rids=rids,
                              logits=out)
            except PoisonError as e:
                if not self._pools_alive():
                    raise  # donated pools died mid-kernel: journal path
                bad = next((s for s in rows
                            if s.req.request_id == e.rid), None)
                if bad is None:
                    raise  # not attributable to this batch
                self._quarantine(bad, e.cause)
                rows = [s for s in rows if s is not bad]
                self._redrives += 1
                record_counter("serve.decode_redrive")
                continue
            break
        if not rows:
            return []
        t1 = time.perf_counter()
        self._mark_compiled(*key, t1 - t0)
        live = list(enumerate(rows))
        if self._nan_check:
            # the verify step returns tokens, not logits, so the finite
            # screen is computed inside the jit and surfaced per row
            finite = fin[:len(rows)]
            if not bool(finite.all()):
                for i, seq in [p for p in live if not finite[p[0]]]:
                    self._quarantine(seq, "non-finite decode logits")
                live = [p for p in live if finite[p[0]]]
        if self.tracer is not None:
            self.tracer.decode([s.req.request_id for _, s in live],
                               t0, t1, self.iteration)
            self.tracer.phase("verify", t0, t1, self.iteration)
        done: List[_Seq] = []
        now = self._now()
        for i, seq in live:
            rid = seq.req.request_id
            self._spec_proposed += int(t_live[i]) - 1
            # accepted draft credit = commit_len - 1: the +1 is the
            # base's own correction/next token, not the draft's
            self._spec_accepted += max(0, int(clen[i]) - 1)
            emitted = 0
            for j in range(int(clen[i])):
                seq.n_cached += 1
                seq.tokens.append(int(out[i, j]))
                self._jtoks.append((rid, seq.tokens[-1]))
                emitted += 1
                if seq.first_token_t is None:
                    seq.first_token_t = now
                    self.slo["ttft"].record(now - seq.arrival)
                elif seq.token_times:
                    self.slo["tpot"].record(now - seq.token_times[-1])
                seq.token_times.append(now)
                if seq.done():
                    # eos/max_new inside the window: later verified
                    # tokens are exactly what sequential decode would
                    # have produced AFTER stopping — discard them
                    break
            self._last_tokens += emitted
            # roll the draft back to the last verified position: its
            # cache past the accepted prefix reflects rejected tokens
            seq.draft_pos = min(seq.draft_pos, seq.n_cached)
            if seq.done():
                self._finish_seq(seq, t1)
                done.append(seq)
        faults.inject("serve.decode.after",
                      rids=[s.req.request_id for _, s in live])
        return done

    # -- preemption + live weight push (PR 13) ------------------------------

    def request_preemption(self) -> None:
        """Signal a graceful stop: run() exits at the next iteration
        boundary with queued/active requests intact (thread/signal safe)."""
        self._preempt.set()

    def clear_preemption(self) -> None:
        """Re-arm a preempted engine: run() continues from intact queue/
        active state (deterministic replay resumes bit-identically)."""
        self._preempt.clear()

    def install_preemption_handler(self, signum: int = signal.SIGTERM) -> None:
        """SIGTERM -> request_preemption(); the loop itself never runs
        device code from the handler."""
        try:
            self._prev_handler = signal.signal(
                signum, lambda s, f: self._preempt.set())
            self._signum = signum
        except ValueError:
            warnings.warn(
                "cannot install a signal handler off the main thread; "
                "use request_preemption()", RuntimeWarning)

    def uninstall_preemption_handler(self) -> None:
        if self._signum is not None:
            signal.signal(self._signum, self._prev_handler or signal.SIG_DFL)
            self._signum = None
            self._prev_handler = None

    def swap_weights(self, source, at_iteration: Optional[int] = None
                     ) -> Dict[str, Any]:
        """Live weight push: replace the model weights without restarting
        the engine or dropping a request.

        `source` is a checkpoint directory (a ``save_state_dict`` dir or a
        CheckpointManager root, whose newest complete checkpoint is used)
        or an in-memory param pytree. The new tree must match the current
        one exactly — same structure, shapes, dtypes (same compiled step
        family, so no recompile). Each leaf is placed onto the CURRENT
        leaf's sharding and rebound in place, one leaf at a time (peak
        extra memory = one weight); the KV pools, block tables and all
        scheduler state are untouched.

        With ``at_iteration`` the swap is deferred to that iteration's
        boundary — the safe drain point: the previous decode has synced
        its sampled tokens, nothing is in flight. Called without it, the
        swap applies immediately (between run() calls, or before serving
        starts). With identical weights the post-swap token stream is
        bit-identical; in-flight sequences keep their KV prefix either
        way (their earlier tokens reflect the old weights — the standard
        live-update contract)."""
        if at_iteration is not None and at_iteration > self.iteration:
            self._pending_swap = (source, int(at_iteration))
            self._event("swap_scheduled", int(at_iteration))
            return {"scheduled_at": int(at_iteration)}
        return self._apply_swap(source)

    def _resolve_swap_source(self, source):
        if not isinstance(source, str):
            return source, None
        path = os.path.abspath(source)
        from ..distributed.checkpoint import save_load as sl
        from ..distributed.checkpoint.manager import (CheckpointManager,
                                                      _STEP_RE)
        try:
            entries = os.listdir(path)
        except OSError:
            entries = []
        if any(_STEP_RE.match(n) for n in entries):
            # a manager root: serve from its newest complete checkpoint
            resolved = CheckpointManager(path).latest_path()
            if resolved is None:
                raise FileNotFoundError(
                    f"swap_weights: no complete checkpoint under {path!r}")
            path = resolved
        with sl._pending_lock:
            prev = sl._pending.get(path)
        if prev is not None:
            prev.wait()  # an in-flight async save to this very dir
        import orbax.checkpoint as ocp
        restored = ocp.PyTreeCheckpointer().restore(path)
        if isinstance(restored, dict):
            for sidecar in ("sharding_meta.json", "manifest.json",
                            "COMMIT.json"):
                restored.pop(sidecar, None)
            # a TrainStep/manager checkpoint nests weights under "params"
            if "params" in restored and "params" not in self.params:
                restored = restored["params"]
        return restored, path

    def _apply_swap(self, source) -> Dict[str, Any]:
        faults.inject("serve.swap.before", iteration=self.iteration)
        t0 = time.perf_counter()
        new_tree, path = self._resolve_swap_source(source)
        n_leaves = [0]

        def swap_fill(target, saved, leaf_path):
            if isinstance(target, dict):
                if not isinstance(saved, dict) or set(target) != set(saved):
                    raise ValueError(
                        f"swap_weights: param tree mismatch at "
                        f"{leaf_path or '<root>'!r}: engine has "
                        f"{sorted(target) if isinstance(target, dict) else type(target)}, "
                        f"source has "
                        f"{sorted(saved) if isinstance(saved, dict) else type(saved)}")
                for k in target:
                    target[k] = swap_fill(
                        target[k], saved[k],
                        f"{leaf_path}.{k}" if leaf_path else str(k))
                return target
            if isinstance(target, (list, tuple)):
                if not isinstance(saved, (list, tuple)) \
                        or len(target) != len(saved):
                    raise ValueError(
                        f"swap_weights: param tree mismatch at "
                        f"{leaf_path!r}")
                out = [swap_fill(t, s, f"{leaf_path}[{i}]")
                       for i, (t, s) in enumerate(zip(target, saved))]
                return type(target)(out)
            shape = tuple(np.shape(saved))
            if tuple(target.shape) != shape:
                raise ValueError(
                    f"swap_weights: shape mismatch at {leaf_path!r}: "
                    f"engine {tuple(target.shape)}, source {shape}")
            # place onto the CURRENT leaf's sharding/dtype: the compiled
            # decode/prefill steps see identical avals, so no recompile;
            # the old buffer frees as soon as this rebind drops it
            arr = jnp.asarray(np.asarray(saved), dtype=target.dtype)  # noqa: PTA006 -- swap boundary is a drain point by contract; source is host-resident
            sh = getattr(target, "sharding", None)
            if sh is not None:
                arr = jax.device_put(arr, sh)
            n_leaves[0] += 1
            return arr

        drained_running = sum(1 for s in self.active if s.state == RUNNING)
        drained_prefill = sum(1 for s in self.active if s.state == PREFILL)
        if isinstance(self.params, dict):
            swap_fill(self.params, new_tree, "")
        else:
            self.params = swap_fill(self.params, new_tree, "")
        self.swaps += 1
        record_counter("serve.swap")
        stats = {
            "iteration": self.iteration,
            "swap_ms": (time.perf_counter() - t0) * 1e3,
            "n_leaves": n_leaves[0],
            "in_flight_running": drained_running,
            "in_flight_prefill": drained_prefill,
            "source": path,
        }
        self.last_swap = stats
        self._event("swap", n_leaves[0])
        if self.recorder is not None:
            self.recorder.record({"iteration": self.iteration,
                                  "event": "swap", **{
                                      k: v for k, v in stats.items()
                                      if k != "iteration"}})
        if self._journal is not None:
            self._journal.swap(self.iteration, path)
        faults.inject("serve.swap.after", iteration=self.iteration)
        return stats

    # -- driving loops ------------------------------------------------------

    def _now(self) -> float:
        return self._clock

    def run(self, requests: Sequence[Request],
            deterministic: bool = False, max_iterations: int = 100000
            ) -> Dict[str, Any]:
        """Drive the engine until every request finishes.

        Wall mode (default): ``arrival`` is seconds from start; the
        engine clock is wall time and idle gaps are slept through.
        Deterministic mode: ``arrival`` is an ITERATION index and the
        clock counts iterations — replaying the same trace must
        reproduce the same event log and tokens bit-for-bit
        (scheduling never consults wall time)."""
        pending = sorted(requests, key=lambda r: r.arrival)
        t0 = time.perf_counter()
        try:
            while pending or not self.idle():
                if self._preempt.is_set() or faults.fires("serve.preempt"):
                    # graceful preemption: stop at the iteration boundary
                    # (nothing in flight), dump the post-mortem ring and
                    # return — queued/active work stays intact for a
                    # successor engine to re-drive
                    self._was_preempted = True
                    record_counter("serve.preempted")
                    self._event("preempt_stop")
                    if self.recorder is not None:
                        self.recorder.dump("preemption")
                    break
                if self.iteration >= max_iterations:
                    raise RuntimeError("engine exceeded max_iterations")
                self._clock = (float(self.iteration) if deterministic
                               else time.perf_counter() - t0)
                while pending and pending[0].arrival <= self._clock:
                    self.submit(pending.pop(0))
                if self.idle() and pending:
                    if deterministic:
                        self.iteration += 1
                    else:
                        time.sleep(min(
                            pending[0].arrival - self._clock, 0.01))
                    continue
                self.step()
                if not deterministic:
                    self._clock = time.perf_counter() - t0
        except BaseException:
            # a crashed run must leave a LEAK-FREE pool: demote every
            # live sequence to the front of the waiting queue (eviction-
            # style, order preserved) with its blocks released, so a
            # successor engine — or recover() — inherits clean state
            while self.active:
                seq = self.active.pop()
                self._release(seq)
                seq.state = WAITING
                seq.n_cached = 0
                seq.draft_pos = 0
                self.waiting.insert(0, seq)
            # crash post-mortem: dump the last N iteration records before
            # the exception leaves the engine (no-op without a recorder
            # or a telemetry dir)
            if self.recorder is not None:
                self.recorder.dump("exception")
            raise
        if self._journal is not None:
            # clean exit: drain the buffered tokens/finish marks so the
            # on-disk journal of an idle engine is always complete
            self._journal.flush()
        return self.stats()

    def drain(self, deterministic: bool = False,
              max_iterations: int = 100000
              ) -> Dict[int, Tuple[str, Optional[str]]]:
        """Graceful wind-down: stop admitting (every later ``submit()``
        rejects with cause ``draining``), run the already-accepted work
        to completion, and return the total :meth:`outcomes` map. The
        overload contract holds throughout — ``outcomes()`` stays total
        during and after the drain, with drained-away submissions
        showing as ``("rejected", "draining")``. The engine stays
        usable: :meth:`undrain` re-opens admissions (the fleet's
        rolling weight swap drains, swaps, then undrains each replica
        in turn)."""
        self._draining = True
        record_counter("serve.drain")
        self._event("drain")
        self.run([], deterministic=deterministic,
                 max_iterations=max_iterations)
        return self.outcomes()

    def undrain(self) -> None:
        """Re-open admissions after :meth:`drain`."""
        self._draining = False
        self._event("undrain")

    def recover(self, journal_path: Optional[str] = None
                ) -> Dict[str, Any]:
        """Rebuild scheduler state from an engine journal after a crash.

        The journal holds every accepted request and every token the
        dead engine emitted. Greedy decoding is deterministic in
        (prompt + generated history), so re-queueing each unfinished
        request with its journaled tokens and re-driving it through the
        ordinary preempted-sequence path (re-prefill the cached
        context, resume decoding) reproduces the remaining stream
        bit-identically — tokens emitted after the journal's last flush
        are simply re-derived. Call on a FRESH engine, or on one whose
        ``run()`` raised (its demoted sequences are discarded in favor
        of the journal's authoritative record); then ``run([])`` drives
        the recovered requests to completion. The journal is reopened
        for append, so the recovered engine keeps journaling."""
        path = journal_path or self.journal_path
        if not path:
            raise ValueError(
                "recover() needs a journal: pass journal_path= or build "
                "the engine with journal=/PADDLE_TPU_SERVE_JOURNAL")
        st = read_journal(path)
        # up-front portability screen (PR 20): either this engine can
        # re-drive the journal bit-identically, or refuse before any
        # state is touched. kv_dtype is the one stream-changing axis
        # (int8 quantization is the documented numeric deviation);
        # mp / prefix_cache / speculative differences recover freely —
        # PARITY.md pins their streams as bit-identical.
        j_dtype = st.meta.get("kv_dtype")
        if j_dtype is not None and j_dtype != self.kv_dtype:
            raise JournalCompatError(
                f"recover(): journal {path!r} was written with "
                f"kv_dtype={j_dtype!r} but this engine stores "
                f"{self.kv_dtype!r}; crossing the int8 quantization "
                f"boundary changes token streams, so the re-drive "
                f"would not be bit-identical")
        for rid in st.unfinished_rids():
            rec = st.requests[rid]
            worst = len(rec["prompt"]) + int(rec["max_new_tokens"])
            if worst > self.serve.max_seq_len:
                raise JournalCompatError(
                    f"recover(): journaled request {rid} needs {worst} "
                    f"tokens but this engine's max_seq_len is "
                    f"{self.serve.max_seq_len}")
            if self.pool.blocks_for(worst) > self.serve.num_blocks - 1:
                raise JournalCompatError(
                    f"recover(): journaled request {rid} can never fit "
                    f"this engine's pool ({worst} tokens > "
                    f"{self.serve.num_blocks - 1} usable blocks)")
        for seq in itertools.chain(self.active, self.waiting):
            self._release(seq)
        self.active, self.waiting = [], []
        if self.pool.used_blocks:
            raise RuntimeError(
                f"recover(): pool leaked {self.pool.used_blocks} blocks")
        terminal = st.terminal_rids()
        n_replayed = n_prefinished = 0
        for rid in st.unfinished_rids():
            rec = st.requests[rid]
            req = Request(
                prompt=rec["prompt"],
                max_new_tokens=rec["max_new_tokens"],
                request_id=rid, eos_id=rec.get("eos_id"),
                arrival=float(rec.get("arrival", 0.0)),
                priority=int(rec.get("priority", 0)),
                ttft_deadline=rec.get("ttft_deadline"),
                deadline=rec.get("deadline"))
            seq = _Seq(req, self._clock)
            seq.order = next(self._seqno)
            seq.tokens.extend(st.tokens.get(rid, ()))
            seq.recovered = True
            if seq.generated:
                # its first token predates this engine: keep the SLO
                # histograms honest by not re-measuring TTFT
                seq.first_token_t = seq.arrival
            if seq.done():
                # crashed after its last token but before its finish
                # mark was journaled: already complete, no re-drive
                seq.state = FINISHED
                self.finished.append(seq)
                n_prefinished += 1
            else:
                self.waiting.append(seq)
                n_replayed += 1
        self._recovered = n_replayed + n_prefinished
        known = list(st.requests) + list(st.rejected)
        if known:
            self._rid = itertools.count(max(known) + 1)
        if self._journal is None:
            self._journal = EngineJournal(
                path, fsync=envs.get(ENV_SERVE_JOURNAL_FSYNC),
                resume=True, meta=self._journal_meta())
            self.journal_path = path
        else:
            # in-place recovery after run() raised: the writer may hold
            # token pairs from before the crash — they predate the read
            # above, and draining them now would duplicate streams
            self._journal.discard_pending()
        self._journal.recovered(self._recovered, st.torn_lines)
        for seq in self.finished[len(self.finished) - n_prefinished:]:
            self._journal.finish(seq.req.request_id)
        record_counter("serve.recover")
        self._event("recover", self._recovered)
        return {
            "recovered": self._recovered,
            "replayed": n_replayed,
            "already_finished": n_prefinished,
            "terminal_in_journal": len(terminal),
            "torn_lines": st.torn_lines,
            "journal_swaps": st.swaps,
        }

    def stats(self) -> Dict[str, Any]:
        """Throughput/latency aggregates over finished requests (times
        in the engine clock: seconds in wall mode, iterations in
        deterministic mode).

        Requests that never produced a first token — still queued, mid-
        prefill, or evicted at shutdown — are counted in ``unfinished``
        rather than silently dropped, so the TTFT percentiles are
        explicitly conditioned on completion instead of optimistically
        biased. The ``*_stream_*`` entries are the live log-bucketed
        histogram estimates next to the exact percentiles (they must
        agree within one bucket)."""
        seqs = self.finished
        gen = sum(len(s.generated) for s in seqs)
        ttfts = [s.first_token_t - s.arrival for s in seqs
                 if s.first_token_t is not None]
        gaps: List[float] = []
        for s in seqs:
            gaps.extend(np.diff(s.token_times).tolist())  # noqa: PTA006 -- host timing stats over Python floats, no device data
        span = (max((s.token_times[-1] for s in seqs if s.token_times),
                    default=0.0)
                - min((s.arrival for s in seqs), default=0.0))
        pct = (lambda a, q: float(np.percentile(a, q)) if a else None)
        unfinished = (len(self.waiting) + len(self.active)
                      + sum(1 for s in seqs if s.first_token_t is None))
        return {
            "requests": len(seqs),
            "unfinished": unfinished,
            "generated_tokens": gen,
            "elapsed_s": span,
            "tokens_per_sec": gen / span if span > 0 else None,
            "ttft_p50_s": pct(ttfts, 50),
            "ttft_p99_s": pct(ttfts, 99),
            "tpot_p50_s": pct(gaps, 50),
            "tpot_p99_s": pct(gaps, 99),
            "ttft_stream_p50_s": self.slo["ttft"].percentile(50),
            "ttft_stream_p99_s": self.slo["ttft"].percentile(99),
            "tpot_stream_p50_s": self.slo["tpot"].percentile(50),
            "tpot_stream_p99_s": self.slo["tpot"].percentile(99),
            "preemptions": self.preemptions,
            "preempted": self._was_preempted,
            "weight_swaps": self.swaps,
            "iterations": self.iteration,
            "compiles": {f"{k}_{v}": round(t, 3)
                         for (k, v), t in sorted(self._compiled.items())},
            "pool_blocks": self.serve.num_blocks - 1,
            "mp": self.mp,
            "pool_bytes_per_rank": pool_bytes_per_rank(
                (self.k_pool, self.v_pool, self.k_scale, self.v_scale,
                 self.k_draft, self.v_draft), self.mp),
            "rejected": len(self.rejected),
            "shed": len(self.shed),
            "failed": len(self.failed),
            "decode_redrives": self._redrives,
            "recovered": self._recovered,
            "kv_dtype": self.kv_dtype,
            "prefix_cache": (dict(self.cache.stats(),
                                  cached_blocks=self.pool.cached_blocks,
                                  cow_copies=self._cow_copies)
                             if self.cache is not None else None),
            "speculative": ({
                "draft_k": self.draft_k,
                "draft_layers": self.draft_config.num_hidden_layers,
                "proposed": self._spec_proposed,
                "accepted": self._spec_accepted,
                "accept_rate": (self._spec_accepted / self._spec_proposed
                                if self._spec_proposed else None),
            } if self.speculative else None),
            "outcomes": self.outcomes(),
        }

    def outcomes(self) -> Dict[int, Tuple[str, Optional[str]]]:
        """Disposition of EVERY request this engine has seen:
        ``rid -> (state, cause)``. The overload contract — nothing is
        silently dropped — means each submitted request appears here in
        exactly one state (terminal: finished/rejected/shed/failed with
        a cause; live requests report their current scheduler state)."""
        out: Dict[int, Tuple[str, Optional[str]]] = {}
        for req, cause in self.rejected:
            out[req.request_id] = ("rejected", cause)
        for seq in self.finished:
            out[seq.req.request_id] = (FINISHED, None)
        for seq in self.shed:
            out[seq.req.request_id] = (SHED, seq.fail_cause)
        for seq in self.failed:
            out[seq.req.request_id] = (FAILED, seq.fail_cause)
        for seq in itertools.chain(self.waiting, self.active):
            out[seq.req.request_id] = (seq.state, None)
        return out

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Live metric snapshot, any time mid-run: the streaming SLO
        histograms plus scheduler gauges, straight from the unified
        :class:`~paddle_tpu.observability.MetricsRegistry` (key order is
        the registration order, unchanged from the pre-PR-15 dict)."""
        return self.registry.snapshot()

    def render_prometheus(self) -> str:
        """Prometheus text exposition via the unified registry (sample
        lines byte-identical to the legacy dict renderer; ``# HELP``/
        ``# TYPE`` pairs ahead of each family)."""
        return self.registry.render_prometheus()
