"""Multi-replica serving fleet: a prefix-affinity router over N engines.

PRs 16-19 maxed out the single-engine axes (prefix caching, speculative
decoding, int8 KV, tensor-parallel sharding); the capacity ceiling left
is ONE engine. :class:`FleetRouter` owns N :class:`InferenceEngine`
replicas and turns the PR 12-15 robustness primitives into aggregate
throughput:

  - **Prefix-affinity dispatch.** Each submit probes every live
    replica's ``PrefixCache.match_len`` (host-side, a dict walk — no
    device work) and prefers the replica holding the longest cached
    prefix, so shared-system-prompt traffic lands where its COW blocks
    already live and fleet-wide hit rate approaches single-engine hit
    rate instead of 1/N of it.
  - **Load-aware tiebreak.** Among equally-cached replicas (including
    the no-hit case) the router picks by the engines' composite
    ``load_signal()`` — queue depth + in-flight, free blocks, streaming
    TTFT p99 — with the replica index as the final tiebreak, so every
    component is deterministic and identical traces route identically.
  - **Spill threshold.** Adversarial prefix skew (all traffic sharing
    one prefix) must not starve N-1 replicas: when the affinity
    winner's queue depth reaches ``spill``, the request spills to the
    least-loaded live replica instead (counted as a rebalance). The
    cold replica re-derives the prefix once and becomes a second
    affinity target — saturation self-heals.
  - **Journal migration.** ``kill_replica()`` simulates a crash (the
    journal fd dies unflushed, exactly like a killed process), then
    re-drives the journal's accepted-but-unfinished requests onto
    surviving replicas via :meth:`InferenceEngine.adopt` — recover()
    semantics, re-routed. Greedy decode is deterministic in (prompt +
    history), so migrated continuation streams are bit-identical to the
    no-failure run and zero accepted requests are lost.
  - **Rolling weight swap.** ``request_rolling_swap()`` walks the fleet
    one replica at a time: steer new traffic away, let in-flight work
    drain, ``swap_weights`` at the idle boundary, re-open, next
    replica. N-1 replicas keep serving throughout — zero downtime,
    zero drops.
  - **Fleet metrics.** ``render_prometheus()`` merges every replica's
    engine registry into one exposition with a ``replica=`` label
    (:meth:`MetricsRegistry.merge`) plus a fleet-level block: router
    counters (affinity hits, spills, migrations, rolling swaps) and
    aggregates.

Determinism contract (PARITY.md PR 20): in deterministic mode the
fleet clock is the fleet iteration index, every engine's clock is
slaved to it, and routing consults only scheduler state — two replays
of one trace produce identical routing decisions, identical per-replica
streams, and identical migration behavior under a seeded kill.
"""
from __future__ import annotations

import itertools
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import envs
from ..observability.registry import MetricsRegistry
from .engine import Admission, InferenceEngine, Request, ServeConfig
from .journal import read_journal

__all__ = ["FleetRouter"]

ENV_FLEET_REPLICAS = "PADDLE_TPU_FLEET_SERVE_REPLICAS"
ENV_FLEET_SPILL = "PADDLE_TPU_FLEET_SERVE_SPILL"
ENV_FLEET_JOURNAL_DIR = "PADDLE_TPU_FLEET_SERVE_JOURNAL_DIR"


class FleetRouter:
    """Deterministic two-level router over N engine replicas.

    >>> fleet = FleetRouter(params, config, ServeConfig(), n_replicas=3,
    ...                     journal_dir="/tmp/journals")
    >>> stats = fleet.run(requests, deterministic=True)

    ``policy="affinity"`` (default) is the two-level prefix-affinity /
    load dispatch; ``policy="random"`` routes uniformly from a seeded
    RNG — the A/B baseline the bench compares affinity hit rate
    against. All replicas share one weight tree (at mp=1 the engines
    hold it by reference); each owns its KV pools, scheduler state and,
    with ``journal_dir``, its own ``replica_<i>.jsonl`` journal."""

    def __init__(self, params: Dict[str, Any], config,
                 serve: Optional[ServeConfig] = None,
                 n_replicas: Optional[int] = None,
                 journal_dir: Optional[str] = None,
                 spill: Optional[int] = None,
                 policy: str = "affinity", seed: int = 0,
                 record_events: bool = False,
                 engine_kw: Optional[Dict[str, Any]] = None):
        self.n = int(n_replicas if n_replicas is not None
                     else envs.get(ENV_FLEET_REPLICAS))
        if self.n < 1:
            raise ValueError(f"n_replicas must be >= 1, got {self.n}")
        if policy not in ("affinity", "random"):
            raise ValueError(
                f"policy must be 'affinity' or 'random', got {policy!r}")
        self.policy = policy
        self.spill = int(spill if spill is not None
                         else envs.get(ENV_FLEET_SPILL))
        if self.spill < 1:
            raise ValueError(f"spill must be >= 1, got {self.spill}")
        journal_dir = (journal_dir if journal_dir is not None
                       else envs.get(ENV_FLEET_JOURNAL_DIR))
        self.journal_dir = journal_dir or None
        self.engines: List[InferenceEngine] = []
        for i in range(self.n):
            jp = (os.path.join(self.journal_dir, f"replica_{i}.jsonl")
                  if self.journal_dir else None)
            self.engines.append(InferenceEngine(
                params, config, serve, journal=jp,
                record_events=record_events, **(engine_kw or {})))
        self.alive: List[bool] = [True] * self.n
        self.dead: List[int] = []
        # router-level steering (rolling swap): replicas here stay live
        # and keep serving their in-flight work, but route() skips them
        self._steering: set = set()
        self._swap: Optional[Dict[str, Any]] = None
        self.last_rolling_swap: Optional[Dict[str, Any]] = None
        self._rng = np.random.RandomState(seed)
        self._rid = itertools.count()
        self._clock = 0.0
        self.iteration = 0
        # rid -> replica holding it; rejections keep the refusing replica
        self.assignments: Dict[int, int] = {}
        self.rejected_at: Dict[int, int] = {}
        self.routed = [0] * self.n
        self.routing_log: List[Tuple[int, int, str, bool]] = []
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.spills = 0
        self.migrations = 0
        self.rolling_swaps = 0
        self.registry = MetricsRegistry(prefix="paddle_tpu_fleet")
        self._register_metrics()

    # -- metrics ------------------------------------------------------------

    def _register_metrics(self) -> None:
        r = self.registry
        r.gauge("replicas", fn=lambda: self.n,
                help="configured replica count")
        r.gauge("replicas_live", fn=lambda: sum(self.alive),
                help="replicas currently serving")
        r.gauge("affinity_hits", fn=lambda: self.affinity_hits,
                help="requests routed to a replica holding their prefix")
        r.gauge("affinity_misses", fn=lambda: self.affinity_misses,
                help="requests with no cached prefix on any replica")
        r.gauge("spills", fn=lambda: self.spills,
                help="rebalances away from a saturated affinity replica")
        r.gauge("migrations", fn=lambda: self.migrations,
                help="requests re-driven off a killed replica's journal")
        r.gauge("rolling_swaps", fn=lambda: self.rolling_swaps,
                help="per-replica weight swaps landed by a rolling swap")
        r.gauge("routed_requests", fn=lambda: sum(self.routed),
                help="accepted requests dispatched by the router")
        r.gauge("queue_depth",
                fn=lambda: sum(len(self.engines[i].waiting)
                               for i in range(self.n) if self.alive[i]),
                help="fleet-wide admitted-but-unscheduled requests")
        r.gauge("finished_requests",
                fn=lambda: len([1 for st, _ in self.outcomes().values()
                                if st == "finished"]),
                help="fleet-wide completed requests (unique rids)")
        r.gauge("generated_tokens",
                fn=lambda: sum(len(t) for t in self.streams().values()),
                help="fleet-wide tokens generated by finished requests")

    # -- routing ------------------------------------------------------------

    def _live(self) -> List[int]:
        return [i for i in range(self.n) if self.alive[i]]

    def _load_key(self, i: int) -> Tuple:
        # composite load, replica index last: fully deterministic order
        return self.engines[i].load_signal() + (i,)

    def route(self, req: Request) -> Tuple[int, str]:
        """Pick a replica for ``req``: ``(index, kind)`` where kind is
        the decision path taken (``affinity`` | ``spill`` | ``load`` |
        ``random``). Pure function of scheduler state (plus the seeded
        RNG under ``policy='random'``) — replays route identically."""
        live = [i for i in self._live() if i not in self._steering]
        if not live:
            # every live replica is draining for a swap (N=1 fleets):
            # routing away has nowhere to go — keep serving, zero drops
            live = self._live()
        if not live:
            raise RuntimeError("route(): no live replicas")
        if self.policy == "random":
            return live[int(self._rng.randint(len(live)))], "random"
        hits: Dict[int, int] = {}
        for i in live:
            eng = self.engines[i]
            if eng.cache is None:
                hits[i] = 0
            else:
                limit = (len(req.prompt) - 1) // eng.pool.block_size
                hits[i] = eng.cache.match_len(list(req.prompt), limit)
        best = max(hits.values())
        if best > 0:
            cands = sorted(i for i in live if hits[i] == best)
            aff = min(cands, key=self._load_key)
            if (self.engines[aff].load_signal()[0] < self.spill
                    or len(cands) == len(live)):
                self.affinity_hits += 1
                return aff, "affinity"
            # affinity replica saturated: spill by load over the whole
            # live set so N-1 replicas never starve under prefix skew
            self.spills += 1
            return min(live, key=self._load_key), "spill"
        self.affinity_misses += 1
        return min(live, key=self._load_key), "load"

    def submit(self, req: Request) -> Admission:
        """Route and submit one request. Fleet-unique rids are assigned
        here (engines honor a pre-set ``request_id``), so journals and
        outcomes merge without collisions."""
        if req.request_id is None:
            req.request_id = next(self._rid)
        i, kind = self.route(req)
        eng = self.engines[i]
        eng._clock = self._clock
        adm = eng.submit(req)
        self.routing_log.append((req.request_id, i, kind, adm.accepted))
        if adm.accepted:
            self.assignments[req.request_id] = i
            self.routed[i] += 1
        else:
            self.rejected_at[req.request_id] = i
        return adm

    # -- replica kill + journal migration -----------------------------------

    def kill_replica(self, idx: int) -> Dict[str, Any]:
        """Simulate a replica crash and migrate its work.

        The journal fd is abandoned mid-buffer (exactly what the OS
        does to a killed process), the replica leaves the routing set,
        and every accepted-but-unfinished request in its journal is
        rebuilt and re-routed onto survivors via ``adopt()`` — tokens
        already journaled ride along, the remainder is re-derived
        bit-identically (greedy determinism). Without a journal the
        in-memory queue migrates instead (drain-style, exact tokens).
        Zero accepted requests are lost either way."""
        if not self.alive[idx]:
            raise ValueError(f"replica {idx} is already dead")
        if sum(self.alive) < 2:
            raise RuntimeError(
                "kill_replica(): no surviving replica to migrate onto")
        eng = self.engines[idx]
        self.alive[idx] = False
        self.dead.append(idx)
        self._steering.discard(idx)
        if eng._journal is not None:
            eng._journal.abandon()
        # host-side block bookkeeping: demote live sequences exactly as
        # run()'s crash path does, so the fleet-wide pool audit stays
        # leak-free (the dead replica's device pools are garbage either
        # way — the journal is the authoritative record)
        while eng.active:
            seq = eng.active.pop()
            eng._release(seq)
            seq.state = "waiting"
            seq.n_cached = 0
            seq.draft_pos = 0
            eng.waiting.insert(0, seq)
        migrated = 0
        if eng.journal_path:
            st = read_journal(eng.journal_path)
            for rid in st.unfinished_rids():
                rec = st.requests[rid]
                req = Request(
                    prompt=rec["prompt"],
                    max_new_tokens=rec["max_new_tokens"],
                    request_id=rid, eos_id=rec.get("eos_id"),
                    arrival=float(rec.get("arrival", 0.0)),
                    priority=int(rec.get("priority", 0)),
                    ttft_deadline=rec.get("ttft_deadline"),
                    deadline=rec.get("deadline"))
                self._migrate(req, st.tokens.get(rid, []))
                migrated += 1
        else:
            for seq in list(eng.waiting):
                self._migrate(seq.req, list(seq.generated))
                migrated += 1
            eng.waiting = []
        return {"replica": idx, "migrated": migrated}

    def _migrate(self, req: Request, generated: Sequence[int]) -> None:
        i, kind = self.route(req)
        eng = self.engines[i]
        eng._clock = self._clock
        eng.adopt(req, generated)
        self.assignments[req.request_id] = i
        self.routed[i] += 1
        self.migrations += 1
        self.routing_log.append((req.request_id, i, f"migrate:{kind}",
                                 True))

    # -- rolling fleet-wide weight swap -------------------------------------

    def request_rolling_swap(self, source) -> None:
        """Start a zero-downtime fleet-wide weight swap: one replica at
        a time is steered out of routing, drains its in-flight work,
        swaps at the idle boundary (nothing in flight — the same safe
        point ``swap_weights(at_iteration=)`` uses), and rejoins. The
        state machine advances one transition per fleet iteration
        inside :meth:`run`."""
        if self._swap is not None:
            raise RuntimeError("a rolling swap is already in progress")
        self._swap = {"source": source, "queue": self._live(),
                      "current": None, "swapped": []}

    def _advance_swap(self) -> None:
        sw = self._swap
        if sw is None:
            return
        cur = sw["current"]
        if cur is not None:
            if not self.alive[cur]:
                # killed mid-drain: its work already migrated, move on
                self._steering.discard(cur)
                sw["current"] = None
            elif self.engines[cur].idle():
                self.engines[cur].swap_weights(sw["source"])
                self.rolling_swaps += 1
                sw["swapped"].append(cur)
                self._steering.discard(cur)
                sw["current"] = None
            else:
                return  # still draining
        while sw["queue"]:
            nxt = sw["queue"].pop(0)
            if not self.alive[nxt]:
                continue
            sw["current"] = nxt
            self._steering.add(nxt)
            return
        self.last_rolling_swap = {"swapped": list(sw["swapped"])}
        self._swap = None

    # -- driving loop -------------------------------------------------------

    def idle(self) -> bool:
        return all(self.engines[i].idle() for i in self._live())

    def run(self, requests: Sequence[Request],
            deterministic: bool = False, max_iterations: int = 100000,
            kill_at: Optional[Tuple[int, int]] = None,
            rolling_swap_at: Optional[int] = None,
            swap_source=None) -> Dict[str, Any]:
        """Drive the fleet until every request finishes (and any rolling
        swap completes). One fleet iteration = one ``step()`` on every
        non-idle live replica, in replica order — lockstep, so the
        deterministic clock (the fleet iteration index) is shared by
        all engines and every scheduling decision replays identically.

        ``kill_at=(iteration, replica)`` kills that replica at the top
        of that fleet iteration (the seeded mid-trace chaos the tests
        and bench drive); ``rolling_swap_at=`` starts a rolling swap of
        ``swap_source`` at that iteration."""
        pending = sorted(requests, key=lambda r: r.arrival)
        t0 = time.perf_counter()
        while pending or not self.idle() or self._swap is not None:
            if self.iteration >= max_iterations:
                raise RuntimeError("fleet exceeded max_iterations")
            self._clock = (float(self.iteration) if deterministic
                           else time.perf_counter() - t0)
            if (kill_at is not None and self.iteration == int(kill_at[0])
                    and self.alive[int(kill_at[1])]):
                self.kill_replica(int(kill_at[1]))
            if (rolling_swap_at is not None and self._swap is None
                    and self.iteration == int(rolling_swap_at)):
                self.request_rolling_swap(swap_source)
            self._advance_swap()
            while pending and pending[0].arrival <= self._clock:
                self.submit(pending.pop(0))
            stepped = False
            for i in self._live():
                eng = self.engines[i]
                if eng.idle():
                    continue
                eng._clock = self._clock
                eng.step()
                stepped = True
            self.iteration += 1
            if not stepped and pending and not deterministic:
                time.sleep(min(pending[0].arrival - self._clock, 0.01))
        for i in self._live():
            if self.engines[i]._journal is not None:
                self.engines[i]._journal.flush()
        return self.stats()

    # -- aggregate views ----------------------------------------------------

    def streams(self) -> Dict[int, List[int]]:
        """``rid -> generated tokens`` over every finished request in
        the fleet. Dead replicas contribute their pre-kill streams
        (already delivered to clients); a migrated rid that ALSO
        finished pre-kill is overridden by the survivor's identical
        re-derivation (greedy determinism)."""
        out: Dict[int, List[int]] = {}
        for i in self.dead:
            for s in self.engines[i].finished:
                out[s.req.request_id] = list(s.generated)
        for i in self._live():
            for s in self.engines[i].finished:
                out[s.req.request_id] = list(s.generated)
        return out

    def outcomes(self) -> Dict[int, Tuple[str, Optional[str]]]:
        """Total disposition map across the fleet: every request any
        replica ever saw, survivors overriding dead replicas for
        migrated rids. The zero-lost contract is checkable here: every
        accepted rid appears, none in a dangling state."""
        out: Dict[int, Tuple[str, Optional[str]]] = {}
        for i in self.dead:
            out.update(self.engines[i].outcomes())
        for i in self._live():
            out.update(self.engines[i].outcomes())
        return out

    def lost_requests(self) -> List[int]:
        """Accepted rids with NO outcome anywhere in the fleet — the
        zero-lost invariant says this is always empty."""
        oc = self.outcomes()
        return [rid for rid in self.assignments if rid not in oc]

    def stats(self) -> Dict[str, Any]:
        oc = self.outcomes()
        streams = self.streams()
        finished = [rid for rid, (st, _) in oc.items()
                    if st == "finished"]
        routed = sum(self.routed)
        return {
            "replicas": self.n,
            "live": sum(self.alive),
            "policy": self.policy,
            "requests": len(finished),
            "generated_tokens": sum(
                len(streams.get(rid, ())) for rid in finished),
            "iterations": self.iteration,
            "routed": routed,
            "routed_per_replica": list(self.routed),
            "affinity_hits": self.affinity_hits,
            "affinity_misses": self.affinity_misses,
            "affinity_hit_rate": (self.affinity_hits / routed
                                  if routed else None),
            "spills": self.spills,
            "migrations": self.migrations,
            "rolling_swaps": self.rolling_swaps,
            "lost": len(self.lost_requests()),
            "outcomes": oc,
        }

    def metrics_snapshot(self) -> Dict[str, Any]:
        return self.registry.snapshot()

    def render_prometheus(self) -> str:
        """One fleet scrape: every replica's engine registry merged
        under a ``replica=`` label, then the fleet-level router block.
        Metric names never collide across the two blocks (engine
        metrics are ``paddle_tpu_serve_*``, fleet ``paddle_tpu_fleet_*``)."""
        merged = MetricsRegistry.merge(
            [(str(i), self.engines[i].registry) for i in range(self.n)],
            label="replica")
        return merged + self.registry.render_prometheus()
